"""Decode-side transformer for the serving runtime.

The serving engine does not re-run the training program descriptor per
token — generation wants one *fixed-shape* decode step (one token per
active batch slot, cache reads/writes through block tables) that XLA
compiles exactly once. This module holds that step and the bridge from
the training world into it:

  * ``GenerationConfig`` — the decoder-only architecture hyperparameters
    (the shape of ``models/transformer_fluid.build``: pre-LN blocks,
    fused QKV, gelu FFN, sinusoidal position encoding, untied LM head).
  * ``extract_decoder_weights(program, scope)`` — walks a Fluid program
    built by ``transformer_fluid.build`` (remat=False, dropout=0) and
    lifts its parameters out of the scope into the serving weight
    layout. This is what ``inference.export_generation_model`` calls.
  * ``GenerationModel`` — config + weights; ``make_decode_step`` builds
    the jitted continuous-batching decode step over a ``KVBlockPool``.
  * ``reference_decode`` — an unbatched, unpaged greedy decoder over a
    contiguous cache; the correctness oracle the tests pin the paged
    batched step against token-for-token.

The decode step's calling convention (all shapes fixed per engine):

    step(weights, kv_k, kv_v, prompt_feed, use_prompt, prev_tokens,
         positions, block_tables, active)
      -> (kv_k', kv_v', next_tokens)

``prev_tokens`` is the *device* token vector the previous step returned:
decode-phase slots chain their input token on device (the host never
has to materialize a step before dispatching the next — the PR-2
async-window contract), while prefill-phase slots override it with
``prompt_feed`` under ``use_prompt``. Inactive slots route their cache
writes to the pool's null block and their outputs are ignored.

``make_prefill_step`` is the second, chunked step shape (Sarathi-style
mixed batches, docs/SERVING.md): every row carries a ``[chunk]`` token
window — prefill rows consume up to ``chunk`` prompt tokens per call
(writing that many KV slots, masked per row by ``lengths``), decode
rows ride the same step as 1-token windows chaining ``prev_tokens`` on
device. Each engine geometry compiles exactly TWO step shapes: this one
and the one-token decode step.

``make_spec_step`` is the speculative-decoding **verify window**
(docs/SERVING.md): the same ``[max_batch, window]`` chunk shape, except
the target's greedy token comes back at EVERY window slot, so feeding
``[t0, d1..dk]`` (a row's last committed token plus ``k`` drafted
continuations) verifies all ``k`` drafts in one step. The matching
draft sources live here too: :class:`NGramDrafter` (prompt-lookup
drafting over the sequence's own prompt+output history — zero extra
weights) and :class:`ModelDrafter` (the pluggable draft-model hook
reusing :class:`GenerationModel`).
"""

import math
import time

import numpy as np

__all__ = ["GenerationConfig", "GenerationModel", "ModelDrafter",
           "NGramDrafter", "extract_decoder_weights", "random_weights",
           "reference_decode", "save_generation_artifact",
           "load_generation_artifact"]

# serving-artifact file names (written by
# inference.export_generation_model next to the one-shot
# __serving__/__serving_native__ artifacts so native_serve and the
# continuous-batching engine deploy from ONE directory)
GENERATION_WEIGHTS = "__generation__.npz"
GENERATION_META = "__generation_meta__.json"


def _kernel_key_suffix():
    """Step-cache key component for the Pallas kernel dispatch policy
    (ops/kernel_registry): a step traced under one PTPU_KERNELS mode
    must not serve another. Empty in the default (auto) state so
    pre-kernel cache keys stay bitwise identical."""
    from ..ops.kernel_registry import cache_key

    key = cache_key()
    return () if key == "auto" else ("kernels:" + key,)


class GenerationConfig:
    """Decoder-only LM hyperparameters (transformer_fluid.build shape)."""

    def __init__(self, vocab_size, d_model, n_heads, n_layers, d_ff,
                 max_seq_len=512, pe_alpha=1.0, pe_beta=1.0):
        if d_model % n_heads:
            raise ValueError("n_heads must divide d_model")
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.d_ff = int(d_ff)
        self.max_seq_len = int(max_seq_len)
        self.pe_alpha = float(pe_alpha)
        self.pe_beta = float(pe_beta)

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    def to_dict(self):
        return {k: getattr(self, k) for k in
                ("vocab_size", "d_model", "n_heads", "n_layers", "d_ff",
                 "max_seq_len", "pe_alpha", "pe_beta")}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


# weight-name layout (one flat dict; per-layer names carry an l<i>/
# prefix). Everything is fp32 on the serving side.
_LAYER_KEYS = ("ln1_scale", "ln1_bias", "wqkv", "bqkv", "wproj", "bproj",
               "ln2_scale", "ln2_bias", "wff1", "bff1", "wff2", "bff2")


def weight_names(config):
    names = ["embedding", "lm_head", "final_ln_scale", "final_ln_bias"]
    for i in range(config.n_layers):
        names.extend("l%d/%s" % (i, k) for k in _LAYER_KEYS)
    return names


def _position_encoding_table(config):
    """The exact ``add_position_encoding`` kernel table
    (ops/nn_ops.py): pe[t] = [sin(t/10000^(2i/d)) | cos(...)]."""
    d = config.d_model
    pos = np.arange(config.max_seq_len)[:, None]
    i = np.arange(d // 2)[None, :]
    angle = pos / np.power(10000.0, 2 * i / d)
    return np.concatenate([np.sin(angle), np.cos(angle)],
                          axis=1).astype(np.float32)


def random_weights(config, seed=0, scale=0.1):
    """Deterministic random weights (tests/bench: a servable model with
    no training program behind it)."""
    rng = np.random.RandomState(seed)
    D, F, V = config.d_model, config.d_ff, config.vocab_size

    def w(*shape):
        return (rng.randn(*shape) * scale).astype(np.float32)

    weights = {
        "embedding": w(V, D),
        "lm_head": w(D, V),
        "final_ln_scale": np.ones(D, np.float32),
        "final_ln_bias": np.zeros(D, np.float32),
    }
    for i in range(config.n_layers):
        p = "l%d/" % i
        weights[p + "ln1_scale"] = np.ones(D, np.float32)
        weights[p + "ln1_bias"] = np.zeros(D, np.float32)
        weights[p + "wqkv"] = w(D, 3 * D)
        weights[p + "bqkv"] = np.zeros(3 * D, np.float32)
        weights[p + "wproj"] = w(D, D)
        weights[p + "bproj"] = np.zeros(D, np.float32)
        weights[p + "ln2_scale"] = np.ones(D, np.float32)
        weights[p + "ln2_bias"] = np.zeros(D, np.float32)
        weights[p + "wff1"] = w(D, F)
        weights[p + "bff1"] = np.zeros(F, np.float32)
        weights[p + "wff2"] = w(F, D)
        weights[p + "bff2"] = np.zeros(D, np.float32)
    return weights


# ---------------------------------------------------------------------------
# extraction from a transformer_fluid.build program
# ---------------------------------------------------------------------------


def extract_decoder_weights(program, scope, max_seq_len=None):
    """Lift the decoder weights out of a program built by
    ``models.transformer_fluid.build(remat=False, dropout_rate=0)`` (the
    bench/CI flagship configuration) into the serving layout.

    The walker is positional over op *types*, so it is insensitive to the
    interleaved elementwise/reshape plumbing: embeddings come from the
    ``lookup_table`` op, per-layer weights from the in-order sequence of
    ``layer_norm`` / ``fused_multihead_attention`` / parameter ``mul``
    ops, and the LM head from the (chunk-shared) ``lm_head_w`` matmuls.
    Returns ``(GenerationConfig, weights_dict)`` with everything cast to
    fp32.
    """
    block = program.global_block()

    def _is_param(name):
        v = block._find_var_recursive(name)
        return v is not None and getattr(v, "persistable", False)

    def _val(name):
        val = scope.get(name)
        if val is None:
            raise RuntimeError(
                "parameter %r has no value — run the startup program "
                "before exporting" % name)
        return np.asarray(val, np.float32)

    emb = None
    pe_alpha = pe_beta = 1.0
    lns, atts, muls = [], [], []
    pending_mul = None
    for op in block.ops:
        if op.type == "lookup_table" and emb is None:
            emb = op.inputs["W"][0].name
        elif op.type == "add_position_encoding":
            pe_alpha = op.attrs.get("alpha", 1.0)
            pe_beta = op.attrs.get("beta", 1.0)
        elif op.type == "layer_norm":
            lns.append((op.inputs["Scale"][0].name,
                        op.inputs["Bias"][0].name))
        elif op.type == "fused_multihead_attention":
            atts.append({k: v[0].name for k, v in op.inputs.items()
                         if k != "X"})
        elif op.type == "mul" and _is_param(op.inputs["Y"][0].name):
            pending_mul = [op.inputs["Y"][0].name, None]
            muls.append(pending_mul)
        elif (op.type == "elementwise_add" and pending_mul is not None
              and _is_param(op.inputs["Y"][0].name)):
            pending_mul[1] = op.inputs["Y"][0].name
            pending_mul = None
        elif op.type == "recompute":
            raise NotImplementedError(
                "export_generation_model walks the flat op list — build "
                "the program with transformer_fluid.build(remat=False)")

    if emb is None or not atts:
        raise ValueError(
            "program does not look like transformer_fluid.build output "
            "(no embedding / fused_multihead_attention ops found)")
    L = len(atts)
    if len(lns) != 2 * L + 1:
        raise ValueError(
            "expected %d layer_norm ops for %d layers, found %d — only "
            "the remat=False, dropout_rate=0 build is exportable"
            % (2 * L + 1, L, len(lns)))
    ffn_muls = muls[:2 * L]
    head_muls = muls[2 * L:]
    head_params = {m[0] for m in head_muls}
    if len(ffn_muls) != 2 * L or len(head_params) != 1:
        raise ValueError(
            "expected 2 FFN matmuls per layer plus one shared LM-head "
            "parameter; found %d muls over params %r"
            % (len(muls), sorted({m[0] for m in muls})))

    emb_w = _val(emb)
    V, D = emb_w.shape
    wq0 = _val(atts[0]["WQ"])
    H = wq0.shape[1]
    F = _val(ffn_muls[0][0]).shape[1]
    config = GenerationConfig(
        vocab_size=V, d_model=D, n_heads=H, n_layers=L, d_ff=F,
        max_seq_len=max_seq_len or 512, pe_alpha=pe_alpha,
        pe_beta=pe_beta)

    weights = {"embedding": emb_w,
               "lm_head": _val(next(iter(head_params))),
               "final_ln_scale": _val(lns[2 * L][0]),
               "final_ln_bias": _val(lns[2 * L][1])}
    if weights["lm_head"].shape != (D, V):
        raise ValueError("LM head shape %r != (d_model, vocab)"
                         % (weights["lm_head"].shape,))
    for i in range(L):
        p = "l%d/" % i
        att = atts[i]
        # [D, H, Dh] per-head projections -> fused [D, 3D] qkv matmul
        wq, wk, wv = (_val(att[k]).reshape(D, D)
                      for k in ("WQ", "WK", "WV"))
        weights[p + "wqkv"] = np.concatenate([wq, wk, wv], axis=1)
        bq, bk, bv = (_val(att[k]).reshape(D) if k in att
                      else np.zeros(D, np.float32)
                      for k in ("BQ", "BK", "BV"))
        weights[p + "bqkv"] = np.concatenate([bq, bk, bv])
        weights[p + "wproj"] = _val(att["WO"]).reshape(D, D)
        weights[p + "bproj"] = (_val(att["BO"]) if "BO" in att
                                else np.zeros(D, np.float32))
        weights[p + "ln1_scale"] = _val(lns[2 * i][0])
        weights[p + "ln1_bias"] = _val(lns[2 * i][1])
        weights[p + "ln2_scale"] = _val(lns[2 * i + 1][0])
        weights[p + "ln2_bias"] = _val(lns[2 * i + 1][1])
        for j, nm in ((0, "ff1"), (1, "ff2")):
            wname, bname = ffn_muls[2 * i + j]
            weights[p + "w" + nm] = _val(wname)
            weights[p + "b" + nm] = (
                _val(bname) if bname is not None
                else np.zeros(weights[p + "w" + nm].shape[1], np.float32))
    return config, weights


# ---------------------------------------------------------------------------
# serving artifact (weights npz + meta json)
# ---------------------------------------------------------------------------


def save_generation_artifact(dirname, config, weights):
    """Write the generation-serving artifact: one STORED npz of fp32
    weights plus a json config. Returns the npz path."""
    import json
    import os

    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, GENERATION_WEIGHTS)
    np.savez(path, **{k: np.asarray(v, np.float32)
                      for k, v in weights.items()})
    with open(os.path.join(dirname, GENERATION_META), "w") as f:
        json.dump(config.to_dict(), f, indent=2, sort_keys=True)
    return path


def load_generation_artifact(dirname, name=None, quantize=None):
    """Load an exported generation artifact as a ready-to-serve
    :class:`GenerationModel`. ``quantize='weight_only'`` serves the SAME
    artifact with the int8 weight store (``GenerationModel.quantized``)
    — no re-export needed."""
    import json
    import os

    meta_path = os.path.join(dirname, GENERATION_META)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            "%s has no %s — export with "
            "paddle_tpu.inference.export_generation_model"
            % (dirname, GENERATION_META))
    with open(meta_path) as f:
        config = GenerationConfig.from_dict(json.load(f))
    with np.load(os.path.join(dirname, GENERATION_WEIGHTS)) as z:
        weights = {k: z[k] for k in z.files}
    model = GenerationModel(config, weights,
                            name=name or os.path.basename(dirname))
    if quantize:
        if quantize not in (True, "weight_only", "int8"):
            raise ValueError(
                "quantize=%r — the serving runtime supports the "
                "weight_only int8 store (docs/QUANTIZATION.md)"
                % (quantize,))
        model = model.quantized()
    return model


# ---------------------------------------------------------------------------
# the fixed-shape decode step
# ---------------------------------------------------------------------------


class GenerationModel:
    """Config + weights + the jitted continuous-batching decode step.

    ``quantized()`` derives the weight-only-int8 variant
    (docs/QUANTIZATION.md): every 2-D matmul weight (embedding, qkv,
    proj, ffn, lm head) is STORED int8 with a per-output-channel fp32
    scale riding in the same weights dict under ``<name>@qscale``, and
    the decode step dequantizes on use — the compute stays fp32, the
    HBM-resident weight store (what a memory-bandwidth-bound decode
    step actually streams) shrinks ~4x. Decoding a quantized model is
    token-identical to ``reference_decode`` over
    ``dequantized_weights()`` (its fp32 reference)."""

    def __init__(self, config, weights, name="model"):
        self.config = config
        self.name = name
        missing = [n for n in weight_names(config) if n not in weights]
        if missing:
            raise ValueError("missing weights: %s" % missing[:4])
        import jax.numpy as jnp

        # int8 entries (the weight-only-quantized store) keep their
        # dtype; everything else normalizes to fp32 as before
        self.weights = {
            k: jnp.asarray(v if np.asarray(v).dtype == np.int8
                           else np.asarray(v, np.float32))
            for k, v in weights.items()}
        self.weight_only_int8 = any(
            str(v.dtype) == "int8" for v in self.weights.values())
        # python-trace counter: the body below only executes while jax
        # traces, so tests can pin "no retrace across join/retire"
        self.trace_count = 0
        self._steps = {}

    @classmethod
    def random(cls, config, seed=0, name="model"):
        return cls(config, random_weights(config, seed), name=name)

    # -- weight-only int8 ---------------------------------------------------
    def quantized(self, name=None):
        """The weight-only-int8 variant of this model: 2-D matmul
        weights become int8 + ``@qscale`` per-output-channel scales;
        biases, layer norms and the model structure are untouched.
        Records quant/{weights_quantized,weight_bytes_saved,
        weight_fp32_bytes} telemetry."""
        from ..quant import quantize_symmetric, record_weight_store

        if self.weight_only_int8:
            return self
        qw = {}
        n_q = saved = fp32 = 0
        for k, v in self.weights.items():
            w = np.asarray(v)
            if w.ndim == 2 and w.dtype == np.float32:
                # the shared symmetric int8 grid (paddle_tpu.quant),
                # per output column (axis 1 of the [in, out] layout;
                # per d_model column for the [V, D] embedding)
                q, s = quantize_symmetric(w, channel_axis=1)
                qw[k] = q
                qw[k + "@qscale"] = (s / 127.0).astype(np.float32)
                n_q += 1
                saved += max(w.nbytes - q.nbytes - s.nbytes, 0)
                fp32 += w.nbytes
            else:
                qw[k] = w
        record_weight_store(n_q, saved, fp32)
        return GenerationModel(self.config, qw,
                               name=name or self.name + ".int8")

    def dequantized_weights(self):
        """fp32 weights dict with the int8 store multiplied back out —
        the quantized model's numerics reference (a GenerationModel
        built from these decodes token-identically to this one)."""
        out = {}
        for k, v in self.weights.items():
            if k.endswith("@qscale"):
                continue
            w = np.asarray(v)
            s = self.weights.get(k + "@qscale")
            out[k] = (w.astype(np.float32) * np.asarray(s)
                      if s is not None else w)
        return out

    def _w(self, jnp, weights, key):
        """One weight in compute dtype: dequantize-on-use for the int8
        store (XLA fuses the convert+scale into the consuming dot)."""
        s = weights.get(key + "@qscale")
        w = weights[key]
        return w.astype(jnp.float32) * s if s is not None else w

    def _forward_token(self, jnp, weights, x, positions, block_tables,
                       active, kv_k, kv_v):
        """One token through all layers. x: [B, D]; returns
        (kv_k, kv_v, logits[B, V])."""
        import jax

        cfg = self.config
        B = x.shape[0]
        H, Dh = cfg.n_heads, cfg.head_dim
        bs = kv_k.shape[2]
        max_ctx = block_tables.shape[1] * bs
        sm_scale = Dh ** -0.5

        blk_idx = positions // bs
        slot_idx = positions % bs
        # inactive slots scatter into the null block (never read back)
        write_blk = jnp.where(
            active,
            jnp.take_along_axis(block_tables, blk_idx[:, None],
                                axis=1)[:, 0],
            0)

        # one dispatch decision per forward (trace time), shared by all
        # layers: the paged flash-decode kernel reads the pool pages
        # through the block table in-kernel, so the contiguous
        # kv[block_tables] gather below never materializes
        from ..ops.kernel_registry import choose as _choose_kernel

        use_paged = _choose_kernel("paged_decode", head_dim=Dh,
                                   block_size=bs)
        if use_paged:
            from ..ops.pallas_kernels import paged_attention

        def ln(h, scale, bias):
            mu = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.mean((h - mu) ** 2, axis=-1, keepdims=True)
            return (h - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias

        # context-position validity: t <= position (the current token's
        # k/v are written before the gather, so self-attention sees them)
        t_ids = jnp.arange(max_ctx)[None, :]
        valid = t_ids <= positions[:, None]

        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, weights[p + "ln1_scale"], weights[p + "ln1_bias"])
            qkv = a @ self._w(jnp, weights, p + "wqkv") \
                + weights[p + "bqkv"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, H, Dh)
            k_new = k_new.reshape(B, H, Dh)
            v_new = v_new.reshape(B, H, Dh)
            kv_k = kv_k.at[i, write_blk, slot_idx].set(k_new)
            kv_v = kv_v.at[i, write_blk, slot_idx].set(v_new)
            if use_paged:
                ctx = paged_attention(
                    kv_k[i], kv_v[i], q[:, None], block_tables,
                    positions[:, None], sm_scale=sm_scale)
                ctx = ctx[:, 0].reshape(B, -1)
            else:
                # paged gather: [B, Mb, bs, H, Dh] -> [B, max_ctx, H, Dh]
                k_ctx = kv_k[i][block_tables].reshape(B, max_ctx, H, Dh)
                v_ctx = kv_v[i][block_tables].reshape(B, max_ctx, H, Dh)
                scores = jnp.einsum("bhd,bthd->bht", q, k_ctx) * sm_scale
                scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
                w = jnp.exp(scores
                            - jnp.max(scores, axis=-1, keepdims=True))
                w = w / jnp.sum(w, axis=-1, keepdims=True)
                ctx = jnp.einsum("bht,bthd->bhd", w, v_ctx) \
                    .reshape(B, -1)
            x = x + ctx @ self._w(jnp, weights, p + "wproj") \
                + weights[p + "bproj"]
            b2 = ln(x, weights[p + "ln2_scale"], weights[p + "ln2_bias"])
            f = jax.nn.gelu(b2 @ self._w(jnp, weights, p + "wff1")
                            + weights[p + "bff1"], approximate=False)
            x = x + f @ self._w(jnp, weights, p + "wff2") \
                + weights[p + "bff2"]

        x = ln(x, weights["final_ln_scale"], weights["final_ln_bias"])
        return kv_k, kv_v, x @ self._w(jnp, weights, "lm_head")

    def make_decode_step(self, max_batch, max_blocks_per_seq,
                         return_logits=False):
        """Build (and cache) the jitted fixed-shape decode step for this
        engine geometry. The KV arrays are donated — updates alias
        in-place in device memory."""
        key = (int(max_batch), int(max_blocks_per_seq),
               bool(return_logits)) + _kernel_key_suffix()
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        cfg = self.config
        pe = jnp.asarray(_position_encoding_table(cfg))
        emb_scale = float(cfg.d_model) ** 0.5

        def step(weights, kv_k, kv_v, prompt_feed, use_prompt,
                 prev_tokens, positions, block_tables, active):
            self.trace_count += 1
            tok = jnp.where(use_prompt, prompt_feed, prev_tokens)
            tok = jnp.clip(tok, 0, cfg.vocab_size - 1)
            # int8 embedding store: gather the int8 rows FIRST, then
            # dequantize the [B, D] slice — the full fp32 table is never
            # materialized
            emb = jnp.take(weights["embedding"], tok, axis=0)
            es = weights.get("embedding@qscale")
            if es is not None:
                emb = emb.astype(jnp.float32) * es
            x = (emb * emb_scale * cfg.pe_alpha
                 + cfg.pe_beta * jnp.take(pe, positions, axis=0))
            kv_k, kv_v, logits = self._forward_token(
                jnp, weights, x, positions, block_tables, active,
                kv_k, kv_v)
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if return_logits:
                return kv_k, kv_v, next_tokens, logits
            return kv_k, kv_v, next_tokens

        jitted = self._instrument_step("decode", jax.jit(
            step, donate_argnums=(1, 2)))
        self._steps[key] = jitted
        return jitted

    def _instrument_step(self, kind, jitted):
        """With metrics enabled, wrap a jitted step so its first call
        compiles ahead of time (the executor's `_compile_instrumented`
        pattern) and the executable's XLA cost analysis lands in the
        exec/* gauges — serving cache misses get the same FLOPs/bytes
        receipts training steps do. Identity when metrics are off: the
        raw jitted function is returned and cached, zero wrapper frames
        on the default hot path."""
        from ..observability import metrics as _metrics

        if not _metrics.enabled():
            return jitted

        from ..observability import cost as _cost
        from ..observability import tracing as _tracing

        aot = []

        def step(*args):
            if not aot:
                with _tracing.span("serving_compile", kind=kind):
                    t0 = time.perf_counter()
                    compiled = jitted.lower(*args).compile()
                    _metrics.histogram(
                        "serving/step_compile_time").observe(
                        time.perf_counter() - t0)
                _cost.publish(compiled)
                aot.append(compiled)
            return aot[0](*args)

        return step

    def _forward_chunk(self, jnp, weights, x, pos2d, lengths,
                       block_tables, active, kv_k, kv_v,
                       all_slots=False):
        """A ``[B, C]`` token window through all layers. x: [B, C, D];
        returns (kv_k, kv_v, logits[B, V]) — each row's logits at its
        LAST valid window slot (``lengths - 1``) — or, with
        ``all_slots=True`` (the speculative verify window), the logits
        at EVERY window slot: (kv_k, kv_v, logits[B, C, V])."""
        import jax

        cfg = self.config
        B, C = x.shape[0], x.shape[1]
        H, Dh = cfg.n_heads, cfg.head_dim
        bs = kv_k.shape[2]
        Mb = block_tables.shape[1]
        max_ctx = Mb * bs
        sm_scale = Dh ** -0.5

        # per-slot write targets: window slot j of row b lands at
        # position pos2d[b, j]; slots past the row's valid length (and
        # whole inactive rows) scatter into the null block instead
        valid = ((jnp.arange(C, dtype=jnp.int32)[None, :]
                  < lengths[:, None]) & active[:, None])
        blk_idx = jnp.clip(pos2d // bs, 0, Mb - 1)
        write_blk = jnp.where(
            valid, jnp.take_along_axis(block_tables, blk_idx, axis=1), 0)
        slot_idx = pos2d % bs

        def ln(h, scale, bias):
            mu = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.mean((h - mu) ** 2, axis=-1, keepdims=True)
            return (h - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias

        # context validity per window slot: t <= that slot's position.
        # The whole window's k/v are written BEFORE the gather, so
        # in-chunk self-attention sees exactly the causal prefix; t=0 is
        # always visible, so no softmax row is fully masked.
        t_ids = jnp.arange(max_ctx)[None, None, :]
        attn_valid = t_ids <= pos2d[:, :, None]          # [B, C, T]

        # the speculative verify window (all_slots) dispatches the
        # fused spec_window kernel — k+1 query positions against the
        # paged cache in one launch, block table resolved in-kernel;
        # one decision per forward, shared by all layers
        from ..ops.kernel_registry import choose as _choose_kernel

        use_paged = all_slots and _choose_kernel(
            "spec_window", head_dim=Dh, block_size=bs, window=C)
        if use_paged:
            from ..ops.pallas_kernels import paged_attention

        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, weights[p + "ln1_scale"], weights[p + "ln1_bias"])
            qkv = a @ self._w(jnp, weights, p + "wqkv") \
                + weights[p + "bqkv"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, C, H, Dh)
            k_new = k_new.reshape(B, C, H, Dh)
            v_new = v_new.reshape(B, C, H, Dh)
            kv_k = kv_k.at[i, write_blk, slot_idx].set(k_new)
            kv_v = kv_v.at[i, write_blk, slot_idx].set(v_new)
            if use_paged:
                ctx = paged_attention(
                    kv_k[i], kv_v[i], q, block_tables, pos2d,
                    sm_scale=sm_scale).reshape(B, C, -1)
            else:
                # paged gather: [B, Mb, bs, H, Dh] -> [B, max_ctx, H, Dh]
                k_ctx = kv_k[i][block_tables].reshape(B, max_ctx, H, Dh)
                v_ctx = kv_v[i][block_tables].reshape(B, max_ctx, H, Dh)
                scores = jnp.einsum("bchd,bthd->bcht", q, k_ctx) \
                    * sm_scale
                scores = jnp.where(attn_valid[:, :, None, :], scores,
                                   -jnp.inf)
                w = jnp.exp(scores
                            - jnp.max(scores, axis=-1, keepdims=True))
                w = w / jnp.sum(w, axis=-1, keepdims=True)
                ctx = jnp.einsum("bcht,bthd->bchd", w, v_ctx) \
                    .reshape(B, C, -1)
            x = x + ctx @ self._w(jnp, weights, p + "wproj") \
                + weights[p + "bproj"]
            b2 = ln(x, weights[p + "ln2_scale"], weights[p + "ln2_bias"])
            f = jax.nn.gelu(b2 @ self._w(jnp, weights, p + "wff1")
                            + weights[p + "bff1"], approximate=False)
            x = x + f @ self._w(jnp, weights, p + "wff2") \
                + weights[p + "bff2"]

        if all_slots:
            x = ln(x, weights["final_ln_scale"], weights["final_ln_bias"])
            return kv_k, kv_v, x @ self._w(jnp, weights, "lm_head")
        last = jnp.clip(lengths - 1, 0, C - 1).astype(jnp.int32)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        x_last = ln(x_last, weights["final_ln_scale"],
                    weights["final_ln_bias"])
        return kv_k, kv_v, x_last @ self._w(jnp, weights, "lm_head")

    def make_prefill_step(self, max_batch, max_blocks_per_seq, chunk,
                          return_logits=False):
        """Build (and cache) the jitted fixed-shape CHUNKED step for
        this engine geometry — the mixed prefill/decode shape
        (docs/SERVING.md). Calling convention:

            step(weights, kv_k, kv_v, chunk_tokens[B, C], use_prompt[B],
                 prev_tokens[B], positions[B], lengths[B],
                 block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', next_tokens[B])

        ``positions[b]`` is row b's FIRST window position; window slot
        ``j`` processes position ``positions[b] + j``. Prefill rows
        (``use_prompt``) take all ``lengths[b]`` tokens from
        ``chunk_tokens``; decode rows are 1-token windows whose first
        slot chains ``prev_tokens`` on device. ``next_tokens[b]`` is
        the greedy token at the row's last valid slot — meaningful when
        the window consumed the final prompt token (the first generated
        token) or for decode rows. The KV arrays are donated."""
        return self._make_window_step("chunk", max_batch,
                                      max_blocks_per_seq, chunk,
                                      all_slots=False,
                                      return_logits=return_logits)

    def _make_window_step(self, kind, max_batch, max_blocks_per_seq,
                          window, all_slots, return_logits):
        """The shared ``[max_batch, window]`` jitted step builder behind
        :meth:`make_prefill_step` (``all_slots=False`` — logits at each
        row's last valid slot) and :meth:`make_spec_step`
        (``all_slots=True`` — the verify window, argmax at every slot).
        One body, so the token-splice/embedding/position plumbing can
        never diverge between the two shapes."""
        key = (kind, int(max_batch), int(max_blocks_per_seq),
               int(window), bool(return_logits)) + _kernel_key_suffix()
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        cfg = self.config
        pe = jnp.asarray(_position_encoding_table(cfg))
        emb_scale = float(cfg.d_model) ** 0.5
        C = int(window)

        def step(weights, kv_k, kv_v, window_tokens, use_prompt,
                 prev_tokens, positions, lengths, block_tables, active):
            self.trace_count += 1
            tok0 = jnp.where(use_prompt, window_tokens[:, 0],
                             prev_tokens)
            tok = jnp.concatenate([tok0[:, None], window_tokens[:, 1:]],
                                  axis=1)
            tok = jnp.clip(tok, 0, cfg.vocab_size - 1)
            pos2d = (positions[:, None]
                     + jnp.arange(C, dtype=jnp.int32)[None, :])
            emb = jnp.take(weights["embedding"], tok, axis=0)
            es = weights.get("embedding@qscale")
            if es is not None:
                emb = emb.astype(jnp.float32) * es
            pe_idx = jnp.clip(pos2d, 0, cfg.max_seq_len - 1)
            x = (emb * emb_scale * cfg.pe_alpha
                 + cfg.pe_beta * jnp.take(pe, pe_idx, axis=0))
            kv_k, kv_v, logits = self._forward_chunk(
                jnp, weights, x, pos2d, lengths, block_tables, active,
                kv_k, kv_v, all_slots=all_slots)
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if return_logits:
                return kv_k, kv_v, next_tokens, logits
            return kv_k, kv_v, next_tokens

        jitted = self._instrument_step(kind, jax.jit(
            step, donate_argnums=(1, 2)))
        self._steps[key] = jitted
        return jitted

    def make_spec_step(self, max_batch, max_blocks_per_seq, window,
                       return_logits=False):
        """Build (and cache) the jitted speculative **verify window**
        for this engine geometry (docs/SERVING.md): the
        ``[max_batch, window]`` chunk shape of :meth:`make_prefill_step`
        except that the target's greedy token is returned at EVERY
        window slot instead of only the last one:

            step(weights, kv_k, kv_v, window_tokens[B, W],
                 use_prompt[B], prev_tokens[B], positions[B],
                 lengths[B], block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', next_tokens[B, W])

        ``next_tokens[b, j]`` is the argmax AFTER window slot ``j`` —
        the token the target would emit at position
        ``positions[b] + j + 1``. A row feeding ``[t0, d1..dk]`` (its
        last committed token plus ``k`` draft tokens) therefore
        verifies every draft in one step: acceptance is the longest
        prefix with ``d[j+1] == next_tokens[b, j]``, and
        ``next_tokens[b, m]`` after the last accepted draft is the
        correction token — computed over an all-verified context, so
        every window emits at least one sequential-greedy-identical
        token. Slots at or past ``lengths[b]`` write to the null block
        and their outputs are meaningless. The KV arrays are donated."""
        return self._make_window_step("spec", max_batch,
                                      max_blocks_per_seq, window,
                                      all_slots=True,
                                      return_logits=return_logits)


# ---------------------------------------------------------------------------
# draft sources for speculative decoding (docs/SERVING.md)
# ---------------------------------------------------------------------------


class NGramDrafter:
    """Prompt-lookup / n-gram drafting (zero extra weights): match the
    sequence's most recent suffix n-gram against earlier occurrences in
    its OWN prompt+output history and propose the tokens that followed
    the most recent earlier match. Strongest exactly where the radix
    prefix cache already wins — templated, repetitive and structured
    generation (code, JSON, quoting the prompt back) — and free
    everywhere else: a miss proposes nothing and the verify window
    degrades to a plain one-token decode step.

    ``propose(history, k)`` tries match lengths from ``max_ngram`` down
    to ``min_ngram`` and returns up to ``k`` continuation tokens (empty
    when no n-gram recurs)."""

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        if self.min_ngram < 1:
            raise ValueError("min_ngram must be >= 1")
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram must be >= min_ngram")

    def propose(self, history, k):
        k = int(k)
        if k < 1 or len(history) < self.min_ngram + 1:
            return []
        hist = [int(t) for t in history]
        L = len(hist)
        for n in range(min(self.max_ngram, L - 1),
                       self.min_ngram - 1, -1):
            suffix = hist[L - n:]
            # the most recent earlier occurrence able to supply a FULL
            # k-token continuation wins (recency beats frequency for
            # local repetition, but a match right at the history's end
            # can only offer a truncated draft — on a period-p
            # repetition the nearest match yields only p tokens, so
            # scan on for an earlier full-window one); the match must
            # end before the suffix starts so the continuation is real
            best = None
            for j in range(L - n - 1, -1, -1):
                if hist[j:j + n] != suffix:
                    continue
                avail = min(k, L - (j + n))
                if best is None or avail > best[1]:
                    best = (j, avail)
                if avail >= k:
                    break
            if best is not None:
                start = best[0] + n
                return hist[start:start + k]
        return []


class ModelDrafter:
    """The pluggable draft-model hook: greedy-decode up to ``k``
    continuation tokens from a (smaller) :class:`GenerationModel` over
    the sequence's committed history. This reference implementation
    runs the unbatched ``reference_decode`` oracle — exact but
    host-side, i.e. a correctness/integration hook for wiring a real
    jitted small-model drafter, not a production fast path. Drafting
    with the TARGET model itself yields perfect acceptance (every
    window emits its full length), which is what the tests pin."""

    def __init__(self, model):
        if not isinstance(model, GenerationModel):
            raise TypeError("ModelDrafter needs a GenerationModel, got "
                            "%r" % (type(model).__name__,))
        self.model = model

    def propose(self, history, k):
        k = int(k)
        hist = [int(t) for t in history]
        if k < 1 or not hist:
            return []
        if len(hist) >= self.model.config.max_seq_len:
            return []
        return reference_decode(self.model, hist, k)


# ---------------------------------------------------------------------------
# unbatched, unpaged reference decoder (the correctness oracle)
# ---------------------------------------------------------------------------


def reference_decode(model, prompt, max_new_tokens, eos_id=None):
    """Greedy-decode ONE sequence with a plain contiguous KV cache and
    full attention — no blocks, no batching, no masking tricks. The
    batched paged decode must match this token-for-token. A weight-only
    quantized model decodes over its dequantized fp32 weights (the same
    values the int8 step computes with)."""
    import jax.numpy as jnp

    cfg = model.config
    w = model.dequantized_weights() if model.weight_only_int8 \
        else model.weights
    pe = _position_encoding_table(cfg)
    emb_scale = float(cfg.d_model) ** 0.5
    H, Dh = cfg.n_heads, cfg.head_dim
    sm_scale = Dh ** -0.5

    def ln(h, scale, bias):
        mu = np.mean(h, keepdims=True)
        var = np.mean((h - mu) ** 2, keepdims=True)
        return (h - mu) / np.sqrt(var + 1e-5) * np.asarray(scale) \
            + np.asarray(bias)

    ks = [[] for _ in range(cfg.n_layers)]
    vs = [[] for _ in range(cfg.n_layers)]
    tokens = list(prompt)
    generated = []

    def one(tok, pos):
        x = (np.asarray(w["embedding"])[tok] * emb_scale * cfg.pe_alpha
             + cfg.pe_beta * pe[pos])
        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, w[p + "ln1_scale"], w[p + "ln1_bias"])
            qkv = a @ np.asarray(w[p + "wqkv"]) + np.asarray(
                w[p + "bqkv"])
            q, k_new, v_new = np.split(qkv, 3)
            ks[i].append(k_new.reshape(H, Dh))
            vs[i].append(v_new.reshape(H, Dh))
            k_ctx = np.stack(ks[i])            # [T, H, Dh]
            v_ctx = np.stack(vs[i])
            qh = q.reshape(H, Dh)
            scores = np.einsum("hd,thd->ht", qh, k_ctx) * sm_scale
            scores = scores - scores.max(axis=-1, keepdims=True)
            wgt = np.exp(scores)
            wgt = wgt / wgt.sum(axis=-1, keepdims=True)
            ctx = np.einsum("ht,thd->hd", wgt, v_ctx).reshape(-1)
            x = x + ctx @ np.asarray(w[p + "wproj"]) + np.asarray(
                w[p + "bproj"])
            b2 = ln(x, w[p + "ln2_scale"], w[p + "ln2_bias"])
            h = b2 @ np.asarray(w[p + "wff1"]) + np.asarray(w[p + "bff1"])
            # exact (erf) gelu, matching jax.nn.gelu(approximate=False)
            h = h * 0.5 * (1.0 + np.vectorize(math.erf)(
                h / np.sqrt(2.0)))
            x = x + h @ np.asarray(w[p + "wff2"]) + np.asarray(
                w[p + "bff2"])
        x = ln(x, w["final_ln_scale"], w["final_ln_bias"])
        logits = x @ np.asarray(w["lm_head"])
        return int(np.argmax(logits))

    nxt = None
    for pos, tok in enumerate(tokens):
        nxt = one(tok, pos)
    pos = len(tokens)
    while len(generated) < max_new_tokens and pos < cfg.max_seq_len:
        generated.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
        nxt = one(generated[-1], pos)
        pos += 1
    return generated
