"""Decode-side transformer for the serving runtime.

The serving engine does not re-run the training program descriptor per
token — generation wants one *fixed-shape* decode step (one token per
active batch slot, cache reads/writes through block tables) that XLA
compiles exactly once. This module holds that step and the bridge from
the training world into it:

  * ``GenerationConfig`` — the decoder-only architecture hyperparameters
    (the shape of ``models/transformer_fluid.build``: pre-LN blocks,
    fused QKV, gelu FFN, sinusoidal position encoding, untied LM head).
  * ``extract_decoder_weights(program, scope)`` — walks a Fluid program
    built by ``transformer_fluid.build`` (remat=False, dropout=0) and
    lifts its parameters out of the scope into the serving weight
    layout. This is what ``inference.export_generation_model`` calls.
  * ``GenerationModel`` — config + weights; ``make_decode_step`` builds
    the jitted continuous-batching decode step over a ``KVBlockPool``.
  * ``reference_decode`` — an unbatched, unpaged greedy decoder over a
    contiguous cache; the correctness oracle the tests pin the paged
    batched step against token-for-token.

The decode step's calling convention (all shapes fixed per engine):

    step(weights, kv_k, kv_v, prompt_feed, use_prompt, prev_tokens,
         positions, block_tables, active)
      -> (kv_k', kv_v', next_tokens)

``prev_tokens`` is the *device* token vector the previous step returned:
decode-phase slots chain their input token on device (the host never
has to materialize a step before dispatching the next — the PR-2
async-window contract), while prefill-phase slots override it with
``prompt_feed`` under ``use_prompt``. Inactive slots route their cache
writes to the pool's null block and their outputs are ignored.

``make_prefill_step`` is the second, chunked step shape (Sarathi-style
mixed batches, docs/SERVING.md): every row carries a ``[chunk]`` token
window — prefill rows consume up to ``chunk`` prompt tokens per call
(writing that many KV slots, masked per row by ``lengths``), decode
rows ride the same step as 1-token windows chaining ``prev_tokens`` on
device. Each engine geometry compiles exactly TWO step shapes: this one
and the one-token decode step.

``make_spec_step`` is the speculative-decoding **verify window**
(docs/SERVING.md): the same ``[max_batch, window]`` chunk shape, except
the target's greedy token comes back at EVERY window slot, so feeding
``[t0, d1..dk]`` (a row's last committed token plus ``k`` drafted
continuations) verifies all ``k`` drafts in one step. The matching
draft sources live here too: :class:`NGramDrafter` (prompt-lookup
drafting over the sequence's own prompt+output history — zero extra
weights) and :class:`ModelDrafter` (the pluggable draft-model hook
reusing :class:`GenerationModel`).
"""

import math
import time
from zipfile import BadZipFile as zipfile_BadZipFile

import numpy as np

__all__ = ["GenerationArtifactError", "GenerationConfig",
           "GenerationModel", "ModelDrafter",
           "NGramDrafter", "extract_decoder_weights",
           "parse_tree_shape", "random_weights", "reference_decode",
           "save_generation_artifact", "load_generation_artifact",
           "verify_generation_artifact", "tree_topology"]


def parse_tree_shape(spec):
    """Parse a ``PTPU_SERVE_SPEC_TREE`` value: ``"WxD"`` (e.g. ``"2x3"``
    = width 2, depth 3) -> ``(width, depth)``; empty/None/off -> None
    (tree speculation disabled, the PR-12 linear window)."""
    if not spec:
        return None
    if isinstance(spec, (tuple, list)):
        w, d = spec
    else:
        s = str(spec).strip().lower()
        if s in ("", "0", "off", "false", "no"):
            return None
        if "x" not in s:
            raise ValueError(
                "spec tree shape must look like 'WxD' (width x depth, "
                "e.g. '2x3'), got %r" % (spec,))
        w, d = s.split("x", 1)
    w, d = int(w), int(d)
    if w < 1 or d < 1:
        raise ValueError(
            "spec tree width and depth must be >= 1, got %dx%d" % (w, d))
    return w, d


def tree_topology(width, depth):
    """Static topology of the speculative token tree (docs/SERVING.md):
    ``width`` root-anchored chains of ``depth`` draft slots in
    LEVEL-ORDER layout, slot 0 the root (the row's last committed
    token). Level ``l`` (1-based) of chain ``c`` is slot
    ``1 + (l - 1) * width + c``; its parent is the same chain one level
    up (the root at ``l == 1``). Level order means any slot-prefix of
    the window is itself a valid (shallower) tree, so the per-row
    budget clamp reuses the window-length masking.

    Returns ``(parents, depths, anc)`` — int32 ``[C]``, int32 ``[C]``
    and bool ``[C, C]`` for ``C = 1 + width * depth``, with
    ``anc[j, t]`` true iff slot ``t`` is ``j`` or an ancestor of ``j``
    (slot ``j``'s in-window attention visibility: exactly its own root
    path, sibling branches mutually invisible)."""
    width, depth = int(width), int(depth)
    C = 1 + width * depth
    parents = np.zeros(C, np.int32)
    depths = np.zeros(C, np.int32)
    for level in range(1, depth + 1):
        for c in range(width):
            s = 1 + (level - 1) * width + c
            parents[s] = 0 if level == 1 else s - width
            depths[s] = level
    anc = np.zeros((C, C), bool)
    for s in range(C):
        anc[s, s] = True
        j = s
        while j:
            j = int(parents[j])
            anc[s, j] = True
    return parents, depths, anc

# serving-artifact file names (written by
# inference.export_generation_model next to the one-shot
# __serving__/__serving_native__ artifacts so native_serve and the
# continuous-batching engine deploy from ONE directory). The manifest
# (per-leaf sha256 digests + file-size inventory, written LAST) is the
# publish marker the atomic tmp+rename export leaves behind — a torn
# export is detected by the loader, never served.
GENERATION_WEIGHTS = "__generation__.npz"
GENERATION_META = "__generation_meta__.json"
GENERATION_MANIFEST = "__generation_manifest__.json"


def _kernel_key_suffix():
    """Step-cache key component for the Pallas kernel dispatch policy
    (ops/kernel_registry): a step traced under one PTPU_KERNELS mode
    must not serve another. Empty in the default (auto) state so
    pre-kernel cache keys stay bitwise identical."""
    from ..ops.kernel_registry import cache_key

    key = cache_key()
    return () if key == "auto" else ("kernels:" + key,)


class GenerationConfig:
    """Decoder-only LM hyperparameters (transformer_fluid.build shape)."""

    def __init__(self, vocab_size, d_model, n_heads, n_layers, d_ff,
                 max_seq_len=512, pe_alpha=1.0, pe_beta=1.0):
        if d_model % n_heads:
            raise ValueError("n_heads must divide d_model")
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.d_ff = int(d_ff)
        self.max_seq_len = int(max_seq_len)
        self.pe_alpha = float(pe_alpha)
        self.pe_beta = float(pe_beta)

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    def to_dict(self):
        return {k: getattr(self, k) for k in
                ("vocab_size", "d_model", "n_heads", "n_layers", "d_ff",
                 "max_seq_len", "pe_alpha", "pe_beta")}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


# weight-name layout (one flat dict; per-layer names carry an l<i>/
# prefix). Everything is fp32 on the serving side.
_LAYER_KEYS = ("ln1_scale", "ln1_bias", "wqkv", "bqkv", "wproj", "bproj",
               "ln2_scale", "ln2_bias", "wff1", "bff1", "wff2", "bff2")


def weight_names(config):
    names = ["embedding", "lm_head", "final_ln_scale", "final_ln_bias"]
    for i in range(config.n_layers):
        names.extend("l%d/%s" % (i, k) for k in _LAYER_KEYS)
    return names


def _position_encoding_table(config):
    """The exact ``add_position_encoding`` kernel table
    (ops/nn_ops.py): pe[t] = [sin(t/10000^(2i/d)) | cos(...)]."""
    d = config.d_model
    pos = np.arange(config.max_seq_len)[:, None]
    i = np.arange(d // 2)[None, :]
    angle = pos / np.power(10000.0, 2 * i / d)
    return np.concatenate([np.sin(angle), np.cos(angle)],
                          axis=1).astype(np.float32)


def random_weights(config, seed=0, scale=0.1):
    """Deterministic random weights (tests/bench: a servable model with
    no training program behind it)."""
    rng = np.random.RandomState(seed)
    D, F, V = config.d_model, config.d_ff, config.vocab_size

    def w(*shape):
        return (rng.randn(*shape) * scale).astype(np.float32)

    weights = {
        "embedding": w(V, D),
        "lm_head": w(D, V),
        "final_ln_scale": np.ones(D, np.float32),
        "final_ln_bias": np.zeros(D, np.float32),
    }
    for i in range(config.n_layers):
        p = "l%d/" % i
        weights[p + "ln1_scale"] = np.ones(D, np.float32)
        weights[p + "ln1_bias"] = np.zeros(D, np.float32)
        weights[p + "wqkv"] = w(D, 3 * D)
        weights[p + "bqkv"] = np.zeros(3 * D, np.float32)
        weights[p + "wproj"] = w(D, D)
        weights[p + "bproj"] = np.zeros(D, np.float32)
        weights[p + "ln2_scale"] = np.ones(D, np.float32)
        weights[p + "ln2_bias"] = np.zeros(D, np.float32)
        weights[p + "wff1"] = w(D, F)
        weights[p + "bff1"] = np.zeros(F, np.float32)
        weights[p + "wff2"] = w(F, D)
        weights[p + "bff2"] = np.zeros(D, np.float32)
    return weights


# ---------------------------------------------------------------------------
# extraction from a transformer_fluid.build program
# ---------------------------------------------------------------------------


def extract_decoder_weights(program, scope, max_seq_len=None):
    """Lift the decoder weights out of a program built by
    ``models.transformer_fluid.build(remat=False, dropout_rate=0)`` (the
    bench/CI flagship configuration) into the serving layout.

    The walker is positional over op *types*, so it is insensitive to the
    interleaved elementwise/reshape plumbing: embeddings come from the
    ``lookup_table`` op, per-layer weights from the in-order sequence of
    ``layer_norm`` / ``fused_multihead_attention`` / parameter ``mul``
    ops, and the LM head from the (chunk-shared) ``lm_head_w`` matmuls.
    Returns ``(GenerationConfig, weights_dict)`` with everything cast to
    fp32.
    """
    block = program.global_block()

    def _is_param(name):
        v = block._find_var_recursive(name)
        return v is not None and getattr(v, "persistable", False)

    def _val(name):
        val = scope.get(name)
        if val is None:
            raise RuntimeError(
                "parameter %r has no value — run the startup program "
                "before exporting" % name)
        return np.asarray(val, np.float32)

    emb = None
    pe_alpha = pe_beta = 1.0
    lns, atts, muls = [], [], []
    pending_mul = None
    for op in block.ops:
        if op.type == "lookup_table" and emb is None:
            emb = op.inputs["W"][0].name
        elif op.type == "add_position_encoding":
            pe_alpha = op.attrs.get("alpha", 1.0)
            pe_beta = op.attrs.get("beta", 1.0)
        elif op.type == "layer_norm":
            lns.append((op.inputs["Scale"][0].name,
                        op.inputs["Bias"][0].name))
        elif op.type == "fused_multihead_attention":
            atts.append({k: v[0].name for k, v in op.inputs.items()
                         if k != "X"})
        elif op.type == "mul" and _is_param(op.inputs["Y"][0].name):
            pending_mul = [op.inputs["Y"][0].name, None]
            muls.append(pending_mul)
        elif (op.type == "elementwise_add" and pending_mul is not None
              and _is_param(op.inputs["Y"][0].name)):
            pending_mul[1] = op.inputs["Y"][0].name
            pending_mul = None
        elif op.type == "recompute":
            raise NotImplementedError(
                "export_generation_model walks the flat op list — build "
                "the program with transformer_fluid.build(remat=False)")

    if emb is None or not atts:
        raise ValueError(
            "program does not look like transformer_fluid.build output "
            "(no embedding / fused_multihead_attention ops found)")
    L = len(atts)
    if len(lns) != 2 * L + 1:
        raise ValueError(
            "expected %d layer_norm ops for %d layers, found %d — only "
            "the remat=False, dropout_rate=0 build is exportable"
            % (2 * L + 1, L, len(lns)))
    ffn_muls = muls[:2 * L]
    head_muls = muls[2 * L:]
    head_params = {m[0] for m in head_muls}
    if len(ffn_muls) != 2 * L or len(head_params) != 1:
        raise ValueError(
            "expected 2 FFN matmuls per layer plus one shared LM-head "
            "parameter; found %d muls over params %r"
            % (len(muls), sorted({m[0] for m in muls})))

    emb_w = _val(emb)
    V, D = emb_w.shape
    wq0 = _val(atts[0]["WQ"])
    H = wq0.shape[1]
    F = _val(ffn_muls[0][0]).shape[1]
    config = GenerationConfig(
        vocab_size=V, d_model=D, n_heads=H, n_layers=L, d_ff=F,
        max_seq_len=max_seq_len or 512, pe_alpha=pe_alpha,
        pe_beta=pe_beta)

    weights = {"embedding": emb_w,
               "lm_head": _val(next(iter(head_params))),
               "final_ln_scale": _val(lns[2 * L][0]),
               "final_ln_bias": _val(lns[2 * L][1])}
    if weights["lm_head"].shape != (D, V):
        raise ValueError("LM head shape %r != (d_model, vocab)"
                         % (weights["lm_head"].shape,))
    for i in range(L):
        p = "l%d/" % i
        att = atts[i]
        # [D, H, Dh] per-head projections -> fused [D, 3D] qkv matmul
        wq, wk, wv = (_val(att[k]).reshape(D, D)
                      for k in ("WQ", "WK", "WV"))
        weights[p + "wqkv"] = np.concatenate([wq, wk, wv], axis=1)
        bq, bk, bv = (_val(att[k]).reshape(D) if k in att
                      else np.zeros(D, np.float32)
                      for k in ("BQ", "BK", "BV"))
        weights[p + "bqkv"] = np.concatenate([bq, bk, bv])
        weights[p + "wproj"] = _val(att["WO"]).reshape(D, D)
        weights[p + "bproj"] = (_val(att["BO"]) if "BO" in att
                                else np.zeros(D, np.float32))
        weights[p + "ln1_scale"] = _val(lns[2 * i][0])
        weights[p + "ln1_bias"] = _val(lns[2 * i][1])
        weights[p + "ln2_scale"] = _val(lns[2 * i + 1][0])
        weights[p + "ln2_bias"] = _val(lns[2 * i + 1][1])
        for j, nm in ((0, "ff1"), (1, "ff2")):
            wname, bname = ffn_muls[2 * i + j]
            weights[p + "w" + nm] = _val(wname)
            weights[p + "b" + nm] = (
                _val(bname) if bname is not None
                else np.zeros(weights[p + "w" + nm].shape[1], np.float32))
    return config, weights


# ---------------------------------------------------------------------------
# serving artifact (weights npz + meta json)
# ---------------------------------------------------------------------------


class GenerationArtifactError(RuntimeError):
    """A generation artifact failed digest/inventory verification — a
    torn export (crash mid-write, injected `ckpt_torn_export`). The
    message names the artifact directory and the first mismatch, so
    the rollout ledger and the operator see the same structured
    story."""

    def __init__(self, dirname, reason):
        self.dirname = dirname
        self.reason = reason
        super().__init__(
            "generation artifact %s is torn or corrupt: %s — "
            "re-export it (inference.export_generation_model); it must "
            "never be served" % (dirname, reason))


def _weight_digest(arr):
    """sha256 over dtype + shape + host bytes (the checkpoint.py leaf
    digest, specialized to the flat fp32 serving layout)."""
    import hashlib

    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _fsync_file(path):
    import os

    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    import os

    try:
        _fsync_file(path)
    except OSError:
        pass  # fsync on a dir is best-effort (not all filesystems)


def _maybe_tear_export(dirname):
    """`ckpt_torn_export` fault injection: after a publish lands,
    truncation-corrupt the weights payload in place — the torn export
    the digest manifest exists to catch (the checkpoint.py
    `ckpt_torn_write` pattern, at the serving-artifact layer)."""
    import os

    from ..resilience import global_injector

    if not global_injector().fire_occurrence("ckpt_torn_export"):
        return
    path = os.path.join(dirname, GENERATION_WEIGHTS)
    with open(path, "r+b") as f:
        data = f.read()
        if not data:
            return
        f.seek(0)
        f.write(bytes(b ^ 0xFF for b in data[: max(1, len(data) // 2)]))
        f.truncate(max(1, len(data) // 2))


def save_generation_artifact(dirname, config, weights):
    """Atomically publish the generation-serving artifact: one STORED
    npz of fp32 weights, a json config, and a digest manifest
    (per-weight sha256 + file-size inventory). Everything lands in a
    temp dir first; a fresh ``dirname`` is published by ONE rename,
    an existing one by per-file replaces with the manifest LAST (the
    completeness marker a crash mid-export never writes). Returns the
    npz path."""
    import json
    import os
    import shutil

    dirname = os.path.abspath(dirname)
    parent = os.path.dirname(dirname) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent,
                       ".ptpu_tmp_" + os.path.basename(dirname))
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    weights = {k: np.asarray(v, np.float32) for k, v in weights.items()}
    np.savez(os.path.join(tmp, GENERATION_WEIGHTS), **weights)
    with open(os.path.join(tmp, GENERATION_META), "w") as f:
        json.dump(config.to_dict(), f, indent=2, sort_keys=True)
    manifest = {
        "format": 1,
        "digests": {k: _weight_digest(v) for k, v in weights.items()},
        "files": {n: os.path.getsize(os.path.join(tmp, n))
                  for n in (GENERATION_WEIGHTS, GENERATION_META)},
    }
    with open(os.path.join(tmp, GENERATION_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    for n in (GENERATION_WEIGHTS, GENERATION_META):
        _fsync_file(os.path.join(tmp, n))
    if not os.path.exists(dirname):
        os.rename(tmp, dirname)
    else:
        # the directory already holds other artifacts (__serving__,
        # a prior generation export): replace per file, payloads
        # before the manifest — a crash in between leaves a digest
        # mismatch the loader reports, never a silently-torn read
        stale = os.path.join(dirname, GENERATION_MANIFEST)
        if os.path.exists(stale):
            os.remove(stale)
        for n in (GENERATION_WEIGHTS, GENERATION_META,
                  GENERATION_MANIFEST):
            os.replace(os.path.join(tmp, n), os.path.join(dirname, n))
        shutil.rmtree(tmp, ignore_errors=True)
    _fsync_dir(dirname)
    _fsync_dir(parent)
    _maybe_tear_export(dirname)
    return os.path.join(dirname, GENERATION_WEIGHTS)


def verify_generation_artifact(dirname):
    """Verify an exported artifact against its digest manifest: file
    inventory sizes plus per-weight sha256 over the loaded arrays.
    Raises :class:`GenerationArtifactError` naming the artifact on any
    mismatch. Returns True when verified, False for a legacy artifact
    with no manifest (nothing to verify against)."""
    import json
    import os

    mpath = os.path.join(dirname, GENERATION_MANIFEST)
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise GenerationArtifactError(dirname,
                                      "unreadable manifest (%s)" % e)
    for n, size in manifest.get("files", {}).items():
        p = os.path.join(dirname, n)
        if not os.path.exists(p):
            raise GenerationArtifactError(dirname, "missing file %s" % n)
        actual = os.path.getsize(p)
        if actual != int(size):
            raise GenerationArtifactError(
                dirname, "file %s is %d bytes, manifest says %d"
                % (n, actual, size))
    digests = manifest.get("digests", {})
    try:
        with np.load(os.path.join(dirname, GENERATION_WEIGHTS)) as z:
            names = set(z.files)
            if names != set(digests):
                raise GenerationArtifactError(
                    dirname, "weight set mismatch (%d stored vs %d in "
                    "manifest)" % (len(names), len(digests)))
            for k in sorted(names):
                if _weight_digest(z[k]) != digests[k]:
                    raise GenerationArtifactError(
                        dirname, "digest mismatch on weight %r" % k)
    except (OSError, ValueError, zipfile_BadZipFile) as e:
        raise GenerationArtifactError(dirname,
                                      "unreadable weights (%s)" % e)
    return True


def load_generation_artifact(dirname, name=None, quantize=None,
                             verify=True):
    """Load an exported generation artifact as a ready-to-serve
    :class:`GenerationModel`. ``quantize='weight_only'`` serves the SAME
    artifact with the int8 weight store (``GenerationModel.quantized``)
    — no re-export needed. Artifacts carrying a digest manifest are
    verified on load (``verify=False`` skips it); a torn export raises
    :class:`GenerationArtifactError` naming the artifact."""
    import json
    import os

    meta_path = os.path.join(dirname, GENERATION_META)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            "%s has no %s — export with "
            "paddle_tpu.inference.export_generation_model"
            % (dirname, GENERATION_META))
    if verify:
        verify_generation_artifact(dirname)
    with open(meta_path) as f:
        config = GenerationConfig.from_dict(json.load(f))
    try:
        with np.load(os.path.join(dirname, GENERATION_WEIGHTS)) as z:
            weights = {k: z[k] for k in z.files}
    except (OSError, ValueError, zipfile_BadZipFile) as e:
        raise GenerationArtifactError(dirname,
                                      "unreadable weights (%s)" % e)
    model = GenerationModel(config, weights,
                            name=name or os.path.basename(dirname))
    if quantize:
        if quantize not in (True, "weight_only", "int8"):
            raise ValueError(
                "quantize=%r — the serving runtime supports the "
                "weight_only int8 store (docs/QUANTIZATION.md)"
                % (quantize,))
        model = model.quantized()
    return model


# ---------------------------------------------------------------------------
# the fixed-shape decode step
# ---------------------------------------------------------------------------


class GenerationModel:
    """Config + weights + the jitted continuous-batching decode step.

    ``quantized()`` derives the weight-only-int8 variant
    (docs/QUANTIZATION.md): every 2-D matmul weight (embedding, qkv,
    proj, ffn, lm head) is STORED int8 with a per-output-channel fp32
    scale riding in the same weights dict under ``<name>@qscale``, and
    the decode step dequantizes on use — the compute stays fp32, the
    HBM-resident weight store (what a memory-bandwidth-bound decode
    step actually streams) shrinks ~4x. Decoding a quantized model is
    token-identical to ``reference_decode`` over
    ``dequantized_weights()`` (its fp32 reference)."""

    def __init__(self, config, weights, name="model"):
        self.config = config
        self.name = name
        missing = [n for n in weight_names(config) if n not in weights]
        if missing:
            raise ValueError("missing weights: %s" % missing[:4])
        import jax.numpy as jnp

        # int8 entries (the weight-only-quantized store) keep their
        # dtype; everything else normalizes to fp32 as before
        self.weights = {
            k: jnp.asarray(v if np.asarray(v).dtype == np.int8
                           else np.asarray(v, np.float32))
            for k, v in weights.items()}
        self.weight_only_int8 = any(
            str(v.dtype) == "int8" for v in self.weights.values())
        # python-trace counter: the body below only executes while jax
        # traces, so tests can pin "no retrace across join/retire"
        self.trace_count = 0
        self._steps = {}

    @classmethod
    def random(cls, config, seed=0, name="model"):
        return cls(config, random_weights(config, seed), name=name)

    # -- weight-only int8 ---------------------------------------------------
    def quantized(self, name=None):
        """The weight-only-int8 variant of this model: 2-D matmul
        weights become int8 + ``@qscale`` per-output-channel scales;
        biases, layer norms and the model structure are untouched.
        Records quant/{weights_quantized,weight_bytes_saved,
        weight_fp32_bytes} telemetry."""
        from ..quant import quantize_symmetric, record_weight_store

        if self.weight_only_int8:
            return self
        qw = {}
        n_q = saved = fp32 = 0
        for k, v in self.weights.items():
            w = np.asarray(v)
            if w.ndim == 2 and w.dtype == np.float32:
                # the shared symmetric int8 grid (paddle_tpu.quant),
                # per output column (axis 1 of the [in, out] layout;
                # per d_model column for the [V, D] embedding)
                q, s = quantize_symmetric(w, channel_axis=1)
                qw[k] = q
                qw[k + "@qscale"] = (s / 127.0).astype(np.float32)
                n_q += 1
                saved += max(w.nbytes - q.nbytes - s.nbytes, 0)
                fp32 += w.nbytes
            else:
                qw[k] = w
        record_weight_store(n_q, saved, fp32)
        return GenerationModel(self.config, qw,
                               name=name or self.name + ".int8")

    def dequantized_weights(self):
        """fp32 weights dict with the int8 store multiplied back out —
        the quantized model's numerics reference (a GenerationModel
        built from these decodes token-identically to this one)."""
        out = {}
        for k, v in self.weights.items():
            if k.endswith("@qscale"):
                continue
            w = np.asarray(v)
            s = self.weights.get(k + "@qscale")
            out[k] = (w.astype(np.float32) * np.asarray(s)
                      if s is not None else w)
        return out

    def _w(self, jnp, weights, key):
        """One weight in compute dtype: dequantize-on-use for the int8
        store (XLA fuses the convert+scale into the consuming dot)."""
        s = weights.get(key + "@qscale")
        w = weights[key]
        return w.astype(jnp.float32) * s if s is not None else w

    def _forward_token(self, jnp, weights, x, positions, block_tables,
                       active, kv_k, kv_v):
        """One token through all layers. x: [B, D]; returns
        (kv_k, kv_v, logits[B, V])."""
        import jax

        cfg = self.config
        B = x.shape[0]
        H, Dh = cfg.n_heads, cfg.head_dim
        bs = kv_k.shape[2]
        max_ctx = block_tables.shape[1] * bs
        sm_scale = Dh ** -0.5

        blk_idx = positions // bs
        slot_idx = positions % bs
        # inactive slots scatter into the null block (never read back)
        write_blk = jnp.where(
            active,
            jnp.take_along_axis(block_tables, blk_idx[:, None],
                                axis=1)[:, 0],
            0)

        # one dispatch decision per forward (trace time), shared by all
        # layers: the paged flash-decode kernel reads the pool pages
        # through the block table in-kernel, so the contiguous
        # kv[block_tables] gather below never materializes
        from ..ops.kernel_registry import choose as _choose_kernel

        use_paged = _choose_kernel("paged_decode", head_dim=Dh,
                                   block_size=bs)
        if use_paged:
            from ..ops.pallas_kernels import paged_attention

        def ln(h, scale, bias):
            mu = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.mean((h - mu) ** 2, axis=-1, keepdims=True)
            return (h - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias

        # context-position validity: t <= position (the current token's
        # k/v are written before the gather, so self-attention sees them)
        t_ids = jnp.arange(max_ctx)[None, :]
        valid = t_ids <= positions[:, None]

        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, weights[p + "ln1_scale"], weights[p + "ln1_bias"])
            qkv = a @ self._w(jnp, weights, p + "wqkv") \
                + weights[p + "bqkv"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, H, Dh)
            k_new = k_new.reshape(B, H, Dh)
            v_new = v_new.reshape(B, H, Dh)
            kv_k = kv_k.at[i, write_blk, slot_idx].set(k_new)
            kv_v = kv_v.at[i, write_blk, slot_idx].set(v_new)
            if use_paged:
                ctx = paged_attention(
                    kv_k[i], kv_v[i], q[:, None], block_tables,
                    positions[:, None], sm_scale=sm_scale)
                ctx = ctx[:, 0].reshape(B, -1)
            else:
                # paged gather: [B, Mb, bs, H, Dh] -> [B, max_ctx, H, Dh]
                k_ctx = kv_k[i][block_tables].reshape(B, max_ctx, H, Dh)
                v_ctx = kv_v[i][block_tables].reshape(B, max_ctx, H, Dh)
                scores = jnp.einsum("bhd,bthd->bht", q, k_ctx) * sm_scale
                scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
                w = jnp.exp(scores
                            - jnp.max(scores, axis=-1, keepdims=True))
                w = w / jnp.sum(w, axis=-1, keepdims=True)
                ctx = jnp.einsum("bht,bthd->bhd", w, v_ctx) \
                    .reshape(B, -1)
            x = x + ctx @ self._w(jnp, weights, p + "wproj") \
                + weights[p + "bproj"]
            b2 = ln(x, weights[p + "ln2_scale"], weights[p + "ln2_bias"])
            f = jax.nn.gelu(b2 @ self._w(jnp, weights, p + "wff1")
                            + weights[p + "bff1"], approximate=False)
            x = x + f @ self._w(jnp, weights, p + "wff2") \
                + weights[p + "bff2"]

        x = ln(x, weights["final_ln_scale"], weights["final_ln_bias"])
        return kv_k, kv_v, x @ self._w(jnp, weights, "lm_head")

    def make_decode_step(self, max_batch, max_blocks_per_seq,
                         return_logits=False):
        """Build (and cache) the jitted fixed-shape decode step for this
        engine geometry. The KV arrays are donated — updates alias
        in-place in device memory."""
        key = (int(max_batch), int(max_blocks_per_seq),
               bool(return_logits)) + _kernel_key_suffix()
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        cfg = self.config
        pe = jnp.asarray(_position_encoding_table(cfg))
        emb_scale = float(cfg.d_model) ** 0.5

        def step(weights, kv_k, kv_v, prompt_feed, use_prompt,
                 prev_tokens, positions, block_tables, active):
            self.trace_count += 1
            tok = jnp.where(use_prompt, prompt_feed, prev_tokens)
            tok = jnp.clip(tok, 0, cfg.vocab_size - 1)
            # int8 embedding store: gather the int8 rows FIRST, then
            # dequantize the [B, D] slice — the full fp32 table is never
            # materialized
            emb = jnp.take(weights["embedding"], tok, axis=0)
            es = weights.get("embedding@qscale")
            if es is not None:
                emb = emb.astype(jnp.float32) * es
            x = (emb * emb_scale * cfg.pe_alpha
                 + cfg.pe_beta * jnp.take(pe, positions, axis=0))
            kv_k, kv_v, logits = self._forward_token(
                jnp, weights, x, positions, block_tables, active,
                kv_k, kv_v)
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if return_logits:
                return kv_k, kv_v, next_tokens, logits
            return kv_k, kv_v, next_tokens

        jitted = self._instrument_step("decode", jax.jit(
            step, donate_argnums=(1, 2)))
        self._steps[key] = jitted
        return jitted

    def _instrument_step(self, kind, jitted):
        """With metrics enabled, wrap a jitted step so its first call
        compiles ahead of time (the executor's `_compile_instrumented`
        pattern) and the executable's XLA cost analysis lands in the
        exec/* gauges — serving cache misses get the same FLOPs/bytes
        receipts training steps do. Identity when metrics are off: the
        raw jitted function is returned and cached, zero wrapper frames
        on the default hot path."""
        from ..observability import metrics as _metrics

        if not _metrics.enabled():
            return jitted

        from ..observability import cost as _cost
        from ..observability import tracing as _tracing

        aot = []

        def step(*args):
            if not aot:
                with _tracing.span("serving_compile", kind=kind):
                    t0 = time.perf_counter()
                    compiled = jitted.lower(*args).compile()
                    _metrics.histogram(
                        "serving/step_compile_time").observe(
                        time.perf_counter() - t0)
                _cost.publish(compiled)
                aot.append(compiled)
            return aot[0](*args)

        return step

    def _forward_chunk(self, jnp, weights, x, pos2d, lengths,
                       block_tables, active, kv_k, kv_v,
                       all_slots=False, tree_anc=None):
        """A ``[B, C]`` token window through all layers. x: [B, C, D];
        returns (kv_k, kv_v, logits[B, V]) — each row's logits at its
        LAST valid window slot (``lengths - 1``) — or, with
        ``all_slots=True`` (the speculative verify window), the logits
        at EVERY window slot: (kv_k, kv_v, logits[B, C, V]).

        ``tree_anc`` (bool ``[C, C]``, trace-time constant from
        :func:`tree_topology`) switches the in-window causal mask to
        TREE visibility: window slot ``j`` still writes its KV at cache
        position ``pos2d[b, j]`` (= pos + j, the linear slot layout the
        block tables already cover), but attends the committed prefix
        (cache positions before the window) plus only its OWN root path
        inside the window — sibling branches are mutually invisible, so
        one step verifies every branch of the token tree."""
        import jax

        cfg = self.config
        B, C = x.shape[0], x.shape[1]
        H, Dh = cfg.n_heads, cfg.head_dim
        bs = kv_k.shape[2]
        Mb = block_tables.shape[1]
        max_ctx = Mb * bs
        sm_scale = Dh ** -0.5

        # per-slot write targets: window slot j of row b lands at
        # position pos2d[b, j]; slots past the row's valid length (and
        # whole inactive rows) scatter into the null block instead
        valid = ((jnp.arange(C, dtype=jnp.int32)[None, :]
                  < lengths[:, None]) & active[:, None])
        blk_idx = jnp.clip(pos2d // bs, 0, Mb - 1)
        write_blk = jnp.where(
            valid, jnp.take_along_axis(block_tables, blk_idx, axis=1), 0)
        slot_idx = pos2d % bs

        def ln(h, scale, bias):
            mu = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.mean((h - mu) ** 2, axis=-1, keepdims=True)
            return (h - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias

        # context validity per window slot: t <= that slot's position.
        # The whole window's k/v are written BEFORE the gather, so
        # in-chunk self-attention sees exactly the causal prefix; t=0 is
        # always visible, so no softmax row is fully masked.
        t_ids = jnp.arange(max_ctx)[None, None, :]
        if tree_anc is None:
            attn_valid = t_ids <= pos2d[:, :, None]      # [B, C, T]
        else:
            # tree window: slot j's visibility is the committed prefix
            # (strictly before the window's first position) plus the
            # static ancestor mask over in-window cache positions. The
            # root slot sees itself via anc[0, 0]; pos0 >= 1 past
            # prefill, so no softmax row is ever fully masked.
            pos0 = pos2d[:, 0]
            rel = t_ids - pos0[:, None, None]            # [B, 1, T]
            in_win = (rel >= 0) & (rel < C)
            rel_c = jnp.clip(rel, 0, C - 1)
            anc_t = tree_anc[jnp.arange(C)[None, :, None], rel_c]
            attn_valid = (rel < 0) | (in_win & anc_t)    # [B, C, T]

        # the speculative verify window (all_slots) dispatches the
        # fused spec_window kernel — k+1 query positions against the
        # paged cache in one launch, block table resolved in-kernel;
        # one decision per forward, shared by all layers. The tree
        # window dispatches the tree-mask variant, which takes the
        # ancestor mask as an extra operand.
        from ..ops.kernel_registry import choose as _choose_kernel

        use_paged = all_slots and _choose_kernel(
            "spec_window" if tree_anc is None else "spec_window_tree",
            head_dim=Dh, block_size=bs, window=C)
        if use_paged:
            if tree_anc is None:
                from ..ops.pallas_kernels import paged_attention
            else:
                from ..ops.pallas_kernels import paged_attention_tree
                anc_f = tree_anc.astype(jnp.float32)

        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, weights[p + "ln1_scale"], weights[p + "ln1_bias"])
            qkv = a @ self._w(jnp, weights, p + "wqkv") \
                + weights[p + "bqkv"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, C, H, Dh)
            k_new = k_new.reshape(B, C, H, Dh)
            v_new = v_new.reshape(B, C, H, Dh)
            kv_k = kv_k.at[i, write_blk, slot_idx].set(k_new)
            kv_v = kv_v.at[i, write_blk, slot_idx].set(v_new)
            if use_paged:
                if tree_anc is None:
                    ctx = paged_attention(
                        kv_k[i], kv_v[i], q, block_tables, pos2d,
                        sm_scale=sm_scale).reshape(B, C, -1)
                else:
                    ctx = paged_attention_tree(
                        kv_k[i], kv_v[i], q, block_tables, pos2d,
                        anc_f, sm_scale=sm_scale).reshape(B, C, -1)
            else:
                # paged gather: [B, Mb, bs, H, Dh] -> [B, max_ctx, H, Dh]
                k_ctx = kv_k[i][block_tables].reshape(B, max_ctx, H, Dh)
                v_ctx = kv_v[i][block_tables].reshape(B, max_ctx, H, Dh)
                scores = jnp.einsum("bchd,bthd->bcht", q, k_ctx) \
                    * sm_scale
                scores = jnp.where(attn_valid[:, :, None, :], scores,
                                   -jnp.inf)
                w = jnp.exp(scores
                            - jnp.max(scores, axis=-1, keepdims=True))
                w = w / jnp.sum(w, axis=-1, keepdims=True)
                ctx = jnp.einsum("bcht,bthd->bchd", w, v_ctx) \
                    .reshape(B, C, -1)
            x = x + ctx @ self._w(jnp, weights, p + "wproj") \
                + weights[p + "bproj"]
            b2 = ln(x, weights[p + "ln2_scale"], weights[p + "ln2_bias"])
            f = jax.nn.gelu(b2 @ self._w(jnp, weights, p + "wff1")
                            + weights[p + "bff1"], approximate=False)
            x = x + f @ self._w(jnp, weights, p + "wff2") \
                + weights[p + "bff2"]

        if all_slots:
            x = ln(x, weights["final_ln_scale"], weights["final_ln_bias"])
            return kv_k, kv_v, x @ self._w(jnp, weights, "lm_head")
        last = jnp.clip(lengths - 1, 0, C - 1).astype(jnp.int32)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        x_last = ln(x_last, weights["final_ln_scale"],
                    weights["final_ln_bias"])
        return kv_k, kv_v, x_last @ self._w(jnp, weights, "lm_head")

    def make_prefill_step(self, max_batch, max_blocks_per_seq, chunk,
                          return_logits=False):
        """Build (and cache) the jitted fixed-shape CHUNKED step for
        this engine geometry — the mixed prefill/decode shape
        (docs/SERVING.md). Calling convention:

            step(weights, kv_k, kv_v, chunk_tokens[B, C], use_prompt[B],
                 prev_tokens[B], positions[B], lengths[B],
                 block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', next_tokens[B])

        ``positions[b]`` is row b's FIRST window position; window slot
        ``j`` processes position ``positions[b] + j``. Prefill rows
        (``use_prompt``) take all ``lengths[b]`` tokens from
        ``chunk_tokens``; decode rows are 1-token windows whose first
        slot chains ``prev_tokens`` on device. ``next_tokens[b]`` is
        the greedy token at the row's last valid slot — meaningful when
        the window consumed the final prompt token (the first generated
        token) or for decode rows. The KV arrays are donated."""
        return self._make_window_step("chunk", max_batch,
                                      max_blocks_per_seq, chunk,
                                      all_slots=False,
                                      return_logits=return_logits)

    def _make_window_step(self, kind, max_batch, max_blocks_per_seq,
                          window, all_slots, return_logits, tree=None):
        """The shared ``[max_batch, window]`` jitted step builder behind
        :meth:`make_prefill_step` (``all_slots=False`` — logits at each
        row's last valid slot), :meth:`make_spec_step`
        (``all_slots=True`` — the verify window, argmax at every slot)
        and :meth:`make_spec_tree_step` (``tree=(width, depth)`` — the
        tree verify window: tree attention mask, position encodings at
        each slot's tree DEPTH rather than its window offset). One
        body, so the token-splice/embedding/position plumbing can never
        diverge between the shapes."""
        key = (kind, int(max_batch), int(max_blocks_per_seq),
               int(window), bool(return_logits)) + _kernel_key_suffix()
        if tree is not None:
            key = key + ("tree:%dx%d" % (int(tree[0]), int(tree[1])),)
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        cfg = self.config
        pe = jnp.asarray(_position_encoding_table(cfg))
        emb_scale = float(cfg.d_model) ** 0.5
        C = int(window)
        if tree is None:
            depths_j = anc_j = None
        else:
            _parents, depths_np, anc_np = tree_topology(*tree)
            depths_j = jnp.asarray(depths_np)            # [C]
            anc_j = jnp.asarray(anc_np)                  # [C, C] bool

        def step(weights, kv_k, kv_v, window_tokens, use_prompt,
                 prev_tokens, positions, lengths, block_tables, active):
            self.trace_count += 1
            tok0 = jnp.where(use_prompt, window_tokens[:, 0],
                             prev_tokens)
            tok = jnp.concatenate([tok0[:, None], window_tokens[:, 1:]],
                                  axis=1)
            tok = jnp.clip(tok, 0, cfg.vocab_size - 1)
            pos2d = (positions[:, None]
                     + jnp.arange(C, dtype=jnp.int32)[None, :])
            emb = jnp.take(weights["embedding"], tok, axis=0)
            es = weights.get("embedding@qscale")
            if es is not None:
                emb = emb.astype(jnp.float32) * es
            if tree is None:
                pe_idx = jnp.clip(pos2d, 0, cfg.max_seq_len - 1)
            else:
                # a tree slot's LOGICAL position is root + its depth
                # (siblings share a position; the cache slot stays
                # pos + j)
                pe_idx = jnp.clip(positions[:, None] + depths_j[None, :],
                                  0, cfg.max_seq_len - 1)
            x = (emb * emb_scale * cfg.pe_alpha
                 + cfg.pe_beta * jnp.take(pe, pe_idx, axis=0))
            kv_k, kv_v, logits = self._forward_chunk(
                jnp, weights, x, pos2d, lengths, block_tables, active,
                kv_k, kv_v, all_slots=all_slots, tree_anc=anc_j)
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if return_logits:
                return kv_k, kv_v, next_tokens, logits
            return kv_k, kv_v, next_tokens

        jitted = self._instrument_step(kind, jax.jit(
            step, donate_argnums=(1, 2)))
        self._steps[key] = jitted
        return jitted

    def make_spec_step(self, max_batch, max_blocks_per_seq, window,
                       return_logits=False):
        """Build (and cache) the jitted speculative **verify window**
        for this engine geometry (docs/SERVING.md): the
        ``[max_batch, window]`` chunk shape of :meth:`make_prefill_step`
        except that the target's greedy token is returned at EVERY
        window slot instead of only the last one:

            step(weights, kv_k, kv_v, window_tokens[B, W],
                 use_prompt[B], prev_tokens[B], positions[B],
                 lengths[B], block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', next_tokens[B, W])

        ``next_tokens[b, j]`` is the argmax AFTER window slot ``j`` —
        the token the target would emit at position
        ``positions[b] + j + 1``. A row feeding ``[t0, d1..dk]`` (its
        last committed token plus ``k`` draft tokens) therefore
        verifies every draft in one step: acceptance is the longest
        prefix with ``d[j+1] == next_tokens[b, j]``, and
        ``next_tokens[b, m]`` after the last accepted draft is the
        correction token — computed over an all-verified context, so
        every window emits at least one sequential-greedy-identical
        token. Slots at or past ``lengths[b]`` write to the null block
        and their outputs are meaningless. The KV arrays are donated."""
        return self._make_window_step("spec", max_batch,
                                      max_blocks_per_seq, window,
                                      all_slots=True,
                                      return_logits=return_logits)

    def make_spec_tree_step(self, max_batch, max_blocks_per_seq, width,
                            depth, return_logits=False):
        """Build (and cache) the jitted TREE verify window
        (docs/SERVING.md tree speculation): the :meth:`make_spec_step`
        shape over a ``C = 1 + width * depth`` window holding a
        level-order token tree (:func:`tree_topology` — slot 0 the
        row's last committed token, ``width`` root-anchored chains of
        ``depth`` slots), verified in ONE compiled step via the
        in-window tree attention mask:

            step(weights, kv_k, kv_v, window_tokens[B, C],
                 use_prompt[B], prev_tokens[B], positions[B],
                 lengths[B], block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', next_tokens[B, C])

        ``next_tokens[b, j]`` is the target's greedy token after window
        slot ``j``'s ROOT PATH (committed prefix + j's ancestors + j) —
        the token sequential greedy decoding would emit after accepting
        exactly that path. Acceptance (the host walk,
        ``scheduler.spec_tree_acceptance``) is the deepest root path
        whose every node matches the running argmax; the argmax at the
        accepted frontier is the correction token, so every window
        emits at least one greedy-identical token. Rows may feed any
        level-order PREFIX of the full tree via ``lengths`` (shallower
        trees near budget caps); slots at or past ``lengths[b]`` write
        to the null block. At ``width == 1`` the mask, positions and
        outputs are numerically the linear verify window. The KV arrays
        are donated."""
        width, depth = int(width), int(depth)
        return self._make_window_step("spec_tree", max_batch,
                                      max_blocks_per_seq,
                                      1 + width * depth,
                                      all_slots=True,
                                      return_logits=return_logits,
                                      tree=(width, depth))

    def make_tree_commit_step(self, max_batch, max_blocks_per_seq,
                              window):
        """Build (and cache) the jitted post-acceptance KV
        **compaction** step for tree speculation (docs/SERVING.md): the
        verify window wrote every tree slot's KV at cache position
        ``pos + slot``, but the committed layout needs the ACCEPTED
        root path contiguous at ``pos + 1 ..``. One tiny gather/scatter
        over the window span moves it:

            commit(kv_k, kv_v, positions[B], src_slots[B, C],
                   n_commit[B], block_tables[B, Mb], active[B])
              -> (kv_k', kv_v')

        Row ``b`` copies window slot ``src_slots[b, j]`` (cache
        position ``positions[b] + src_slots[b, j]``) onto cache
        position ``positions[b] + j`` for every ``j < n_commit[b]``
        (the engine passes ``[0, path...]`` so ``j = 0`` is the root's
        identity self-copy); rows needing no move pass ``n_commit = 0``
        and their writes route to the null block. All sources are
        gathered before any destination is written, and the engine
        dispatches this BEFORE ``truncate_owner`` re-points the tail
        blocks, so sources always live in still-owned blocks. Pure data
        movement — no weights are read. The KV arrays are donated."""
        key = ("tree_commit", int(max_batch), int(max_blocks_per_seq),
               int(window)) + _kernel_key_suffix()
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        C = int(window)

        def commit(kv_k, kv_v, positions, src_slots, n_commit,
                   block_tables, active):
            self.trace_count += 1
            Mb = block_tables.shape[1]
            bs = kv_k.shape[2]
            src_pos = positions[:, None] + src_slots        # [B, C]
            src_blk = jnp.take_along_axis(
                block_tables, jnp.clip(src_pos // bs, 0, Mb - 1),
                axis=1)
            k_win = kv_k[:, src_blk, src_pos % bs]  # [L, B, C, H, Dh]
            v_win = kv_v[:, src_blk, src_pos % bs]
            dst_pos = (positions[:, None]
                       + jnp.arange(C, dtype=jnp.int32)[None, :])
            dst_ok = ((jnp.arange(C, dtype=jnp.int32)[None, :]
                       < n_commit[:, None]) & active[:, None])
            dst_blk = jnp.where(
                dst_ok,
                jnp.take_along_axis(block_tables,
                                    jnp.clip(dst_pos // bs, 0, Mb - 1),
                                    axis=1),
                0)
            kv_k = kv_k.at[:, dst_blk, dst_pos % bs].set(k_win)
            kv_v = kv_v.at[:, dst_blk, dst_pos % bs].set(v_win)
            return kv_k, kv_v

        jitted = self._instrument_step("tree_commit", jax.jit(
            commit, donate_argnums=(0, 1)))
        self._steps[key] = jitted
        return jitted

    def make_draft_step(self, max_batch, max_blocks_per_seq, n_new):
        """Build (and cache) the fused jitted DRAFT step
        (docs/SERVING.md tree speculation): starting from
        ``first_tokens`` (each row's first draft token, already argmaxed
        by the catch-up chunk) at ``positions``, run ``n_new`` greedy
        one-token micro-steps in ONE compiled call (a ``lax.scan`` over
        the one-token forward), each writing its KV slot and chaining
        its argmax into the next — this is what retires the per-row
        host ``reference_decode`` loop of the PR-12 :class:`ModelDrafter`:

            draft(weights, kv_k, kv_v, first_tokens[B], positions[B],
                  block_tables[B, Mb], active[B])
              -> (kv_k', kv_v', tokens[B, n_new])

        ``tokens[b, i]`` is the greedy token after feeding the
        ``i+1``-th chain token, i.e. chain tokens ``2 .. n_new + 1`` of
        a draft whose first token is ``first_tokens[b]``. Active rows
        MUST have ``positions + n_new <= max_seq_len`` (the caller
        deactivates rows near the cap — inactive rows write to the null
        block and their outputs are ignored). The KV arrays are
        donated."""
        key = ("draft", int(max_batch), int(max_blocks_per_seq),
               int(n_new)) + _kernel_key_suffix()
        if key in self._steps:
            return self._steps[key]
        import jax
        import jax.numpy as jnp

        cfg = self.config
        pe = jnp.asarray(_position_encoding_table(cfg))
        emb_scale = float(cfg.d_model) ** 0.5
        n_new = int(n_new)

        def embed(weights, tok, pos):
            tok = jnp.clip(tok, 0, cfg.vocab_size - 1)
            emb = jnp.take(weights["embedding"], tok, axis=0)
            es = weights.get("embedding@qscale")
            if es is not None:
                emb = emb.astype(jnp.float32) * es
            pe_idx = jnp.clip(pos, 0, cfg.max_seq_len - 1)
            return (emb * emb_scale * cfg.pe_alpha
                    + cfg.pe_beta * jnp.take(pe, pe_idx, axis=0))

        def draft(weights, kv_k, kv_v, first_tokens, positions,
                  block_tables, active):
            self.trace_count += 1

            def micro(carry, i):
                kv_k, kv_v, tok = carry
                pos = positions + i
                x = embed(weights, tok, pos)
                kv_k, kv_v, logits = self._forward_token(
                    jnp, weights, x, pos, block_tables, active,
                    kv_k, kv_v)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (kv_k, kv_v, nxt), nxt

            (kv_k, kv_v, _last), toks = jax.lax.scan(
                micro, (kv_k, kv_v, first_tokens),
                jnp.arange(n_new, dtype=jnp.int32))
            return kv_k, kv_v, jnp.transpose(toks)      # [B, n_new]

        jitted = self._instrument_step("draft", jax.jit(
            draft, donate_argnums=(1, 2)))
        self._steps[key] = jitted
        return jitted


# ---------------------------------------------------------------------------
# draft sources for speculative decoding (docs/SERVING.md)
# ---------------------------------------------------------------------------


class NGramDrafter:
    """Prompt-lookup / n-gram drafting (zero extra weights): match the
    sequence's most recent suffix n-gram against earlier occurrences in
    its OWN prompt+output history and propose the tokens that followed
    the most recent earlier match. Strongest exactly where the radix
    prefix cache already wins — templated, repetitive and structured
    generation (code, JSON, quoting the prompt back) — and free
    everywhere else: a miss proposes nothing and the verify window
    degrades to a plain one-token decode step.

    ``propose(history, k)`` tries match lengths from ``max_ngram`` down
    to ``min_ngram`` and returns up to ``k`` continuation tokens (empty
    when no n-gram recurs).

    With a ``seq_id`` (``propose_for`` — what the scheduler passes),
    the drafter keeps an INCREMENTAL per-sequence suffix index instead
    of rescanning the full history every window: each n-gram's start
    positions are recorded once when the history first covers them
    (committed history is append-only between windows; a shrunken or
    diverged history rebuilds the index from scratch), so draft-side
    host time per window is O(k + tokens newly committed), not O(L).
    ``index_ops`` counts gram insertions + occurrence probes — the
    unit-test pin that the rescan is really gone. ``release(seq_id)``
    drops a retired sequence's index (the scheduler's reap calls it)."""

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        if self.min_ngram < 1:
            raise ValueError("min_ngram must be >= 1")
        if self.max_ngram < self.min_ngram:
            raise ValueError("max_ngram must be >= min_ngram")
        self._index = {}        # seq_id -> {len, last, grams{n: {...}}}
        self.index_ops = 0

    def release(self, seq_id):
        """Drop a retired sequence's memoized suffix index."""
        self._index.pop(seq_id, None)

    def _indexed(self, seq_id, hist):
        """The per-sequence suffix index advanced to cover ``hist``:
        ``grams[n]`` maps each n-gram tuple to its ASCENDING start
        positions. Incremental — only grams starting in the newly
        appended span are inserted; a history that shrank or whose
        last cached token changed (external rollback/divergence)
        rebuilds from scratch."""
        L = len(hist)
        ent = self._index.get(seq_id)
        if (ent is None or ent["len"] > L
                or (ent["len"] > 0 and hist[ent["len"] - 1] != ent["last"])):
            ent = {"len": 0, "last": None,
                   "grams": {n: {} for n in
                             range(self.min_ngram, self.max_ngram + 1)}}
            self._index[seq_id] = ent
        L0 = ent["len"]
        for n in range(self.min_ngram, self.max_ngram + 1):
            grams = ent["grams"][n]
            for j in range(max(L0 - n + 1, 0), L - n + 1):
                grams.setdefault(tuple(hist[j:j + n]), []).append(j)
                self.index_ops += 1
        ent["len"] = L
        ent["last"] = hist[L - 1] if L else None
        return ent

    def propose_for(self, seq_id, history, k):
        """``propose`` through the incremental per-sequence index —
        identical tokens, O(k)-per-window host cost."""
        return self.propose(history, k, seq_id=seq_id)

    def propose(self, history, k, seq_id=None):
        k = int(k)
        if k < 1 or len(history) < self.min_ngram + 1:
            return []
        hist = [int(t) for t in history]
        L = len(hist)
        ent = self._indexed(seq_id, hist) if seq_id is not None else None
        for n in range(min(self.max_ngram, L - 1),
                       self.min_ngram - 1, -1):
            suffix = hist[L - n:]
            # the most recent earlier occurrence able to supply a FULL
            # k-token continuation wins (recency beats frequency for
            # local repetition, but a match right at the history's end
            # can only offer a truncated draft — on a period-p
            # repetition the nearest match yields only p tokens, so
            # scan on for an earlier full-window one); the match must
            # end before the suffix starts so the continuation is real
            best = None
            if ent is not None:
                # memoized path: same candidates in the same recency
                # order, read straight off the occurrence list
                occ = ent["grams"][n].get(tuple(suffix), ())
                for j in reversed(occ):
                    self.index_ops += 1
                    if j >= L - n:      # the trailing suffix itself
                        continue
                    avail = min(k, L - (j + n))
                    if best is None or avail > best[1]:
                        best = (j, avail)
                    if avail >= k:
                        break
            else:
                for j in range(L - n - 1, -1, -1):
                    if hist[j:j + n] != suffix:
                        continue
                    avail = min(k, L - (j + n))
                    if best is None or avail > best[1]:
                        best = (j, avail)
                    if avail >= k:
                        break
            if best is not None:
                start = best[0] + n
                return hist[start:start + k]
        return []

    def propose_tree(self, history, width, depth, seq_id=None):
        """Tree drafting (docs/SERVING.md): up to ``width``
        root-anchored chains of up to ``depth`` tokens. Chain 0 is the
        linear :meth:`propose` draft; alternate chains are the
        continuations of OTHER occurrence sites of the same suffix
        whose next token differs — exactly the traffic
        (period-alternating repetition) where a single linear chain
        keeps losing the verify window. Host work is bounded by a small
        per-call probe budget, so tree drafting stays O(width * depth)
        per window on the memoized path."""
        width, depth = int(width), int(depth)
        primary = self.propose(history, depth, seq_id=seq_id)
        if width <= 1 or len(history) < self.min_ngram + 1:
            return [primary] if primary else []
        hist = [int(t) for t in history]
        L = len(hist)
        chains = [primary] if primary else []
        seen = {primary[0]} if primary else set()
        for n in range(min(self.max_ngram, L - 1),
                       self.min_ngram - 1, -1):
            if seq_id is not None:
                occ = list(self._indexed(seq_id, hist)["grams"][n]
                           .get(tuple(hist[L - n:]), ()))
            else:
                suffix = hist[L - n:]
                occ = [j for j in range(L - n)
                       if hist[j:j + n] == suffix]
            budget = 8 * width + depth
            for j in reversed(occ):
                if len(chains) >= width or budget <= 0:
                    break
                budget -= 1
                self.index_ops += 1
                if j >= L - n:
                    continue
                cont = hist[j + n:j + n + depth]
                if not cont or cont[0] in seen:
                    continue
                seen.add(cont[0])
                chains.append(cont)
            if occ:
                # branches come from the longest recurring suffix only
                break
        return chains


class _DraftSeq:
    """Per-sequence drafter-side KV state: the drafter pool's owner
    object (reservation/rollback accounting hangs off its identity)."""

    __slots__ = ("slot", "n_cached")

    def __init__(self, slot):
        self.slot = int(slot)
        self.n_cached = 0


class ModelDrafter:
    """The pluggable draft-model hook: greedy-decode continuation
    tokens from a (smaller) :class:`GenerationModel` over each
    sequence's committed history.

    ``propose(history, k)`` is the PR-12 host-side oracle path
    (``reference_decode`` — exact, unbatched, the API the original
    tests pin). The production fast path is ``propose_batch`` /
    ``propose_tree_batch``: the draft model runs as its OWN tiny jitted
    steps batched across all occupied rows — catch-up prefill chunks
    (``make_prefill_step`` with ``return_logits``) bring each row's
    draft KV level with its committed history, then ONE fused
    ``make_draft_step`` scan drafts the whole chain on device. Draft KV
    lives in the drafter's own :class:`~.kv_cache.KVBlockPool` slice
    and every window ends with the same reservation-restoring
    ``truncate_owner`` rollback the target cache uses, so speculative
    draft state can never leak blocks (``pool.check_invariants`` is
    clean at every window boundary). Drafting with the TARGET model
    itself yields perfect acceptance, which is what the tests pin.

    ``draft_steps`` counts jitted draft-side dispatches (catch-up
    chunks + fused scans) — the bench's draft-cost accounting."""

    def __init__(self, model, block_size=16, chunk=None):
        if not isinstance(model, GenerationModel):
            raise TypeError("ModelDrafter needs a GenerationModel, got "
                            "%r" % (type(model).__name__,))
        self.model = model
        self.draft_steps = 0
        self._block_size = int(block_size)
        self._chunk = chunk
        self._pool = None
        self._tables = None
        self._max_batch = 0
        self._n_new = 0
        self._mb = 0
        self._states = {}       # seq_id -> _DraftSeq
        self._free_slots = []

    # -- PR-12 host oracle path (API-compatible) ----------------------------
    def propose(self, history, k):
        k = int(k)
        hist = [int(t) for t in history]
        if k < 1 or not hist:
            return []
        if len(hist) >= self.model.config.max_seq_len:
            return []
        return reference_decode(self.model, hist, k)

    # -- jitted batched path ------------------------------------------------
    def bind(self, max_batch, max_chain):
        """Size the drafter-side geometry (the engine calls this once
        at worker construction): ``max_batch`` rows, chains up to
        ``max_chain`` tokens. Builds the drafter's own KV pool —
        ``max_batch * blocks_needed(draft max_seq_len)`` blocks, so a
        full reservation per row always succeeds and admission can
        never deadlock on draft KV. Growing an existing binding resets
        all per-sequence draft state (the next window re-prefills)."""
        from .kv_cache import KVBlockPool, blocks_needed

        max_batch = int(max_batch)
        max_chain = max(int(max_chain), 1)
        if (self._pool is not None and self._max_batch >= max_batch
                and self._n_new == max_chain - 1):
            return
        cfg = self.model.config
        if self._chunk is None:
            from .. import flags as _flags
            self._chunk = int(_flags.env("PTPU_SERVE_DRAFT_CHUNK"))
        self._chunk = max(int(self._chunk), 1)
        self._max_batch = max(max_batch, self._max_batch)
        self._n_new = max_chain - 1
        self._mb = blocks_needed(cfg.max_seq_len, self._block_size)
        self._pool = KVBlockPool(
            cfg.n_layers, cfg.n_heads, cfg.head_dim, self._block_size,
            num_blocks=self._max_batch * self._mb)
        self._tables = np.zeros((self._max_batch, self._mb), np.int32)
        self._states = {}
        self._free_slots = list(range(self._max_batch - 1, -1, -1))

    def release(self, seq_id):
        """Free a retired sequence's draft-side KV state (the
        scheduler's reap calls this)."""
        st = self._states.pop(seq_id, None)
        if st is None:
            return
        self._pool.free_owner(st)
        self._tables[st.slot, :] = 0
        self._free_slots.append(st.slot)

    def _state_for(self, seq_id):
        st = self._states.get(seq_id)
        if st is None:
            st = _DraftSeq(self._free_slots.pop())
            self._states[seq_id] = st
            # full per-row reservation up front: the drafter pool is
            # sized so this can never fail, and truncate_owner restores
            # it after every window's rollback
            self._pool.reserve(st, self._mb)
        return st

    def _alloc_span(self, st, start, stop):
        """Own (and table-map) the draft blocks covering positions
        [start, stop)."""
        from .kv_cache import blocks_needed

        have = blocks_needed(start, self._block_size)
        need = blocks_needed(stop, self._block_size)
        for b in range(have, need):
            self._tables[st.slot, b] = self._pool.alloc_block(st)

    def propose_batch(self, rows, k):
        """Draft up to ``k`` greedy continuation tokens for MANY
        sequences in a constant number of jitted draft-side steps.
        ``rows`` is ``[(seq_id, history), ...]``; returns
        ``{seq_id: [tokens...]}`` (missing/empty where a row cannot be
        drafted — at the draft model's sequence cap)."""
        got = self.propose_tree_batch(
            [(sid, hist, k) for sid, hist in rows], width=1)
        return {sid: (ch[0] if ch else []) for sid, ch in got.items()}

    def propose_tree_batch(self, rows, width):
        """Tree drafting for MANY sequences in a constant number of
        jitted steps. ``rows`` is ``[(seq_id, history, depth), ...]``;
        returns ``{seq_id: [chain0, chain1, ...]}`` — chain 0 the fused
        greedy scan (up to ``depth`` tokens), chains 1.. the top
        ``width - 1`` alternate FIRST tokens from the same catch-up
        logits (depth-1 branches: the cheap high-value part of the
        tree, no extra device steps)."""
        import jax.numpy as jnp
        from .kv_cache import blocks_needed

        width = int(width)
        out = {sid: [] for sid, _h, _d in rows}
        cfg = self.model.config
        work = []
        for sid, hist, depth in rows:
            hist = [int(t) for t in hist]
            depth = int(depth)
            if depth < 1 or not hist or len(hist) >= cfg.max_seq_len:
                continue
            work.append((sid, hist,
                         min(depth, cfg.max_seq_len - len(hist))))
        if not work:
            return out
        max_depth = max(d for _s, _h, d in work)
        if self._pool is None:
            self.bind(len(work), max_depth)
        # grow the binding when a call outruns it (direct/unit-test use;
        # the engine binds its full geometry up front so this is a
        # no-op there) — growing resets draft state, the next window
        # simply re-prefills
        new_ids = sum(1 for sid, _h, _d in work
                      if sid not in self._states)
        if (new_ids > len(self._free_slots)
                or max_depth - 1 > self._n_new):
            self.bind(max(self._max_batch,
                          len(self._states) + new_ids),
                      max(max_depth, self._n_new + 1))
        B, Mb, chunk = self._max_batch, self._mb, self._chunk
        weights = self.model.weights
        pool = self._pool

        # -- catch-up: feed history[n_cached:] through prefill chunks;
        # each row's FINAL chunk's logits give draft token 1 (argmax)
        # and the alternate branch roots (top width-1 runners-up)
        states = {}
        for sid, hist, depth in work:
            st = self._state_for(sid)
            if st.n_cached > len(hist):
                # diverged/rolled-back history: rebuild from scratch
                pool.truncate_owner(st, 0)
                self._tables[st.slot, :] = 0
                st.n_cached = 0
            states[sid] = st
        pstep = self.model.make_prefill_step(B, Mb, chunk,
                                             return_logits=True)
        final_logits = {}
        while True:
            feed = np.zeros((B, chunk), np.int32)
            lengths = np.zeros(B, np.int32)
            positions = np.zeros(B, np.int32)
            active = np.zeros(B, bool)
            finishing = []
            for sid, hist, depth in work:
                st = states[sid]
                rem = len(hist) - st.n_cached
                if rem <= 0:
                    continue
                n = min(chunk, rem)
                feed[st.slot, :n] = hist[st.n_cached:st.n_cached + n]
                lengths[st.slot] = n
                positions[st.slot] = st.n_cached
                active[st.slot] = True
                self._alloc_span(st, st.n_cached, st.n_cached + n)
                st.n_cached += n
                if st.n_cached == len(hist):
                    finishing.append(sid)
            if not active.any():
                break
            k_arr, v_arr, _nt, logits = pstep(
                weights, pool.k, pool.v, jnp.asarray(feed),
                jnp.asarray(active), jnp.zeros((B,), jnp.int32),
                jnp.asarray(positions), jnp.asarray(lengths),
                jnp.asarray(self._tables), jnp.asarray(active))
            pool.k, pool.v = k_arr, v_arr
            self.draft_steps += 1
            if finishing:
                lg = np.asarray(logits)
                for sid in finishing:
                    final_logits[sid] = lg[states[sid].slot]

        # -- branch roots from the final-chunk logits (stable argsort:
        # order[0] is exactly np.argmax, the chain-0 first token)
        first_tok = {}
        alt_tok = {}
        for sid, hist, depth in work:
            order = np.argsort(-final_logits[sid], kind="stable")
            first_tok[sid] = int(order[0])
            alt_tok[sid] = [int(t) for t in order[1:width]]

        # -- fused scan: draft chain-0 tokens 2..depth in ONE step.
        # Rows whose remaining draft span would cross the draft cache
        # cap ride inactive (their chain stays [d1]).
        scan_toks = None
        if self._n_new > 0 and any(d > 1 for _s, _h, d in work):
            first = np.zeros(B, np.int32)
            positions = np.zeros(B, np.int32)
            active = np.zeros(B, bool)
            for sid, hist, depth in work:
                st = states[sid]
                H = len(hist)
                if depth < 2 or H + self._n_new > cfg.max_seq_len:
                    continue
                first[st.slot] = first_tok[sid]
                positions[st.slot] = H
                active[st.slot] = True
                self._alloc_span(st, H, H + self._n_new)
            if active.any():
                dstep = self.model.make_draft_step(B, Mb, self._n_new)
                k_arr, v_arr, toks = dstep(
                    weights, pool.k, pool.v, jnp.asarray(first),
                    jnp.asarray(positions), jnp.asarray(self._tables),
                    jnp.asarray(active))
                pool.k, pool.v = k_arr, v_arr
                self.draft_steps += 1
                scan_toks = np.asarray(toks)
                scan_active = active
            else:
                scan_active = np.zeros(B, bool)
        else:
            scan_active = np.zeros(B, bool)

        # -- assemble chains + roll draft KV back to the committed
        # history (same truncate_owner contract as the target cache:
        # reservation restored, freed table entries re-point to null)
        for sid, hist, depth in work:
            st = states[sid]
            chain0 = [first_tok[sid]]
            if scan_active[st.slot] and scan_toks is not None:
                chain0 += [int(t) for t in scan_toks[st.slot]]
            chains = [chain0[:depth]]
            chains += [[t] for t in alt_tok[sid]]
            out[sid] = chains
            keep = blocks_needed(len(hist), self._block_size)
            dropped = pool.truncate_owner(st, keep)
            if dropped:
                self._tables[st.slot, keep:keep + len(dropped)] = 0
            st.n_cached = len(hist)
        return out


# ---------------------------------------------------------------------------
# unbatched, unpaged reference decoder (the correctness oracle)
# ---------------------------------------------------------------------------


def reference_decode(model, prompt, max_new_tokens, eos_id=None):
    """Greedy-decode ONE sequence with a plain contiguous KV cache and
    full attention — no blocks, no batching, no masking tricks. The
    batched paged decode must match this token-for-token. A weight-only
    quantized model decodes over its dequantized fp32 weights (the same
    values the int8 step computes with)."""
    import jax.numpy as jnp

    cfg = model.config
    w = model.dequantized_weights() if model.weight_only_int8 \
        else model.weights
    pe = _position_encoding_table(cfg)
    emb_scale = float(cfg.d_model) ** 0.5
    H, Dh = cfg.n_heads, cfg.head_dim
    sm_scale = Dh ** -0.5

    def ln(h, scale, bias):
        mu = np.mean(h, keepdims=True)
        var = np.mean((h - mu) ** 2, keepdims=True)
        return (h - mu) / np.sqrt(var + 1e-5) * np.asarray(scale) \
            + np.asarray(bias)

    ks = [[] for _ in range(cfg.n_layers)]
    vs = [[] for _ in range(cfg.n_layers)]
    tokens = list(prompt)
    generated = []

    def one(tok, pos):
        x = (np.asarray(w["embedding"])[tok] * emb_scale * cfg.pe_alpha
             + cfg.pe_beta * pe[pos])
        for i in range(cfg.n_layers):
            p = "l%d/" % i
            a = ln(x, w[p + "ln1_scale"], w[p + "ln1_bias"])
            qkv = a @ np.asarray(w[p + "wqkv"]) + np.asarray(
                w[p + "bqkv"])
            q, k_new, v_new = np.split(qkv, 3)
            ks[i].append(k_new.reshape(H, Dh))
            vs[i].append(v_new.reshape(H, Dh))
            k_ctx = np.stack(ks[i])            # [T, H, Dh]
            v_ctx = np.stack(vs[i])
            qh = q.reshape(H, Dh)
            scores = np.einsum("hd,thd->ht", qh, k_ctx) * sm_scale
            scores = scores - scores.max(axis=-1, keepdims=True)
            wgt = np.exp(scores)
            wgt = wgt / wgt.sum(axis=-1, keepdims=True)
            ctx = np.einsum("ht,thd->hd", wgt, v_ctx).reshape(-1)
            x = x + ctx @ np.asarray(w[p + "wproj"]) + np.asarray(
                w[p + "bproj"])
            b2 = ln(x, w[p + "ln2_scale"], w[p + "ln2_bias"])
            h = b2 @ np.asarray(w[p + "wff1"]) + np.asarray(w[p + "bff1"])
            # exact (erf) gelu, matching jax.nn.gelu(approximate=False)
            h = h * 0.5 * (1.0 + np.vectorize(math.erf)(
                h / np.sqrt(2.0)))
            x = x + h @ np.asarray(w[p + "wff2"]) + np.asarray(
                w[p + "bff2"])
        x = ln(x, w["final_ln_scale"], w["final_ln_bias"])
        logits = x @ np.asarray(w["lm_head"])
        return int(np.argmax(logits))

    nxt = None
    for pos, tok in enumerate(tokens):
        nxt = one(tok, pos)
    pos = len(tokens)
    while len(generated) < max_new_tokens and pos < cfg.max_seq_len:
        generated.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
        nxt = one(generated[-1], pos)
        pos += 1
    return generated
