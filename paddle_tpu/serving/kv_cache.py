"""Blocked (paged) KV-cache pool for the generation serving runtime
(vLLM SOSP '23 PagedAttention, mapped onto the framework's fixed-shape
decode step) — with content-addressed **radix prefix caching** (SGLang
RadixAttention mapped onto flat block tables).

The device side is two dense arrays per model —
``k``/``v`` of shape ``[n_layers, num_blocks, block_size, n_heads,
head_dim]`` — that the jitted decode step takes as donated arguments and
returns updated, so the pool never round-trips over the host link. A
sequence's cache is NOT contiguous: it owns an ordered list of block ids
(its *block table*), and the decode step gathers
``k[layer][block_table]`` to reconstruct the sequence's logical
``[max_seq_len]`` key/value layout. Fixed shapes everywhere means XLA
compiles the step exactly once no matter how sequences join and retire.

Block 0 is the *null block*: it is never allocated, every unused
block-table entry points at it, and inactive batch slots route their
(masked-out) cache writes into it — so scatter/gather indices are always
in range without per-slot branches in the compiled step.

Allocation is host-side and two-phase:

  * ``reserve(n)`` at admission: the scheduler reserves the worst-case
    block count for a request (``ceil((prompt + max_new) / block_size)``)
    before it joins the batch. Admission control — a request only enters
    the batch when its whole reservation fits, so the pool can never be
    exhausted mid-decode and no preemption/swap path is needed.
  * ``alloc_block(owner)`` per crossing: physical ids are handed out
    lazily as the sequence's position crosses a block boundary, drawn
    from the reservation made at admit time.

``free_owner`` returns a retired sequence's blocks and releases any
unused remainder of its reservation. ``truncate_owner`` is the
speculative-decoding **rollback** path (docs/SERVING.md): rejected
draft positions wrote KV into over-allocated tail blocks, and
truncation hands them back while growing the owner's reservation by
the same count — the exact inverse of ``alloc_block``, so the
two-phase invariant survives rewinds.

Prefix caching (docs/SERVING.md) makes the pool *content-addressed*:

  * Every block is refcounted. A FULL block whose contents are a known
    prompt span can be *sealed* into the content index under a
    chain-hash key (:func:`prefix_chain_keys`: key ``i`` commits to the
    namespace — the model — plus every token of blocks ``0..i``, so
    equal keys imply an identical prompt prefix AND an identical chain
    of predecessor blocks).
  * ``reserve(owner, n, prefix_keys=...)`` adopts the longest sealed
    run of the caller's prefix keys: matched blocks join the new
    owner's table with a refcount bump, and only the remainder of the
    worst case is actually reserved — the admission gate shrinks by
    exactly the shared span.
  * A shared block is returned to circulation only when its refcount
    hits zero; sealed blocks then park on an LRU *cached* list instead
    of the free list, still indexed, so a later identical prefix can
    revive them without recomputation. ``alloc_block`` evicts from the
    LRU (dropping the index entry) only once the free list is empty.

Reservation conservation survives sharing (pinned by test):
``blocks_free(+cached) - reserved >= 0`` at every point, and
``free + cached + owned + shared == total`` — reviving a cached block
during adoption is charged against availability exactly like an
allocation, so outstanding reservations can never be left unbacked
(the two-phase no-deadlock invariant).
"""

import hashlib
from collections import OrderedDict

import numpy as np

__all__ = ["KVBlockPool", "blocks_needed", "prefix_chain_keys"]


def blocks_needed(num_tokens, block_size):
    """Blocks required to hold ``num_tokens`` cache slots."""
    if num_tokens <= 0:
        return 0
    return -(-int(num_tokens) // int(block_size))


def prefix_chain_keys(token_ids, block_size, namespace=""):
    """Content-addressed keys for every FULL block of ``token_ids``.

    ``key[i]`` is a hash chain committing to ``namespace`` (the model),
    ``key[i-1]`` and block ``i``'s token content — two requests share
    ``key[i]`` iff their first ``(i + 1) * block_size`` tokens are
    identical under the same namespace. Returns
    ``len(token_ids) // block_size`` hex digests (the trailing partial
    block, whose content a future decode would extend, is never keyed).
    """
    bs = int(block_size)
    h = hashlib.sha1(("ptpu-prefix:%s" % namespace).encode()).hexdigest()
    out = []
    for i in range(len(token_ids) // bs):
        blk = token_ids[i * bs:(i + 1) * bs]
        h = hashlib.sha1(
            (h + ":" + ",".join(str(int(t)) for t in blk)).encode()
        ).hexdigest()
        out.append(h)
    return out


class KVBlockPool:
    """Fixed-size-block KV cache pool with refcounted per-owner block
    accounting and an optional content-addressed prefix index.

    ``num_blocks`` counts usable blocks; one extra null block (id 0) is
    added on top, so the device arrays hold ``num_blocks + 1`` blocks.
    """

    NULL_BLOCK = 0

    def __init__(self, n_layers, n_heads, head_dim, block_size,
                 num_blocks, dtype="float32", device=None):
        if num_blocks < 1:
            raise ValueError("KVBlockPool needs at least one usable block")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = np.dtype(dtype)

        import jax.numpy as jnp

        shape = (self.n_layers, self.num_blocks + 1, self.block_size,
                 self.n_heads, self.head_dim)
        if device is not None:
            import jax

            with jax.default_device(device):
                self.k = jnp.zeros(shape, self.dtype)
                self.v = jnp.zeros(shape, self.dtype)
        else:
            self.k = jnp.zeros(shape, self.dtype)
            self.v = jnp.zeros(shape, self.dtype)

        from ..analysis.concurrency import make_lock

        self._lock = make_lock("serving.kv_pool")
        # LIFO free list: a retired sequence's blocks are handed to the
        # next admit while still warm in cache
        self._free = list(range(self.num_blocks, 0, -1))
        self._reserved = {}      # owner -> blocks still reservable
        self._owned = {}         # owner -> [block ids], table order
        # owner -> reserved + owned ceiling, fixed at reserve() time:
        # alloc_block moves one unit reserved->owned, truncate_owner
        # moves it back, so the sum is invariant until free_owner —
        # check_invariants pins it (the rollback accounting audit)
        self._reserve_ceiling = {}
        # -- content-addressed prefix state -----------------------------
        self._refs = {}          # bid -> refcount (>= 1 while in a table)
        self._sealed = {}        # content key -> bid
        self._block_key = {}     # bid -> content key (sealed blocks)
        # refcount-0 sealed blocks, oldest-freed first (the LRU evictees)
        self._cached = OrderedDict()   # bid -> content key
        # cumulative rollback accounting (speculative decoding's
        # truncate path — surfaced in stats() so the drafter-pool and
        # target-pool rollback volume is auditable per pool)
        self.truncate_calls = 0
        self.blocks_truncated = 0

    # -- accounting ----------------------------------------------------
    @property
    def blocks_total(self):
        return self.num_blocks

    @property
    def blocks_free(self):
        """Blocks reclaimable for a new reservation: truly free plus
        refcount-zero cached prefix blocks, minus what reservations
        already spoke for."""
        with self._lock:
            return (len(self._free) + len(self._cached)
                    - sum(self._reserved.values()))

    @property
    def blocks_in_use(self):
        """Unique blocks referenced by at least one owner's table."""
        with self._lock:
            return len(self._refs)

    @property
    def blocks_cached(self):
        """Refcount-zero sealed blocks kept for prefix reuse."""
        with self._lock:
            return len(self._cached)

    def stats(self):
        with self._lock:
            free = len(self._free)
            cached = len(self._cached)
            reserved = sum(self._reserved.values())
            owned = sum(1 for r in self._refs.values() if r == 1)
            shared = len(self._refs) - owned
        return {
            "blocks_total": self.num_blocks,
            "blocks_in_use": owned + shared,
            "blocks_owned": owned,
            "blocks_shared": shared,
            "blocks_cached": cached,
            "blocks_reserved": reserved,
            "blocks_free": free + cached - reserved,
            "utilization": (owned + shared) / self.num_blocks,
            "truncate_calls": self.truncate_calls,
            "blocks_truncated": self.blocks_truncated,
        }

    # -- admission-side API --------------------------------------------
    def can_reserve(self, n):
        return self.blocks_free >= int(n)

    def reserve(self, owner, n, prefix_keys=None):
        """Reserve ``n`` worst-case blocks for ``owner``. Returns False
        (reserving nothing) when the pool cannot cover the reservation —
        the scheduler's admission check.

        With ``prefix_keys`` (the prompt's :func:`prefix_chain_keys`),
        the longest sealed run is adopted first: matched blocks join the
        owner's table (``block_table(owner)``) with a refcount bump and
        only ``n - matched`` blocks are actually reserved. Reviving a
        refcount-zero cached block is charged against availability like
        an allocation, so reservations already outstanding stay backed.
        """
        n = int(n)
        with self._lock:
            if owner in self._reserved or owner in self._owned:
                raise ValueError("owner %r already holds a reservation"
                                 % (owner,))
            matched = []
            if prefix_keys:
                for key in prefix_keys:
                    bid = self._sealed.get(key)
                    if bid is None:
                        break
                    matched.append(bid)
            revive = sum(1 for bid in matched
                         if self._refs.get(bid, 0) == 0)
            need = max(n - len(matched), 0)
            avail = (len(self._free) + len(self._cached)
                     - sum(self._reserved.values()))
            if avail < need + revive:
                return False
            for bid in matched:
                r = self._refs.get(bid, 0)
                if r == 0:
                    self._cached.pop(bid, None)  # revive from the LRU
                self._refs[bid] = r + 1
            self._reserved[owner] = need
            self._owned[owner] = list(matched)
            self._reserve_ceiling[owner] = need + len(matched)
            return True

    def alloc_block(self, owner):
        """Hand one physical block id to ``owner``, drawn from its
        reservation (appends to the owner's block table). Evicts the
        least-recently-freed cached prefix block when the free list is
        empty (its content-index entry is dropped)."""
        with self._lock:
            if self._reserved.get(owner, 0) <= 0:
                raise RuntimeError(
                    "owner %r has no remaining reservation — the "
                    "scheduler must reserve the worst-case block count "
                    "at admission" % (owner,))
            if self._free:
                bid = self._free.pop()
            else:
                bid, key = self._cached.popitem(last=False)
                del self._sealed[key]
                del self._block_key[bid]
            self._reserved[owner] -= 1
            self._refs[bid] = 1
            self._owned[owner].append(bid)
            return bid

    def block_table(self, owner):
        with self._lock:
            return list(self._owned.get(owner, ()))

    def free_owner(self, owner):
        """Drop ``owner``'s references and release the unused part of
        its reservation. A block returns to circulation only at
        refcount zero: sealed blocks park on the cached LRU (still
        prefix-matchable), unsealed ones go back to the free list.
        Parking walks the table in REVERSE order so eviction consumes a
        chain tail-first — the longest-prefix-match walks head-first,
        so evicting the head would strand every still-cached successor
        as unmatchable dead index entries. Idempotent. Returns the
        number of blocks the owner's table held."""
        with self._lock:
            blocks = self._owned.pop(owner, [])
            self._reserved.pop(owner, None)
            self._reserve_ceiling.pop(owner, None)
            for bid in reversed(blocks):
                r = self._refs.get(bid, 0) - 1
                if r > 0:
                    self._refs[bid] = r
                    continue
                self._refs.pop(bid, None)
                key = self._block_key.get(bid)
                if key is not None:
                    self._cached[bid] = key
                    self._cached.move_to_end(bid)
                else:
                    self._free.append(bid)
            return len(blocks)

    def truncate_owner(self, owner, n_keep):
        """Rewind ``owner``'s block table to its first ``n_keep``
        entries — the KV **rollback** path of speculative decoding
        (docs/SERVING.md): positions written for rejected draft tokens
        live in over-allocated tail blocks, and this returns them.

        Each dropped block leaves the table, clears its refcount, and
        goes back to the free list while the owner's RESERVATION grows
        back by one — the exact inverse of ``alloc_block``, so the
        two-phase no-deadlock invariant is preserved and the rewound
        sequence re-crosses the same block boundaries without needing
        a new reservation. Only unshared (refcount 1), unsealed tail
        blocks may be truncated; a sealed or adopted prefix block can
        never sit past a rollback point (the scheduler only rewinds
        decode-phase positions), so hitting one raises rather than
        corrupting the content index. Returns the dropped block ids in
        table order."""
        n_keep = int(n_keep)
        if n_keep < 0:
            raise ValueError("n_keep must be >= 0, got %d" % n_keep)
        with self._lock:
            blocks = self._owned.get(owner)
            if blocks is None:
                raise KeyError("owner %r holds no block table" % (owner,))
            if n_keep >= len(blocks):
                return []
            dropped = blocks[n_keep:]
            for bid in dropped:
                if self._refs.get(bid, 0) != 1:
                    raise RuntimeError(
                        "refusing to truncate block %d with refcount %d "
                        "— shared blocks are never rolled back"
                        % (bid, self._refs.get(bid, 0)))
                if bid in self._block_key:
                    raise RuntimeError(
                        "refusing to truncate sealed block %d (key %s..)"
                        " — cached prefix blocks are never rolled back"
                        % (bid, self._block_key[bid][:8]))
            del blocks[n_keep:]
            # reversed: the shallowest dropped block lands last on the
            # LIFO free list, so re-crossing the same boundary hands
            # the SAME (cache-warm) block back first
            for bid in reversed(dropped):
                del self._refs[bid]
                self._free.append(bid)
            self._reserved[owner] = (self._reserved.get(owner, 0)
                                     + len(dropped))
            self.truncate_calls += 1
            self.blocks_truncated += len(dropped)
            return list(dropped)

    # -- runtime invariants (docs/STATIC_ANALYSIS.md, PTPU_LOCK_CHECK) -
    def check_invariants(self):
        """Audit the pool's accounting in one consistent snapshot and
        return a list of problem strings (empty = clean). The serving
        engine calls this at step boundaries under ``PTPU_LOCK_CHECK=1``
        and reports findings as ``pool-invariant`` violations; the pins:

          * conservation: ``free + cached + in-table == total`` (the
            ``free+reserved+owned+shared==total`` identity of stats(),
            with reservations counted against availability)
          * every referenced block has refcount >= 1, reservations are
            never negative, and outstanding reservations stay backed
            (``free + cached - reserved >= 0`` — the two-phase
            no-deadlock invariant)
          * LRU/index consistency: sealed index and reverse map agree,
            cached blocks are exactly the refcount-zero sealed ones,
            the null block never circulates, and no block id appears
            twice across free/cached/tables
          * rollback accounting (speculative decoding's truncate path):
            every owner's ``reserved + owned`` still equals the ceiling
            fixed at ``reserve()`` time (``alloc_block`` moves a unit
            one way, ``truncate_owner`` moves it back), and no
            free-list block retains a content-index entry (a truncated
            or flushed block must leave the index)
        """
        problems = []
        with self._lock:
            free = list(self._free)
            cached = list(self._cached)
            refs = dict(self._refs)
            reserved = dict(self._reserved)
            owned = {o: list(b) for o, b in self._owned.items()}
            sealed = dict(self._sealed)
            block_key = dict(self._block_key)
            ceilings = dict(self._reserve_ceiling)
        n_free, n_cached, n_tab = len(free), len(cached), len(refs)
        if n_free + n_cached + n_tab != self.num_blocks:
            problems.append(
                "conservation broken: free %d + cached %d + in-table %d "
                "!= total %d" % (n_free, n_cached, n_tab,
                                 self.num_blocks))
        for bid, r in refs.items():
            if r < 1:
                problems.append("block %d referenced with refcount %d"
                                % (bid, r))
        for owner, n in reserved.items():
            if n < 0:
                problems.append("owner %r reservation went negative (%d)"
                                % (owner, n))
        n_reserved = sum(max(n, 0) for n in reserved.values())
        if n_free + n_cached < n_reserved:
            problems.append(
                "reservations unbacked: free %d + cached %d < reserved "
                "%d" % (n_free, n_cached, n_reserved))
        for key, bid in sealed.items():
            if block_key.get(bid) != key:
                problems.append(
                    "sealed index maps key %s.. to block %d but the "
                    "block's key is %r" % (key[:8], bid,
                                           block_key.get(bid)))
        for bid, key in block_key.items():
            if sealed.get(key) != bid:
                problems.append(
                    "block %d keyed %s.. missing from the sealed index"
                    % (bid, key[:8]))
        for bid in cached:
            if bid in refs:
                problems.append("cached block %d is also referenced "
                                "(refcount %d)" % (bid, refs[bid]))
            if bid not in block_key:
                problems.append("cached block %d lost its index entry"
                                % bid)
        seen = {}
        for where, ids in (("free", free), ("cached", cached)):
            for bid in ids:
                if bid == self.NULL_BLOCK:
                    problems.append("null block circulating on the %s "
                                    "list" % where)
                if bid in seen:
                    problems.append("block %d on both %s and %s"
                                    % (bid, seen[bid], where))
                seen[bid] = where
        for owner, blocks in owned.items():
            for bid in blocks:
                if refs.get(bid, 0) < 1:
                    problems.append(
                        "owner %r table references block %d with no "
                        "refcount" % (owner, bid))
                if bid in seen:
                    problems.append("block %d in a table but also on "
                                    "the %s list" % (bid, seen[bid]))
        # rollback accounting: reserve()'s ceiling is conserved across
        # alloc_block/truncate_owner round trips
        for owner, blocks in owned.items():
            ceiling = ceilings.get(owner)
            have = reserved.get(owner, 0) + len(blocks)
            if ceiling is None:
                problems.append("owner %r holds a table but no "
                                "reservation ceiling" % (owner,))
            elif have != ceiling:
                problems.append(
                    "owner %r reserved %d + owned %d != reservation "
                    "ceiling %d (truncate/alloc accounting drift)"
                    % (owner, reserved.get(owner, 0), len(blocks),
                       ceiling))
        for bid in free:
            if bid in block_key:
                problems.append(
                    "free-list block %d still carries content-index "
                    "key %s.. (truncated/flushed blocks must leave "
                    "the index)" % (bid, block_key[bid][:8]))
        return problems

    # -- content index (radix prefix caching) --------------------------
    def seal_block(self, bid, key):
        """Register a FULL, fully-written prompt block in the content
        index so later ``reserve(prefix_keys=...)`` calls can adopt it.
        Only live (refcount >= 1) non-null blocks are sealable; the
        first sealer of a key wins (a concurrent identical prefill just
        keeps its private copy). Returns True when ``bid`` is the
        canonical block for ``key``."""
        bid = int(bid)
        with self._lock:
            if bid == self.NULL_BLOCK or self._refs.get(bid, 0) < 1:
                return False
            if bid in self._block_key:
                return self._block_key[bid] == key
            if key in self._sealed:
                return False
            self._sealed[key] = bid
            self._block_key[bid] = key
            return True

    def lookup_prefix(self, prefix_keys):
        """Longest sealed run of ``prefix_keys`` currently adoptable
        (diagnostic; admission uses the atomic ``reserve``)."""
        with self._lock:
            out = []
            for key in prefix_keys:
                bid = self._sealed.get(key)
                if bid is None:
                    break
                out.append(bid)
            return out

    def flush_prefix_cache(self):
        """Drop the whole content index (after a weight hot-swap —
        cached KV state is only valid for the weights that computed it;
        ``ServingEngine.swap_weights`` calls this in the same critical
        section that installs the new weights, so a stale prefix can
        never serve a post-swap request). Referenced blocks stay in
        their owners' tables but lose their index entry; cached blocks
        return to the free list. Returns the number of index entries
        dropped."""
        from ..observability import metrics as _metrics

        with self._lock:
            dropped = len(self._sealed)
            self._free.extend(self._cached)
            self._cached.clear()
            self._sealed.clear()
            self._block_key.clear()
        _metrics.counter("serving/prefix_cache_flushes").inc()
        return dropped
