"""Blocked (paged) KV-cache pool for the generation serving runtime
(vLLM SOSP '23 PagedAttention, mapped onto the framework's fixed-shape
decode step).

The device side is two dense arrays per model —
``k``/``v`` of shape ``[n_layers, num_blocks, block_size, n_heads,
head_dim]`` — that the jitted decode step takes as donated arguments and
returns updated, so the pool never round-trips over the host link. A
sequence's cache is NOT contiguous: it owns an ordered list of block ids
(its *block table*), and the decode step gathers
``k[layer][block_table]`` to reconstruct the sequence's logical
``[max_seq_len]`` key/value layout. Fixed shapes everywhere means XLA
compiles the step exactly once no matter how sequences join and retire.

Block 0 is the *null block*: it is never allocated, every unused
block-table entry points at it, and inactive batch slots route their
(masked-out) cache writes into it — so scatter/gather indices are always
in range without per-slot branches in the compiled step.

Allocation is host-side and two-phase:

  * ``reserve(n)`` at admission: the scheduler reserves the worst-case
    block count for a request (``ceil((prompt + max_new) / block_size)``)
    before it joins the batch. Admission control — a request only enters
    the batch when its whole reservation fits, so the pool can never be
    exhausted mid-decode and no preemption/swap path is needed.
  * ``alloc_block(owner)`` per crossing: physical ids are handed out
    lazily as the sequence's position crosses a block boundary, drawn
    from the reservation made at admit time.

``free_owner`` returns a retired sequence's blocks to the free list and
releases any unused remainder of its reservation.
"""

import threading

import numpy as np

__all__ = ["KVBlockPool", "blocks_needed"]


def blocks_needed(num_tokens, block_size):
    """Blocks required to hold ``num_tokens`` cache slots."""
    if num_tokens <= 0:
        return 0
    return -(-int(num_tokens) // int(block_size))


class KVBlockPool:
    """Fixed-size-block KV cache pool with per-owner block accounting.

    ``num_blocks`` counts usable blocks; one extra null block (id 0) is
    added on top, so the device arrays hold ``num_blocks + 1`` blocks.
    """

    NULL_BLOCK = 0

    def __init__(self, n_layers, n_heads, head_dim, block_size,
                 num_blocks, dtype="float32", device=None):
        if num_blocks < 1:
            raise ValueError("KVBlockPool needs at least one usable block")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = np.dtype(dtype)

        import jax.numpy as jnp

        shape = (self.n_layers, self.num_blocks + 1, self.block_size,
                 self.n_heads, self.head_dim)
        if device is not None:
            import jax

            with jax.default_device(device):
                self.k = jnp.zeros(shape, self.dtype)
                self.v = jnp.zeros(shape, self.dtype)
        else:
            self.k = jnp.zeros(shape, self.dtype)
            self.v = jnp.zeros(shape, self.dtype)

        self._lock = threading.Lock()
        # LIFO free list: a retired sequence's blocks are handed to the
        # next admit while still warm in cache
        self._free = list(range(self.num_blocks, 0, -1))
        self._reserved = {}      # owner -> blocks still reservable
        self._owned = {}         # owner -> [block ids], table order

    # -- accounting ----------------------------------------------------
    @property
    def blocks_total(self):
        return self.num_blocks

    @property
    def blocks_free(self):
        """Blocks neither allocated nor spoken for by a reservation."""
        with self._lock:
            return len(self._free) - sum(self._reserved.values())

    @property
    def blocks_in_use(self):
        with self._lock:
            return self.num_blocks - len(self._free)

    def stats(self):
        with self._lock:
            free = len(self._free)
            reserved = sum(self._reserved.values())
        return {
            "blocks_total": self.num_blocks,
            "blocks_in_use": self.num_blocks - free,
            "blocks_reserved": reserved,
            "blocks_free": free - reserved,
            "utilization": (self.num_blocks - free) / self.num_blocks,
        }

    # -- admission-side API --------------------------------------------
    def can_reserve(self, n):
        return self.blocks_free >= int(n)

    def reserve(self, owner, n):
        """Reserve ``n`` blocks for ``owner``. Returns False (reserving
        nothing) when the pool cannot cover the reservation — the
        scheduler's admission check."""
        n = int(n)
        with self._lock:
            if owner in self._reserved or owner in self._owned:
                raise ValueError("owner %r already holds a reservation"
                                 % (owner,))
            if len(self._free) - sum(self._reserved.values()) < n:
                return False
            self._reserved[owner] = n
            self._owned[owner] = []
            return True

    def alloc_block(self, owner):
        """Hand one physical block id to ``owner``, drawn from its
        reservation (appends to the owner's block table)."""
        with self._lock:
            if self._reserved.get(owner, 0) <= 0:
                raise RuntimeError(
                    "owner %r has no remaining reservation — the "
                    "scheduler must reserve the worst-case block count "
                    "at admission" % (owner,))
            bid = self._free.pop()
            self._reserved[owner] -= 1
            self._owned[owner].append(bid)
            return bid

    def block_table(self, owner):
        with self._lock:
            return list(self._owned.get(owner, ()))

    def free_owner(self, owner):
        """Return all of ``owner``'s blocks and release the unused part
        of its reservation. Idempotent."""
        with self._lock:
            blocks = self._owned.pop(owner, [])
            self._reserved.pop(owner, None)
            self._free.extend(blocks)
            return len(blocks)
