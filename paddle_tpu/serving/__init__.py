"""`paddle_tpu.serving` — continuous-batching generation serving runtime
(docs/SERVING.md).

The "millions of users" leg of the north star: a multi-model generation
service that batches concurrent requests at decode-*step* granularity
(Orca-style iteration-level scheduling over one fixed-shape XLA step, so
joins/retires never retrace) with a blocked KV-cache pool (vLLM-style
block tables) for memory feasibility. The opt-in serving fast path adds
chunked prefill (Sarathi-style mixed prompt-window/decode steps,
``prefill_chunk=`` / ``$PTPU_SERVE_PREFILL_CHUNK``) and radix prefix
caching (content-addressed refcounted KV block sharing across requests,
``prefix_cache=`` / ``$PTPU_SERVE_PREFIX_CACHE``) and speculative
decoding (draft-k tokens — n-gram prompt lookup by default, or a
pluggable draft model — verified in one batched target step,
``spec_k=`` / ``$PTPU_SERVE_SPEC_K``). ``native_serve`` remains the
Python-free deployment backend for the same exported artifact
directory.

    from paddle_tpu import serving
    engine = serving.ServingEngine(serving.GenerationModel.random(cfg))
    req = engine.submit([1, 2, 3], max_new_tokens=16)
    tokens = engine.result(req)
"""

from .engine import ServingEngine  # noqa: F401
from .kv_cache import (KVBlockPool, blocks_needed,  # noqa: F401
                       prefix_chain_keys)
from .loadgen import PoissonLoadGenerator  # noqa: F401
from .model import (GenerationArtifactError,  # noqa: F401
                    GenerationConfig, GenerationModel,
                    ModelDrafter, NGramDrafter,
                    extract_decoder_weights, load_generation_artifact,
                    parse_tree_shape, random_weights, reference_decode,
                    save_generation_artifact, tree_topology,
                    verify_generation_artifact)
from .online import CanaryGate, OnlineUpdater  # noqa: F401
from .router import RouterRequest, ServingRouter  # noqa: F401
from .scheduler import (AdmissionError,  # noqa: F401
                        DeadlineExceededError, GenerationRequest,
                        RequestQueue, StepScheduler,
                        spec_tree_acceptance)

__all__ = ["ServingEngine", "ServingRouter", "RouterRequest",
           "KVBlockPool", "blocks_needed", "prefix_chain_keys",
           "PoissonLoadGenerator", "GenerationConfig", "GenerationModel",
           "GenerationArtifactError", "ModelDrafter", "NGramDrafter",
           "extract_decoder_weights", "load_generation_artifact",
           "parse_tree_shape", "random_weights", "reference_decode",
           "save_generation_artifact", "tree_topology",
           "verify_generation_artifact",
           "OnlineUpdater", "CanaryGate",
           "spec_tree_acceptance", "AdmissionError",
           "DeadlineExceededError", "GenerationRequest", "RequestQueue",
           "StepScheduler"]
