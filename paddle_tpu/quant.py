"""Post-training int8 quantized inference (parity: the contrib/slim +
contrib/quantize deployment toolkit, SURVEY §2 — `QuantizeTranspiler`
gave Fluid its int8-deploy shape; here the same capability is a
COMPILE-TIME rewrite riding the PR-3 pass pipeline, exactly the way the
PR-5 `amp_rewrite` pass carries bf16 training).

Workflow (docs/QUANTIZATION.md):

  1. **Calibrate** — ``calibrate(program, sample_feeds,
     strategy='abs_max'|'percentile')`` runs the fp32 program over a
     small representative feed set and collects per-tensor activation
     ranges (per-CHANNEL ranges for the persistable weights, read
     straight from the scope) into a serializable
     :class:`CalibrationTable`.
  2. **Rewrite** — the ``quant_rewrite`` pass (registered in
     `fluid.ir`'s registry, scheduled by the default pipeline right
     after `amp_rewrite`'s slot) rewrites each white-list op
     (mul/matmul/conv2d family) on the compile clone:

       full_int8    quantize(activation, scale from the table) -> int8
                    dot/conv accumulating in int32
                    (``preferred_element_type=int32`` — the op carries
                    ``__quant_int8__``) -> ``dequantize_linear`` back to
                    fp32 with the combined per-channel scale
       weight_only  the weight is STORED int8 (baked as a fresh
                    content-addressed persistable scope entry via the
                    PR-3 baking machinery) and a ``dequantize_linear``
                    reconstructs the fp32 weight on use — the compute
                    stays fp32; the win is the halved-or-better weight
                    store, which is what memory-bandwidth-bound decode
                    monetizes.

     Grad-referenced ops, optimizer ops, structural ops, non-fp32
     operands and black-listed names are never rewritten; the original
     fp32 weight vars simply stop being read, so the compiled step's
     device weight store shrinks while the user's program and scope stay
     untouched (the non-destructive compile-clone contract).
  3. **Deploy** — ``AnalysisConfig.enable_quantize(...)`` quantizes at
     predictor load (weight_only rides
     ``QuantizeTranspiler.convert_to_int8``'s genuinely halved scope
     store; full_int8 decorates the loaded program for this pass), and
     ``serving.GenerationModel.quantized()`` is the weight-only-int8
     decode-step variant for the continuous-batching engine.

Activation: ``decorate(program, ...)`` pins a :class:`QuantConfig` on
the program; ``PTPU_QUANT=1`` activates a process-wide default
(``PTPU_QUANT_MODE``, ``PTPU_QUANT_TABLE``, ``PTPU_QUANT_BLACKLIST``).
With both unset the pass pipeline, the compile-cache keys and every
lowered program are BITWISE identical to the pre-quant framework
(pinned by tests/test_quant.py, the AMP-off invariance pattern).

Telemetry: ``quant/{ops_rewritten,weights_quantized,calib_tensors,
weight_bytes_saved,weight_fp32_bytes}`` (docs/OBSERVABILITY.md).
"""

import hashlib
import json
import os

import numpy as np

from .flags import env as _env
from .ir import Pass, register_pass
from .observability import metrics as _metrics

__all__ = [
    "CalibrationTable", "QuantConfig", "calibrate", "decorate",
    "active_config", "quant_env_enabled", "weight_channel_scales",
    "quantize_to_int8", "quantize_symmetric", "weight_store_bytes",
    "quantize_predictor_program", "DEFAULT_QUANT_OPS",
]

# white list: MXU-dot ops whose persistable weight operand can store int8
DEFAULT_QUANT_OPS = frozenset({
    "mul", "matmul", "conv2d", "depthwise_conv2d",
})

# per-op-type slot layout: (activation slot, weight slot)
_SLOTS = {
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
}

_QMAX = 127.0        # symmetric int8 grid (reference weight_bits=8)
_EPS = 1e-8

MODES = ("weight_only", "full_int8")


def _kernel_enabled(name):
    """Emission-time dispatch policy for the fused Pallas kernels
    (ops/kernel_registry.enabled_for): mode + platform only — shape
    qualification happens at trace time inside the emitted op. The
    kernel mode rides the pipeline cache key (ir_passes.pipeline_key),
    so a program rewritten under one policy never serves another."""
    from .ops.kernel_registry import enabled_for

    return enabled_for(name)


def _check_ops(ops):
    """Validate a user-supplied quantizable-op set against the known
    slot layouts — a typo'd op type fails here with the supported list,
    not as a KeyError deep inside the pass."""
    ops = frozenset(ops)
    unknown = ops - frozenset(_SLOTS)
    if unknown:
        raise ValueError(
            "unsupported quantizable op type(s) %s — supported: %s"
            % (sorted(unknown), sorted(_SLOTS)))
    return ops


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------


class CalibrationTable:
    """Serializable per-tensor ranges: ``acts`` maps an activation var
    name to its scalar range (abs-max or percentile of |x| over the
    calibration feeds — the value `s` such that the int8 grid spans
    [-s, s]); ``weights`` maps a weight var name to its per-output-
    channel ranges plus the channel axis. JSON round-trips via
    save/load."""

    def __init__(self, acts=None, weights=None, strategy="abs_max",
                 percentile=None):
        self.acts = {str(k): float(v) for k, v in (acts or {}).items()}
        self.weights = {str(k): {"scales": [float(s) for s in v["scales"]],
                                 "axis": int(v["axis"])}
                        for k, v in (weights or {}).items()}
        self.strategy = strategy
        self.percentile = percentile
        self._digest = None

    def act_scale(self, name):
        return self.acts.get(name)

    def weight_scales(self, name):
        w = self.weights.get(name)
        return None if w is None else (np.asarray(w["scales"], np.float32),
                                       w["axis"])

    def to_dict(self):
        return {"strategy": self.strategy, "percentile": self.percentile,
                "acts": self.acts, "weights": self.weights}

    @classmethod
    def from_dict(cls, d):
        return cls(acts=d.get("acts"), weights=d.get("weights"),
                   strategy=d.get("strategy", "abs_max"),
                   percentile=d.get("percentile"))

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def digest(self):
        # memoized: digest() sits on the per-compile cache-key path
        # (pipeline_key), and a table is immutable once handed to a
        # QuantConfig
        if self._digest is None:
            h = hashlib.sha1()
            h.update(repr((
                self.strategy, self.percentile,
                sorted(self.acts.items()),
                sorted((k, tuple(v["scales"]), v["axis"])
                       for k, v in self.weights.items()))).encode())
            self._digest = h.hexdigest()[:10]
        return self._digest


def record_weight_store(n_weights, saved_bytes, fp32_bytes):
    """The one emitter for the weight-store telemetry triple — the
    rewrite pass, convert_to_int8 and GenerationModel.quantized() all
    report through here (docs/OBSERVABILITY.md)."""
    _metrics.counter("quant/weights_quantized").inc(n_weights)
    _metrics.counter("quant/weight_bytes_saved").inc(saved_bytes)
    _metrics.counter("quant/weight_fp32_bytes").inc(fp32_bytes)


def weight_store_bytes(weights):
    """Byte accounting for a (possibly int8) weight dict: ``n_int8``
    int8-stored entries, ``int8_bytes`` they occupy (int8 payload plus
    their fp32 ``@qscale`` companions) and ``fp32_bytes`` the same
    entries would occupy dequantized — the serving-stats receipt that a
    model really is running off the int8 store. Shapes/dtypes only; no
    device transfer."""
    n_int8 = 0
    int8_bytes = 0
    fp32_bytes = 0
    for key, v in weights.items():
        size = int(getattr(v, "size", np.asarray(v).size))
        if str(getattr(v, "dtype", "")) == "int8":
            n_int8 += 1
            int8_bytes += size
            fp32_bytes += size * 4
        elif key.endswith("@qscale"):
            int8_bytes += size * 4
    return {"n_int8": n_int8, "int8_bytes": int8_bytes,
            "fp32_bytes": fp32_bytes}


def quantize_to_int8(w, scale_broadcast, qmax=_QMAX):
    """THE symmetric int8 grid (one formula for the pass, the serving
    store and the transpiler): round(w / s * qmax) clipped to
    [-qmax, qmax], with `scale_broadcast` already shaped to broadcast
    onto `w` (`qmax` generalizes to the transpiler's weight_bits
    knob)."""
    return np.clip(np.round(np.asarray(w, np.float32) / scale_broadcast
                            * qmax), -qmax, qmax).astype(np.int8)


def quantize_symmetric(w, channel_axis=-1):
    """Per-channel symmetric int8 quantization along one axis: returns
    ``(q, scales)`` with ``w ≈ q * (scales / 127)`` broadcast along
    `channel_axis` (abs-max ranges reduced over every other axis)."""
    w = np.asarray(w, np.float32)
    ax = channel_axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != ax)
    s = np.maximum(np.abs(w).max(axis=reduce_axes) if reduce_axes
                   else np.abs(w), _EPS).astype(np.float32)
    shape = [1] * w.ndim
    shape[ax] = s.size
    return quantize_to_int8(w, s.reshape(shape)), s


def weight_channel_scales(w, op_type, attrs=None):
    """Per-output-channel abs-max ranges of one weight array plus the
    channel axis: conv filters are ranged over C_out (axis 0); mul/matmul
    weights over the output-feature axis (the trailing dims past
    y_num_col_dims for `mul`, rows under transpose_Y for `matmul`)."""
    attrs = attrs or {}
    w = np.asarray(w)
    if op_type in ("conv2d", "depthwise_conv2d"):
        axis = 0
        s = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
    elif op_type == "matmul" and attrs.get("transpose_Y"):
        axis = 0
        s = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
    else:
        yn = int(attrs.get("y_num_col_dims", 1)) if op_type == "mul" \
            else w.ndim - 1
        axis = yn
        s = np.abs(w.reshape(int(np.prod(w.shape[:yn])), -1)).max(axis=0)
    return np.maximum(s, _EPS).astype(np.float32), axis


def _quantizable_sites(program, white):
    """[(op, act var, weight var)] for every global-block white op with a
    persistable, never-in-block-written fp32 weight operand (the shape
    quantization can bake) — skipping grad/optimizer/structural ops."""
    from .core.lowering import _SPECIAL, _STRUCTURAL
    from .framework import (_AMP_STATE_OP_TYPES, _OPTIMIZER_OP_TYPES,
                            Block, Operator, convert_dtype)
    from .ir_passes import _grad_referenced_ids, _write_indices

    block = program.global_block()
    writes = _write_indices(block)
    grad_refed = _grad_referenced_ids(program)
    sites = []
    for op in block.ops:
        if op.type not in white or id(op) in grad_refed:
            continue
        if ("__fwd_op__" in op.attrs or op.type in _OPTIMIZER_OP_TYPES
                or op.type in _AMP_STATE_OP_TYPES
                or op.type in _STRUCTURAL or op.type in _SPECIAL
                or any(isinstance(a, (Block, Operator))
                       for a in op.attrs.values())):
            continue
        aslot, wslot = _SLOTS[op.type]
        avs = op.inputs.get(aslot, [])
        wvs = op.inputs.get(wslot, [])
        if len(avs) != 1 or len(wvs) != 1:
            continue
        a, w = avs[0], wvs[0]
        if not getattr(w, "persistable", False) or writes.get(w.name):
            continue
        if convert_dtype(w.dtype) != "float32" \
                or convert_dtype(a.dtype) != "float32":
            continue
        sites.append((op, a, w))
    return sites


def calibrate(program, sample_feeds, strategy="abs_max", percentile=99.9,
              scope=None, place=None, ops=None,
              max_samples_per_tensor=1 << 19):
    """Run the fp32 `program` over `sample_feeds` (an iterable of feed
    dicts) and collect a :class:`CalibrationTable`: per-tensor activation
    ranges for every quantizable op's activation input (``abs_max`` keeps
    the running max of |x|; ``percentile`` keeps a bounded subsample of
    |x| and takes its `percentile`), plus per-channel weight ranges read
    directly from `scope`. The calibration run is pinned un-quantized
    (a process-wide ``PTPU_QUANT=1`` cannot recurse into it)."""
    from .core.place import CPUPlace
    from .core.scope import global_scope
    from .executor import Executor

    if strategy not in ("abs_max", "percentile"):
        raise ValueError("calibrate: unknown strategy %r "
                         "(use 'abs_max' or 'percentile')" % (strategy,))
    scope = scope if scope is not None else global_scope()
    white = _check_ops(ops) if ops else DEFAULT_QUANT_OPS

    sites = _quantizable_sites(program, white)
    weights = {}
    for op, _a, w in sites:
        if w.name in weights:
            continue
        val = scope.get(w.name)
        if val is None:
            continue
        s, axis = weight_channel_scales(val, op.type, op.attrs)
        weights[w.name] = {"scales": [float(x) for x in s], "axis": axis}
    act_names = sorted({a.name for _op, a, _w in sites
                        if not getattr(a, "persistable", False)})

    acts = {}
    if act_names:
        calib = program.clone(for_test=True)
        # the calibration run must see the plain fp32 graph even when
        # PTPU_QUANT=1 is exported process-wide (chicken-and-egg)
        calib._quant_disable = True
        exe = Executor(place if place is not None else CPUPlace())
        maxima = {n: 0.0 for n in act_names}
        samples = {n: [] for n in act_names}
        # EVERY batch contributes to the percentile distribution: each
        # one is strided down to a bounded slice, and the concatenation
        # is re-strided to the cap at the end — a large first batch can
        # neither blow the memory bound nor shadow later feeds whose
        # ranges differ
        per_batch = max(1, max_samples_per_tensor // 16)
        batches = 0
        for feed in sample_feeds:
            outs = exe.run(calib, feed=feed, fetch_list=list(act_names),
                           scope=scope)
            batches += 1
            for name, val in zip(act_names, outs):
                a = np.abs(np.asarray(val, np.float32)).ravel()
                if strategy == "abs_max":
                    maxima[name] = max(maxima[name], float(a.max()))
                else:
                    stride = max(1, -(-a.size // per_batch))
                    samples[name].append(a[::stride])
        exe.close()
        if batches == 0:
            raise ValueError("calibrate: sample_feeds yielded no batches")
        for name in act_names:
            if strategy == "abs_max":
                acts[name] = max(maxima[name], _EPS)
            else:
                allv = np.concatenate(samples[name])
                if allv.size > max_samples_per_tensor:
                    allv = allv[::max(
                        1, -(-allv.size // max_samples_per_tensor))]
                acts[name] = max(
                    float(np.percentile(allv, percentile)), _EPS)

    _metrics.counter("quant/calib_tensors").inc(len(acts) + len(weights))
    return CalibrationTable(acts=acts, weights=weights, strategy=strategy,
                            percentile=percentile
                            if strategy == "percentile" else None)


# ---------------------------------------------------------------------------
# config + activation
# ---------------------------------------------------------------------------


class QuantConfig:
    """Resolved quantization policy consumed by the `quant_rewrite`
    pass. mode ``weight_only``: int8 weight store, dequantize-on-use,
    fp32 compute (no table needed). mode ``full_int8``: activations
    quantize per-tensor against the calibration table and the dot/conv
    executes int8×int8→int32; an op whose activation has no table entry
    degrades to weight_only for that op. `blacklist` names (any input or
    output var) pin their ops fp32."""

    def __init__(self, mode="weight_only", table=None, ops=None,
                 blacklist=None):
        mode = str(mode)
        if mode not in MODES:
            raise ValueError("quant mode must be one of %s, got %r"
                             % (MODES, mode))
        if table is not None and not isinstance(table, CalibrationTable):
            table = coerce_table(table)
        self.mode = mode
        self.table = table
        self.ops = _check_ops(ops or DEFAULT_QUANT_OPS)
        self.blacklist = frozenset(blacklist or ())

    def cache_key(self):
        """Short stable digest for the compile-cache pipeline key."""
        h = hashlib.sha1()
        h.update(repr((self.mode, sorted(self.ops),
                       sorted(self.blacklist),
                       self.table.digest() if self.table is not None
                       else None)).encode())
        return "%s:%s" % (self.mode, h.hexdigest()[:8])


# saved-table files resolved from PTPU_QUANT_TABLE sit on the per-run
# cache-key path (pipeline_key -> active_config): cache the parsed table
# per (mtime, size) so steady-state runs never re-read or re-parse it
_TABLE_CACHE = {}


def _load_table_cached(path):
    path = str(path)
    try:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        # table file moved/deleted mid-run: keep serving the already-
        # loaded table so compiled-and-cached steps stay usable
        hit = _TABLE_CACHE.get(path)
        if hit is not None:
            return hit[1]
        raise
    hit = _TABLE_CACHE.get(path)
    if hit is not None and hit[0] == sig:
        return hit[1]
    table = CalibrationTable.load(path)
    _TABLE_CACHE[path] = (sig, table)
    return table


def coerce_table(table):
    """CalibrationTable from a table object, a dict, or a JSON path
    (paths are cached by mtime+size — env-activated compiles resolve
    the table on every cache-key computation)."""
    if table is None or isinstance(table, CalibrationTable):
        return table
    if isinstance(table, dict):
        return CalibrationTable.from_dict(table)
    return _load_table_cached(table)


def quant_env_enabled():
    return bool(_env("PTPU_QUANT"))


def _env_config():
    blk = _env("PTPU_QUANT_BLACKLIST")
    return QuantConfig(
        mode=_env("PTPU_QUANT_MODE"),
        table=coerce_table(_env("PTPU_QUANT_TABLE")),
        blacklist=[s.strip() for s in blk.split(",") if s.strip()]
        if blk else None)


def active_config(program=None, build_strategy=None):
    """The quantization config in effect for one compile, or None.
    Precedence: program decoration (`decorate`) > PTPU_QUANT=1. A
    program carrying ``_quant_disable`` (the calibration clone) is
    always un-quantized."""
    if program is not None and getattr(program, "_quant_disable", False):
        return None
    cfg = getattr(program, "_quant_config", None) if program is not None \
        else None
    if cfg is not None:
        return cfg
    if quant_env_enabled():
        return _env_config()
    return None


def decorate(program, mode="weight_only", table=None, ops=None,
             blacklist=None):
    """Pin a quantization policy on `program`: every subsequent compile
    of it (executor, CompiledProgram, AnalysisPredictor) schedules the
    `quant_rewrite` pass with this config. Returns the program."""
    program._quant_config = QuantConfig(mode=mode, table=table, ops=ops,
                                        blacklist=blacklist)
    return program


# ---------------------------------------------------------------------------
# the rewrite pass
# ---------------------------------------------------------------------------


@register_pass("quant_rewrite")
class QuantRewritePass(Pass):
    """Rewrite white-list ops to int8 execution on the compile clone.
    Soundness:

      - only forward, non-grad-referenced ops with a persistable,
        never-rewritten fp32 weight operand are touched — training
        programs keep their exact graph (grad ops re-run forward
        kernels; an int8 dot has no useful vjp);
      - the op's ORIGINAL output var keeps its name, declared dtype and
        write position — consumers, fetches and reaching-def reasoning
        are untouched; only fresh vars (int8 activation, int8 weight,
        int32 accumulator, scale constants) are introduced;
      - int8 weights and their fp32 scales bake as fresh
        content-addressed persistable scope entries via the PR-3
        machinery (`bake_value` + `state_fallback`), so cached compiled
        steps stay scope-portable and the original fp32 parameters are
        never overwritten;
      - activation quantize ops are deduped per (source, reaching
        definition), weight dequantize ops per weight name.
    """

    def apply(self, program, scope=None):
        cfg = active_config(program)
        if cfg is None or scope is None:
            return program
        from . import unique_name
        from .framework import Operator, convert_dtype
        from .ir_passes import (_fetch_targets, _write_indices, bake_value)

        targets = _fetch_targets(program)
        if targets is None:
            # fetch set unknown (standalone apply): pin
            # program._opt_fetch_targets to run this pass standalone
            return program
        block = program.global_block()
        writes = _write_indices(block)

        def rdef(name, i):
            last = -1
            for w in writes.get(name, ()):
                if w < i:
                    last = w
                else:
                    break
            return last

        sites = {id(op): (a, w)
                 for op, a, w in _quantizable_sites(program, cfg.ops)}
        table = cfg.table
        quant_cache = {}   # (act name, reaching def) -> int8 Variable
        deq_cache = {}     # weight layout key -> dequantized fp32 Var
        baked_w = {}       # weight layout key -> (int8 var, scales, sb,
        #                    fp32 value) — keyed per LAYOUT, not per
        #                    name: a weight shared by consumers with
        #                    different channel axes (matmul vs its
        #                    transpose_Y twin, conv vs mul) must not
        #                    reuse the other layout's scales
        new_ops = []
        rewritten = 0
        stats = {"saved": 0, "fp32": 0}
        counted = set()  # weight NAMES in the byte stats — a shared
        # weight baked under two layouts still has ONE fp32 original
        # (the saved-ratio denominator must not double-count it)

        def wkey(op, w):
            if op.type == "mul":
                return (w.name, "mul",
                        int(op.attrs.get("y_num_col_dims", 1)))
            if op.type == "matmul":
                return (w.name, "matmul",
                        bool(op.attrs.get("transpose_Y")))
            return (w.name, "conv")

        def bake_const(name, arr, dtype):
            """Fresh content-addressed persistable scope entry (PR-3
            bake machinery — existing names are never overwritten)."""
            digest = hashlib.sha1(
                arr.tobytes() + repr((name, arr.shape,
                                      str(arr.dtype))).encode()
            ).hexdigest()[:12]
            fname = "__quant__.%s.%s" % (digest, name)
            if not block.has_var(fname):
                block.create_var(name=fname, shape=arr.shape, dtype=dtype,
                                 persistable=True)
            scope.set(fname, arr)
            bake_value(program, fname, arr)
            return block.var(fname)

        def quantized_weight(op, w):
            key = wkey(op, w)
            hit = baked_w.get(key)
            if hit is not None:
                return hit
            val = np.asarray(scope.get(w.name), np.float32)
            scales, axis = weight_channel_scales(val, op.type, op.attrs)
            if table is not None and table.weight_scales(w.name) \
                    is not None:
                ts, taxis = table.weight_scales(w.name)
                if taxis == axis and ts.size == scales.size:
                    scales = ts
            # scale broadcast shape along the channel axis; the trailing
            # output-feature axes of `mul` may span several dims — the
            # flattened per-column vector reshapes onto them
            if op.type == "mul":
                yn = int(op.attrs.get("y_num_col_dims", 1))
                sb = scales.reshape((1,) * yn + val.shape[yn:])
            else:
                bshape = [1] * val.ndim
                bshape[axis] = scales.size
                sb = scales.reshape(bshape)
            q = quantize_to_int8(val, sb)
            qv = bake_const(w.name + ".int8", q, "int8")
            if w.name not in counted:
                # int8 twin + fp32 per-channel scales vs the fp32
                # original: the step's device weight store shrinks by
                # this (once per weight, however many layouts bake)
                counted.add(w.name)
                stats["saved"] += max(val.nbytes - (q.nbytes
                                                    + scales.size * 4),
                                      0)
                stats["fp32"] += val.nbytes
            out = (qv, scales, sb, val)
            baked_w[key] = out
            return out

        for i, op in enumerate(block.ops):
            site = sites.get(id(op))
            if site is None:
                new_ops.append(op)
                continue
            a, w = site
            names = (set(op.input_names()) | set(op.output_names()))
            if names & cfg.blacklist:
                new_ops.append(op)
                continue
            aslot, wslot = _SLOTS[op.type]
            out_slot = "Output" if op.type.startswith(
                ("conv", "depthwise")) else "Out"
            outs = op.outputs.get(out_slot, [])
            if len(outs) != 1 \
                    or convert_dtype(outs[0].dtype) != "float32":
                new_ops.append(op)
                continue
            if scope.get(w.name) is None:
                new_ops.append(op)
                continue

            full = (cfg.mode == "full_int8" and table is not None
                    and table.act_scale(a.name) is not None
                    and not getattr(a, "persistable", False)
                    # int8 matmul constraints: plain 2-D dot, no alpha
                    # (declared rank — no host materialization here)
                    and (op.type != "matmul"
                         or (op.attrs.get("alpha", 1.0) == 1.0
                             and w.shape is not None
                             and len(w.shape) == 2))
                    # FoldedBias lands on the fp32 conv output — an
                    # int32 accumulator cannot absorb it
                    and not op.inputs.get("FoldedBias"))

            qv, scales, sb, val = quantized_weight(op, w)

            # full-int8 dense layers (mul / plain matmul) fuse the whole
            # quantize -> int8 dot -> dequantize chain into ONE op when
            # the Pallas int8 kernel's dispatch policy has it on
            # (ops/kernel_registry.enabled_for — an emission-time mode+
            # platform decision, so kernels-off programs are op-for-op
            # the historical 3-op emission): the standalone
            # quantize/dequantize_linear HLOs around the dot vanish from
            # the lowered module
            fuse = full and op.type in ("matmul", "mul") \
                and not op.attrs.get("transpose_X", False) \
                and not op.attrs.get("transpose_Y", False) \
                and _kernel_enabled("int8_matmul")

            if fuse:
                s_a = float(table.act_scale(a.name))
                out = outs[0]
                # flat per-output-channel combined scale: the op impl
                # flattens mul's operands to 2-D the same way the mul
                # op does, so the kernel always sees an [N] vector
                dq = (np.asarray(scales).reshape(-1) / _QMAX) \
                    * (s_a / _QMAX)
                dqv = bake_const(out.name + ".qdq",
                                 np.asarray(dq, np.float32), "float32")
                fattrs = {"act_scale": _QMAX / max(s_a, _EPS),
                          "__quant__": True}
                if op.type == "mul":
                    fattrs["x_num_col_dims"] = int(
                        op.attrs.get("x_num_col_dims", 1))
                    fattrs["y_num_col_dims"] = int(
                        op.attrs.get("y_num_col_dims", 1))
                new_ops.append(Operator(
                    block, "fused_int8_matmul",
                    inputs={"X": [a], "Y": [qv], "Scale": [dqv]},
                    outputs={"Out": [out]},
                    attrs=fattrs))
            elif full:
                s_a = float(table.act_scale(a.name))
                qa_key = (a.name, rdef(a.name, i))
                qa = quant_cache.get(qa_key)
                if qa is None:
                    qa = block.create_var(
                        name=unique_name.generate(a.name + "@quant.int8"),
                        shape=a.shape, dtype="int8", persistable=False)
                    new_ops.append(Operator(
                        block, "quantize", inputs={"Input": [a]},
                        outputs={"Output": [qa]},
                        attrs={"Scale": _QMAX / max(s_a, _EPS),
                               "__quant__": True}))
                    quant_cache[qa_key] = qa
                out = outs[0]
                acc = block.create_var(
                    name=unique_name.generate(out.name + "@quant.acc"),
                    shape=out.shape, dtype="int32", persistable=False)
                # combined dequant scale, shaped to broadcast onto the
                # op's OUTPUT: trailing feature dims for mul/matmul, the
                # (C_out, 1, 1) channel axis for NCHW conv
                if op.type in ("conv2d", "depthwise_conv2d"):
                    dq = (scales.reshape((-1, 1, 1)) / _QMAX) \
                        * (s_a / _QMAX)
                elif op.type == "mul":
                    yn = int(op.attrs.get("y_num_col_dims", 1))
                    dq = (scales.reshape(val.shape[yn:]) / _QMAX) \
                        * (s_a / _QMAX)
                else:  # matmul
                    dq = (scales / _QMAX) * (s_a / _QMAX)
                dqv = bake_const(out.name + ".qdq",
                                 np.asarray(dq, np.float32), "float32")
                op.inputs[aslot] = [qa]
                op.inputs[wslot] = [qv]
                op.outputs[out_slot] = [acc]
                op.attrs["__quant_int8__"] = True
                new_ops.append(op)
                new_ops.append(Operator(
                    block, "dequantize_linear",
                    inputs={"Input": [acc], "Scale": [dqv]},
                    outputs={"Output": [out]},
                    attrs={"out_dtype": "float32", "__quant__": True}))
            else:
                dqw = deq_cache.get(wkey(op, w))
                if dqw is None:
                    sv = bake_const(w.name + ".qscale",
                                    np.asarray(sb / _QMAX, np.float32),
                                    "float32")
                    dqw = block.create_var(
                        name=unique_name.generate(w.name + "@quant.deq"),
                        shape=w.shape, dtype="float32",
                        persistable=False)
                    new_ops.append(Operator(
                        block, "dequantize_linear",
                        inputs={"Input": [qv], "Scale": [sv]},
                        outputs={"Output": [dqw]},
                        attrs={"out_dtype": "float32",
                               "__quant__": True}))
                    deq_cache[wkey(op, w)] = dqw
                op.inputs[wslot] = [dqw]
                new_ops.append(op)
            rewritten += 1

        if not rewritten:
            return program
        block.ops = new_ops
        _metrics.counter("quant/ops_rewritten").inc(rewritten)
        record_weight_store(len(counted), stats["saved"], stats["fp32"])
        program._bump_version()
        return program


# ---------------------------------------------------------------------------
# predictor integration (inference.AnalysisPredictor load-time hook)
# ---------------------------------------------------------------------------


def quantize_predictor_program(program, scope, mode="weight_only",
                               table=None, blacklist=None):
    """Load-time quantization for a freshly loaded predictor program
    with its own private scope (docs/QUANTIZATION.md):

      weight_only  rides ``QuantizeTranspiler.convert_to_int8`` — the
                   fp32 weights are REPLACED by int8 twins in the scope
                   (the store genuinely halves-plus) and prepended
                   ``dequantize`` ops reconstruct them on use;
      full_int8    decorates the program so the compile pipeline's
                   `quant_rewrite` pass emits the int8 execution path
                   (requires a calibration `table` for the activation
                   ranges; ops it cannot calibrate fall back to
                   weight-only).

    Destructive scope edits are safe here exactly because the predictor
    owns both the program and the scope (the same argument that lets
    the load-time conv_bn fold edit weights)."""
    if mode == "weight_only":
        from .contrib.quantize import QuantizeTranspiler

        QuantizeTranspiler().convert_to_int8(program, scope=scope,
                                             skip=blacklist or ())
    elif mode == "full_int8":
        decorate(program, mode=mode, table=coerce_table(table),
                 blacklist=blacklist)
    else:
        raise ValueError("quant mode must be one of %s, got %r"
                         % (MODES, mode))
    return program
