"""Dataset classes + factory (parity: framework/data_set.h C16 —
`Dataset::LoadIntoMemory/LocalShuffle/GlobalShuffle`, dataset_factory.cc,
python dataset.py DatasetFactory/InMemoryDataset/QueueDataset).

TPU-native: file lists hold recordio shards (native/recordio.cc). The
Hogwild thread-per-core consumption model (C15) becomes a reader thread
pool over the file shards (`set_thread`) feeding the single jitted step —
host parsing overlaps device compute; `Executor.train_from_dataset`
drives it, and FLAGS_cpu_deterministic pins emission to filelist order.
GlobalShuffle's cross-node RPC exchange becomes a deterministic
shard-reassignment by hash (same sample redistribution capability, no RPC:
every worker reads the shards whose hash maps to it).
"""

import random

import numpy as np

from . import recordio_writer

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._use_var = []
        self._thread = 1
        self._feed_desc = None

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_data_feed_desc(self, desc):
        """Attach a DataFeedDesc: the filelist is then read as MultiSlot
        TEXT files through the C++ parser (native/data_feed.cc —
        MultiSlotDataFeed parity) instead of recordio shards."""
        self._feed_desc = desc
        # only an explicitly-set desc batch size overrides the dataset's
        if getattr(desc, "_batch_size_set", False):
            self._batch_size = desc.batch_size

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread = thread_num

    def set_use_var(self, var_list):
        self._use_var = list(var_list)

    def _file_samples(self, path, shard_index=0):
        """Parse ONE shard file into its sample list — the unit of work a
        Hogwild-style reader thread owns (device_worker.h:135: each
        worker consumes its own DataFeed shard). Recordio shards read
        through the fault-tolerant data plane (docs/DATA_PLANE.md):
        CRC/framing/truncation damage routes through
        `PTPU_DATA_ANOMALY_POLICY` instead of raising mid-epoch, and
        `shard_index` keys the `data_corrupt_shard`/`data_stall_shard`
        chaos sites. Healthy shards yield the bitwise-legacy stream."""
        if self._feed_desc is not None:
            from .core import native

            desc = self._feed_desc
            # ALL declared slots are parsed (they're in the file), but only
            # is_used slots are yielded, in declaration order — matching
            # set_use_slots/set_use_var binding semantics
            types = [s["type"] for s in desc.slots]
            used = [i for i, s in enumerate(desc.slots)
                    if s.get("is_used", True)]
            mods = [desc.slots[i].get("hash_mod") for i in used]

            from .parallel.host_embedding import fold_ids

            def fold(v, mod):
                # host-side id folding (set_hash_mod): raw uint64 hashes
                # never reach the device as 64-bit values; same rule as
                # HostEmbeddingTable(hash_ids=True) so serving-time
                # pull(raw_ids) agrees with training-time folds
                if mod is None:
                    return v
                return fold_ids(v, mod)

            records, bad = native.parse_multislot_file(path, types)
            if bad:
                import logging

                logging.warning("MultiSlot file %s: skipped %d malformed "
                                "line(s)", path, bad)
            if used == list(range(len(types))) and not any(
                    m is not None for m in mods):
                return records  # all slots used verbatim: no rebuild
            return [tuple(fold(rec[i], m) for i, m in zip(used, mods))
                    for rec in records]
        from . import data_plane

        reader = data_plane.resilient_sample_reader(
            [path], shard_indices=[shard_index])
        return list(reader())

    def _sample_reader(self):
        def reader():
            for i, path in enumerate(self._filelist):
                yield from self._file_samples(path, shard_index=i)

        return reader

    def _pool_map_items(self, fn, items, ordered):
        """The ONE windowed thread-pool shape every shard-parse path
        shares (C15 Hogwild parity, TPU-native reading: worker threads
        parse on the host while the single jitted step owns the
        device). Submission is WINDOWED — at most n_workers+2 items
        outstanding — so a streaming dataset never buffers the whole
        filelist in RAM. `ordered` emits results in item order (bitwise
        the serial run); off = completion order for max overlap."""
        from concurrent.futures import (FIRST_COMPLETED,
                                        ThreadPoolExecutor, wait)

        n = max(1, min(self._thread, len(items)))
        if n == 1:
            for item in items:
                yield fn(item)
            return
        window = n + 2
        with ThreadPoolExecutor(max_workers=n) as ex:
            it = iter(items)
            pending = []
            for item in it:
                pending.append(ex.submit(fn, item))
                if len(pending) >= window:
                    break
            while pending:
                if ordered:
                    done = pending.pop(0)  # item order
                else:
                    wait(pending, return_when=FIRST_COMPLETED)
                    done = next(f for f in pending if f.done())
                    pending.remove(done)
                result = done.result()
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(ex.submit(fn, nxt))
                yield result

    def _pool_map(self, fn):
        """Thread-pool over file shards. FLAGS_cpu_deterministic keeps
        emission in filelist order so losses reproduce the serial run
        exactly; off = completion order for max overlap."""
        from .flags import flag

        yield from self._pool_map_items(
            lambda item: fn(item[1], item[0]),
            list(enumerate(self._filelist)),
            ordered=flag("cpu_deterministic"))

    def _iter_samples_threaded(self):
        for samples in self._pool_map(self._file_samples):
            yield from samples

    def _file_columns(self, path, _shard_index=0):
        """Columnar parse of one shard: ((vals, offs) per USED slot,
        n_rec) with set_hash_mod folds applied vectorized over the whole
        value column — no per-record python objects anywhere. The
        MultiSlot text format has no CRC framing, so the recordio
        containment policy and the `data_corrupt_shard`/
        `data_stall_shard` chaos sites do NOT cover this path
        (docs/DATA_PLANE.md) — `_shard_index` exists only to fit the
        shared `_pool_map` item shape."""
        from .core import native
        from .parallel.host_embedding import fold_ids

        desc = self._feed_desc
        types = [s["type"] for s in desc.slots]
        used = [i for i, s in enumerate(desc.slots)
                if s.get("is_used", True)]
        mods = [desc.slots[i].get("hash_mod") for i in used]
        slots, n_rec, bad = native.parse_multislot_columns(path, types)
        if bad:
            import logging

            logging.warning("MultiSlot file %s: skipped %d malformed "
                            "line(s)", path, bad)
        out = []
        for i, m in zip(used, mods):
            vals, offs = slots[i]
            if m is not None:
                vals = fold_ids(vals, m)
            out.append((vals, offs))
        return out, n_rec

    def _iter_file_columns(self):
        if self._thread > 1 and len(self._filelist) > 1:
            yield from self._pool_map(self._file_columns)
        else:
            for path in self._filelist:
                yield self._file_columns(path)

    @staticmethod
    def _concat_columns(a, b):
        """Append column block b after a (batching crosses file
        boundaries, like the serial record stream)."""
        (sa, na), (sb, nb) = a, b
        merged = []
        for (va, oa), (vb, ob) in zip(sa, sb):
            merged.append((np.concatenate([va, vb]),
                           np.concatenate([oa, oa[-1] + ob[1:]])))
        return merged, na + nb

    def _emit_columnar(self, slots, r0, r1, feed_names, pads):
        feed = {}
        n = r1 - r0
        for i, (name, (vals, offs)) in enumerate(zip(feed_names, slots)):
            lens = offs[r0 + 1:r1 + 1] - offs[r0:r1]
            seg = vals[offs[r0]:offs[r1]]
            lmax = int(lens.max()) if n else 0
            if n and int(lens.min()) == lmax:
                arr = seg.reshape(n, lmax)
            else:
                pad = 0
                if pads is not None and i < len(pads):
                    pad = pads[i]
                arr = np.full((n, lmax), pad, seg.dtype)
                arr[np.arange(lmax)[None, :] < lens[:, None]] = seg
            feed[name] = arr
        return feed

    def _batches_columnar(self):
        """Vectorized batcher over columnar shards: numpy slicing and a
        mask-scatter pad replace the reference's per-record DataFeed loop
        (data_feed.cc AddInstanceToInsVec) — host cost is O(bytes), not
        O(records) of python objects."""
        feed_names = [v.name for v in self._use_var]
        pads = self._pad_values()
        bs = self._batch_size
        acc = None
        for block in self._iter_file_columns():
            acc = block if acc is None else self._concat_columns(acc,
                                                                 block)
            slots, n = acc
            r = 0
            while n - r >= bs:
                yield self._emit_columnar(slots, r, r + bs, feed_names,
                                          pads)
                r += bs
            if r:
                slots = [(v[o[r]:o[-1]], o[r:] - o[r]) for v, o in slots]
                acc = (slots, n - r)
        if acc is not None and acc[1]:
            yield self._emit_columnar(acc[0], 0, acc[1], feed_names, pads)

    def _batches_prefetched(self, depth=4, source=None):
        """Producer-thread batch prefetch: host parsing/batching overlaps
        the device step (the BufferedReader/double-buffer shape, C17).
        `source` overrides the generator being prefetched (the resumable
        path prefetches `(batch, cursor-state)` PAIRS through the same
        queue so cursor application stays on the consumer side)."""
        import queue
        import threading

        if source is None:
            source = self._batches()
        q = queue.Queue(maxsize=depth)
        sentinel = object()
        stop = threading.Event()
        err = []

        def produce():
            try:
                for b in source:
                    # bounded put that notices an abandoned consumer, so
                    # a mid-epoch exception in the training loop doesn't
                    # leave this thread blocked forever holding batches
                    while not stop.is_set():
                        try:
                            q.put(b, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                err.append(e)
            finally:
                # the sentinel must LAND (a dropped one strands the
                # consumer on q.get forever); keep trying unless the
                # consumer already abandoned us
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=produce, daemon=True,
                             name="ptpu-dataset-prefetch")
        t.start()
        try:
            while True:
                b = q.get()
                if b is sentinel:
                    break
                yield b
        finally:
            stop.set()
            t.join(timeout=10)
        if err:
            raise err[0]

    def _pad_values(self):
        """Per-used-slot batch pad value (positional, matching the order
        `_sample_reader` yields). Declared via DataFeedDesc
        `set_pad_value` — pad ids with the embedding's padding_idx so
        sum-pooled lookups exclude pad rows (reference LoD batching has no
        pad contributions)."""
        if self._feed_desc is None:
            return None
        return [s.get("pad_value", 0) for s in self._feed_desc.slots
                if s.get("is_used", True)]

    def _batches(self):
        # streaming desc-driven datasets batch columnar (InMemoryDataset
        # keeps the per-record path: shuffle permutes record objects)
        if self._feed_desc is not None and not hasattr(self, "_samples"):
            yield from self._batches_columnar()
            return
        feed_names = [v.name for v in self._use_var]
        pads = self._pad_values()
        batch = []
        for sample in self._iter_samples():
            batch.append(sample)
            if len(batch) == self._batch_size:
                yield self._to_feed(feed_names, batch, pads)
                batch = []
        if batch:
            yield self._to_feed(feed_names, batch, pads)

    @staticmethod
    def _to_feed(feed_names, batch, pad_values=None):
        cols = list(zip(*batch))
        feed = {}
        for i, (name, col) in enumerate(zip(feed_names, cols)):
            arrs = [np.asarray(c) for c in col]
            # variable-length sparse slots (the MultiSlot norm) batch
            # padded-dense: pad 1-D id/value lists to the batch max with the
            # slot's declared pad value (the LoD -> padded+lengths bridge,
            # SURVEY §5.7)
            if (arrs[0].ndim == 1
                    and len({a.shape[0] for a in arrs}) > 1):
                pad = 0
                if pad_values is not None and i < len(pad_values):
                    pad = pad_values[i]
                maxlen = max(a.shape[0] for a in arrs)
                arrs = [np.pad(a, (0, maxlen - a.shape[0]),
                               constant_values=pad) for a in arrs]
            stacked = np.stack(arrs)
            if stacked.ndim == 1:  # scalar fields batch to [N, 1] (labels)
                stacked = stacked.reshape(-1, 1)
            feed[name] = stacked
        return feed

    def _iter_samples(self):
        raise NotImplementedError

    # -- mid-epoch resumable ingestion (docs/DATA_PLANE.md) ---------------
    def _shard_samples_seq(self, order, start_si):
        """Yield `(si, samples)` for `order[start_si:]` IN ORDER; with
        `set_thread(N)` the shard parses overlap on the shared
        `_pool_map_items` window, FORCE-ordered — the resumable
        stream's order is part of the cursor contract, so results are
        consumed strictly in shard order and the output is bitwise the
        serial parse's."""
        def parse(si):
            real = order[si]
            return si, self._file_samples(self._filelist[real],
                                          shard_index=real)

        yield from self._pool_map_items(parse,
                                        range(start_si, len(order)),
                                        ordered=True)

    def _resumable_pairs(self, start, epochs):
        """Producer for the resumable stream: yields
        `(feed_dict, (epoch, shard_idx, record_offset))` where the
        position names the first record NOT in any batch yielded so
        far. Shard order per epoch comes from the cursor's seed
        (`data_plane.shard_order`); within an epoch batches cross
        shard boundaries exactly like the legacy `_batches` stream, so
        a fresh cursor with no seed reproduces it bitwise — but a
        partial tail batch FLUSHES at each epoch end (matching legacy
        per-epoch iteration); batches never span epochs."""
        feed_names = [v.name for v in self._use_var]
        pads = self._pad_values()
        bs = self._batch_size
        epoch = start.epoch
        shard_idx = start.shard_idx
        offset = start.record_offset
        while epochs is None or epoch < epochs:
            order = start.shard_order(len(self._filelist), epoch=epoch)
            batch = []
            for si, samples in self._shard_samples_seq(order, shard_idx):
                consumed = offset
                for sample in samples[offset:]:
                    batch.append(sample)
                    consumed += 1
                    if len(batch) == bs:
                        # normalize a batch ending exactly on the
                        # epoch's last record to the next epoch's start
                        pos = ((epoch + 1, 0, 0)
                               if (si == len(order) - 1
                                   and consumed == len(samples))
                               else (epoch, si, consumed))
                        yield (self._to_feed(feed_names, batch, pads),
                               pos)
                        batch = []
                offset = 0
            if batch:
                # epoch tail (the legacy partial batch): the next
                # position is the following epoch's first record
                yield (self._to_feed(feed_names, batch, pads),
                       (epoch + 1, 0, 0))
            epoch += 1
            shard_idx = 0

    def _resumable_stream(self, cursor, epochs, prefetch):
        """The raw `(feed, position)` pair stream behind
        `resumable_batches` (host prefetch applied, cursor NOT yet
        attached) — for consumers like `Executor.train_from_dataset`
        whose device-side lookahead pulls batches ahead of their steps:
        they must apply each pair's position at the true consumption
        point themselves, or the mirrored cursor runs a batch ahead."""
        from .observability import metrics as obs_metrics

        if cursor.position() != (0, 0, 0):
            obs_metrics.counter("data/cursor_resumes").inc()
        if prefetch is None:
            prefetch = self._thread > 1
        pairs = self._resumable_pairs(cursor.clone(), epochs)
        if prefetch:
            pairs = self._batches_prefetched(source=pairs)
        return pairs

    def resumable_batches(self, cursor, epochs=None, scope=None,
                          prefetch=None):
        """The checkpoint-resumable batch stream (docs/DATA_PLANE.md):
        starts at `cursor`'s position and ADVANCES the cursor as each
        batch is consumed — never as it is prefetched — so a scope
        snapshot/checkpoint taken between batches names exactly the
        first unconsumed record, and a restored run resumes the
        byte-identical stream. `scope` mirrors the cursor into
        ``__data_cursor__`` on every consumption (this is how the
        cursor rides the PR-4 checkpoint manifest with no format
        change). `epochs` is the ABSOLUTE epoch bound of the stream;
        default = one pass from the cursor's CURRENT epoch, so a
        restored epoch-k cursor resumes the rest of epoch k instead of
        silently yielding nothing against a stale absolute bound. A
        fresh cursor (seed None) yields bitwise the legacy
        `_batches()` stream."""
        from . import data_plane

        if epochs is None:
            epochs = cursor.epoch + 1
        pairs = self._resumable_stream(cursor, epochs, prefetch)
        return data_plane.apply_cursor(pairs, cursor, scope)


class QueueDataset(DatasetBase):
    """Streaming dataset: shards are read on the fly (data_set.h
    QueueDataset — no in-memory shuffle); `set_thread(N)` parses shards
    on N reader threads."""

    def _iter_samples(self):
        if self._thread > 1 and len(self._filelist) > 1:
            return self._iter_samples_threaded()
        return self._sample_reader()()


class InMemoryDataset(DatasetBase):
    """load_into_memory + local/global shuffle (data_set.h:77-83)."""

    def __init__(self):
        super().__init__()
        self._samples = None
        self._rank = 0
        self._world = 1

    def load_into_memory(self):
        if self._thread > 1 and len(self._filelist) > 1:
            self._samples = list(self._iter_samples_threaded())
        else:
            self._samples = list(self._sample_reader()())

    def local_shuffle(self, seed=None):
        assert self._samples is not None, "call load_into_memory first"
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, seed=0):
        """Cross-worker sample redistribution (data_set.h:77-83
        GlobalShuffle). Single-process: a seeded full shuffle.

        Multi-worker STREAMING path (fleet with trainer endpoints —
        PADDLE_TRAINER_ENDPOINTS): each worker loads only ITS OWN
        filelist shard, then samples are exchanged worker-to-worker over
        the framed-TCP runtime: destination = content-hash % world, so
        every sample lands on exactly one worker no matter who loaded it
        and per-worker memory stays ~N/world — the reference's RPC
        redistribution, not a full local copy.

        Fallback (world > 1 but no endpoints): hash-keep over a full
        local load — every worker must then hold the ENTIRE dataset
        before discarding its complement; kept only for endpoint-less
        setups and documented as the memory-unbounded mode."""
        assert self._samples is not None, "call load_into_memory first"
        endpoints = []
        if fleet is not None:
            self._rank = fleet.worker_index()
            self._world = fleet.worker_num()
            get_eps = getattr(fleet, "worker_endpoints", None)
            endpoints = list(get_eps() or []) if get_eps else []
        if self._world > 1 and len(endpoints) == self._world:
            import zlib

            from .distributed_runtime import exchange_samples
            from .recordio_writer import (deserialize_sample,
                                          serialize_sample)

            salt = (b"%d" % seed)
            outgoing = [[] for _ in range(self._world)]
            for s in self._samples:
                rec = serialize_sample(s)
                outgoing[zlib.crc32(rec + salt) % self._world].append(rec)
            # free the deserialized pre-exchange copy (the serialized
            # records in `outgoing` still hold every local sample), but
            # keep it RECOVERABLE: a peer failure mid-exchange must not
            # lose this worker's share of the dataset
            self._samples = None
            try:
                records = exchange_samples(endpoints, self._rank, outgoing)
            except BaseException:
                # restore the pre-exchange samples from the outgoing
                # buckets so the dataset stays usable (retry/local run)
                self._samples = [deserialize_sample(r)
                                 for bucket in outgoing for r in bucket]
                raise
            samples = [deserialize_sample(r) for r in records]
            random.Random(seed * 1000003 + self._rank).shuffle(samples)
            self._samples = samples
            return
        rng = random.Random(seed)
        order = list(range(len(self._samples)))
        rng.shuffle(order)
        if self._world > 1:
            order = [i for i in order if i % self._world == self._rank]
        self._samples = [self._samples[i] for i in order]

    def release_memory(self):
        self._samples = None

    def _iter_samples(self):
        assert self._samples is not None, "call load_into_memory first"
        return iter(self._samples)

    def _resumable_stream(self, cursor, epochs, prefetch):
        """Not supported: the `DatasetCursor` names a position in the
        deterministic ON-DISK shard order, but an InMemoryDataset
        trains from its loaded — usually shuffled or globally
        redistributed — sample list. Re-reading the files here would
        silently resume a DIFFERENT stream than the one trained on, so
        this raises instead (covering both `resumable_batches` and
        `Executor.train_from_dataset(cursor=)`, which drive the same
        stream). Use a QueueDataset for mid-epoch resumable ingestion
        (docs/DATA_PLANE.md)."""
        raise NotImplementedError(
            "InMemoryDataset does not support resumable batch streams: "
            "a DatasetCursor positions the on-disk shard stream, not a "
            "shuffled/redistributed in-memory sample list. Use a "
            "QueueDataset for resumable ingestion (docs/DATA_PLANE.md).")


class DatasetFactory:
    """dataset_factory.cc parity."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)
