"""Dataset classes + factory (parity: framework/data_set.h C16 —
`Dataset::LoadIntoMemory/LocalShuffle/GlobalShuffle`, dataset_factory.cc,
python dataset.py DatasetFactory/InMemoryDataset/QueueDataset).

TPU-native: file lists hold recordio shards (native/recordio.cc). The
Hogwild thread-per-core consumption model (C15) collapses into the single
jitted step fed batch-by-batch — `Executor.train_from_dataset` drives it.
GlobalShuffle's cross-node RPC exchange becomes a deterministic
shard-reassignment by hash (same sample redistribution capability, no RPC:
every worker reads the shards whose hash maps to it).
"""

import random

import numpy as np

from . import recordio_writer

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._use_var = []
        self._thread = 1
        self._feed_desc = None

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_data_feed_desc(self, desc):
        """Attach a DataFeedDesc: the filelist is then read as MultiSlot
        TEXT files through the C++ parser (native/data_feed.cc —
        MultiSlotDataFeed parity) instead of recordio shards."""
        self._feed_desc = desc
        # only an explicitly-set desc batch size overrides the dataset's
        if getattr(desc, "_batch_size_set", False):
            self._batch_size = desc.batch_size

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread = thread_num

    def set_use_var(self, var_list):
        self._use_var = list(var_list)

    def _sample_reader(self):
        if self._feed_desc is not None:
            from .core import native

            desc = self._feed_desc
            # ALL declared slots are parsed (they're in the file), but only
            # is_used slots are yielded, in declaration order — matching
            # set_use_slots/set_use_var binding semantics
            types = [s["type"] for s in desc.slots]
            used = [i for i, s in enumerate(desc.slots)
                    if s.get("is_used", True)]
            mods = [desc.slots[i].get("hash_mod") for i in used]

            from .parallel.host_embedding import fold_ids

            def fold(v, mod):
                # host-side id folding (set_hash_mod): raw uint64 hashes
                # never reach the device as 64-bit values; same rule as
                # HostEmbeddingTable(hash_ids=True) so serving-time
                # pull(raw_ids) agrees with training-time folds
                if mod is None:
                    return v
                return fold_ids(v, mod)

            def reader():
                for path in self._filelist:
                    records, bad = native.parse_multislot_file(path, types)
                    if bad:
                        import logging

                        logging.warning(
                            "MultiSlot file %s: skipped %d malformed "
                            "line(s)", path, bad)
                    for rec in records:
                        yield tuple(fold(rec[i], m)
                                    for i, m in zip(used, mods))

            return reader
        return recordio_writer.recordio_reader_creator(self._filelist)

    def _pad_values(self):
        """Per-used-slot batch pad value (positional, matching the order
        `_sample_reader` yields). Declared via DataFeedDesc
        `set_pad_value` — pad ids with the embedding's padding_idx so
        sum-pooled lookups exclude pad rows (reference LoD batching has no
        pad contributions)."""
        if self._feed_desc is None:
            return None
        return [s.get("pad_value", 0) for s in self._feed_desc.slots
                if s.get("is_used", True)]

    def _batches(self):
        feed_names = [v.name for v in self._use_var]
        pads = self._pad_values()
        batch = []
        for sample in self._iter_samples():
            batch.append(sample)
            if len(batch) == self._batch_size:
                yield self._to_feed(feed_names, batch, pads)
                batch = []
        if batch:
            yield self._to_feed(feed_names, batch, pads)

    @staticmethod
    def _to_feed(feed_names, batch, pad_values=None):
        cols = list(zip(*batch))
        feed = {}
        for i, (name, col) in enumerate(zip(feed_names, cols)):
            arrs = [np.asarray(c) for c in col]
            # variable-length sparse slots (the MultiSlot norm) batch
            # padded-dense: pad 1-D id/value lists to the batch max with the
            # slot's declared pad value (the LoD -> padded+lengths bridge,
            # SURVEY §5.7)
            if (arrs[0].ndim == 1
                    and len({a.shape[0] for a in arrs}) > 1):
                pad = 0
                if pad_values is not None and i < len(pad_values):
                    pad = pad_values[i]
                maxlen = max(a.shape[0] for a in arrs)
                arrs = [np.pad(a, (0, maxlen - a.shape[0]),
                               constant_values=pad) for a in arrs]
            stacked = np.stack(arrs)
            if stacked.ndim == 1:  # scalar fields batch to [N, 1] (labels)
                stacked = stacked.reshape(-1, 1)
            feed[name] = stacked
        return feed

    def _iter_samples(self):
        raise NotImplementedError


class QueueDataset(DatasetBase):
    """Streaming dataset: shards are read on the fly (data_set.h
    QueueDataset — no in-memory shuffle)."""

    def _iter_samples(self):
        return self._sample_reader()()


class InMemoryDataset(DatasetBase):
    """load_into_memory + local/global shuffle (data_set.h:77-83)."""

    def __init__(self):
        super().__init__()
        self._samples = None
        self._rank = 0
        self._world = 1

    def load_into_memory(self):
        self._samples = list(self._sample_reader()())

    def local_shuffle(self, seed=None):
        assert self._samples is not None, "call load_into_memory first"
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, seed=0):
        """Cross-worker sample redistribution (data_set.h GlobalShuffle).
        Single-process: a seeded full shuffle. Multi-worker (fleet set):
        keep the samples whose hash maps to this worker — all workers
        together see every sample exactly once, shuffled."""
        assert self._samples is not None, "call load_into_memory first"
        if fleet is not None:
            self._rank = fleet.worker_index()
            self._world = fleet.worker_num()
        rng = random.Random(seed)
        order = list(range(len(self._samples)))
        rng.shuffle(order)
        if self._world > 1:
            order = [i for i in order if i % self._world == self._rank]
        self._samples = [self._samples[i] for i in order]

    def release_memory(self):
        self._samples = None

    def _iter_samples(self):
        assert self._samples is not None, "call load_into_memory first"
        return iter(self._samples)


class DatasetFactory:
    """dataset_factory.cc parity."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)
