"""Program-pass infrastructure (parity: framework/ir/pass.h:34 Pass/
REGISTER_PASS and ir/graph_pattern_detector.h:254 GraphPatternDetector).

The reference rewrites a C++ graph IR through ~30 registered passes with a
declarative pattern detector. TPU-native, HLO-level optimization belongs
to XLA; what remains OURS is the PROGRAM level — algebraic folds that
change what gets computed (conv+bn weight folding), op removal with
rewiring (inference dropout), and analysis annotations (memory reuse
plans). This module gives those transforms the reference's extensibility
surface: a `Pass` base, a name registry any user can extend, and an
op-CHAIN pattern matcher over a block's dataflow (the 90% case of
GraphPatternDetector — producer feeds consumer, optionally
single-consumer links).

    @fluid.ir.register_pass("my_fold")
    class MyFold(fluid.ir.Pass):
        def apply(self, program, scope=None):
            for conv, bn in fluid.ir.match_chain(
                    program.global_block(), ("conv2d", "batch_norm")):
                ...
    fluid.ir.apply_passes(program, ["my_fold"], scope)

The built-in inference passes (conv_bn_fold, dropout_remove,
memory_optimize) are registered here and the transpilers now delegate to
them, so user passes and builtins compose through one pipeline.
"""

from .core.scope import global_scope

__all__ = ["Pass", "register_pass", "unregister_pass", "get_pass",
           "apply_passes", "registered_passes", "match_chain", "Pattern"]


class Pass:
    """One program transform. Subclass and implement `apply(program,
    scope=None)`; mutate the program in place (bump its version if you
    change ops) and return it. `scope` carries materialized parameter
    values for weight-editing passes (pass.h:34 Apply contract)."""

    name = None

    def apply(self, program, scope=None):
        raise NotImplementedError

    def __call__(self, program, scope=None):
        return self.apply(program, scope)


_REGISTRY = {}


def register_pass(name):
    """Decorator registering a Pass subclass (or a plain
    `fn(program, scope)` function) under `name` — REGISTER_PASS parity.
    Duplicate names raise (matching the op registry's convention);
    `unregister_pass` frees a name deliberately."""
    def deco(obj):
        if name in _REGISTRY:
            raise ValueError(
                "pass %r already registered; unregister_pass(%r) first "
                "to replace it deliberately" % (name, name))
        if isinstance(obj, type) and issubclass(obj, Pass):
            inst = obj()
            inst.name = name
        else:
            fn = obj

            class _FnPass(Pass):
                def apply(self, program, scope=None):
                    return fn(program, scope)

            inst = _FnPass()
            inst.name = name
        _REGISTRY[name] = inst
        return obj

    return deco


def unregister_pass(name):
    """Remove a registered pass (tests / deliberate replacement)."""
    _REGISTRY.pop(name, None)


def get_pass(name):
    if name not in _REGISTRY:
        raise KeyError("no pass registered under %r (have: %s)"
                       % (name, sorted(_REGISTRY)))
    return _REGISTRY[name]


def registered_passes():
    return sorted(_REGISTRY)


def apply_passes(program, names, scope=None):
    """Run the named passes in order over `program` (PassBuilder parity).

    Under `PTPU_VERIFY_PASSES=1` the Program IR verifier runs on the
    input and after every pass, raising `analysis.VerifyError` naming
    the pass that introduced a violation (docs/STATIC_ANALYSIS.md) —
    the same hook `ir_passes.optimize_for_execution` uses, so
    AnalysisPredictor load-time passes and user pipelines get the same
    per-pass validation as the compile-time pipeline."""
    scope = scope if scope is not None else global_scope()
    from .analysis import verifier as _av

    verifier = None
    if _av.verify_enabled():
        verifier = _av.PassPipelineVerifier(program)
    for name in names:
        get_pass(name).apply(program, scope)
        if verifier is not None:
            verifier.after_pass(name, program)
    return program


# ---------------------------------------------------------------------------
# op-chain pattern matching (graph_pattern_detector.h:254, the linear case)
# ---------------------------------------------------------------------------


def _consumers(block):
    cons = {}
    for op in block.ops:
        for n in op.input_names():
            cons.setdefault(n, []).append(op)
    return cons


def match_chain(block, types, single_consumer=True):
    """Yield op lists [o1, ..., ok] with o1.type..ok.type == types, where
    each o_{j+1} consumes an output var of o_j (dataflow adjacency, not
    list adjacency). With single_consumer (the safe default for rewrites),
    every linking var must have exactly one consuming op.

    Matches are yielded in program order and never share an op. The op
    list and consumer map are SNAPSHOTTED when iteration starts: a
    handler may freely remove the yielded chain's own ops, but ops it
    inserts (and consumer-count changes it causes) are only seen by a
    fresh match_chain call — run the pass to a fixed point if rewrites
    enable further matches."""
    cons = _consumers(block)
    order = {id(op): i for i, op in enumerate(block.ops)}
    claimed = set()
    for op in list(block.ops):
        if op.type != types[0] or id(op) in claimed:
            continue
        chain = [op]
        ok = True
        for want in types[1:]:
            cur = chain[-1]
            nxt = None
            for out_name in cur.output_names():
                users = [u for u in cons.get(out_name, [])
                         if id(u) in order]
                if single_consumer and len(users) != 1:
                    continue
                for u in users:
                    if (u.type == want and id(u) not in claimed
                            and order[id(u)] > order[id(cur)]):
                        nxt = u
                        break
                if nxt is not None:
                    break
            if nxt is None:
                ok = False
                break
            chain.append(nxt)
        if ok:
            claimed.update(id(o) for o in chain)
            yield chain


# ---------------------------------------------------------------------------
# DAG pattern matching (graph_pattern_detector.h:254 PDNode/PDPattern —
# the general case match_chain cannot express: multi-input consumers,
# slot-pinned edges, shared producers)
# ---------------------------------------------------------------------------


class _Edge:
    __slots__ = ("src", "dst", "dst_slot", "src_slot", "single_consumer")

    def __init__(self, src, dst, dst_slot, src_slot, single_consumer):
        self.src, self.dst = src, dst
        self.dst_slot, self.src_slot = dst_slot, src_slot
        self.single_consumer = single_consumer


class Pattern:
    """Declarative op-DAG pattern: named nodes + dataflow edges.

        p = fluid.ir.Pattern()
        p.op("convA", "conv2d")
        p.op("convB", "conv2d")
        p.op("add", "elementwise_add")
        p.edge("convA", "add", dst_slot="X")
        p.edge("convB", "add", dst_slot="Y")
        for m in p.match(block):            # {"convA": op, ...}
            ...

    Node `type` is one op type or a tuple of alternatives; `pred(op)`
    adds an arbitrary per-node test. An edge means: some output var of
    `src` (restricted to `src_slot` if given) is an input var of `dst`
    (restricted to `dst_slot` if given); with single_consumer (the safe
    default for rewrites) that linking var must have exactly ONE
    consuming op in the block, so deleting the matched interior never
    orphans an outside reader. Matches are maximal assignments yielded
    in program order of the first-declared node, never share an op, and
    see a SNAPSHOT of the op list (same contract as match_chain)."""

    def __init__(self):
        self._nodes = {}   # name -> (types tuple or None, pred or None)
        self._order = []
        self._edges = []

    def op(self, name, type=None, pred=None):
        if name in self._nodes:
            raise ValueError("pattern node %r already defined" % name)
        types = (type,) if isinstance(type, str) else \
            (tuple(type) if type is not None else None)
        self._nodes[name] = (types, pred)
        self._order.append(name)
        return name

    def edge(self, src, dst, dst_slot=None, src_slot=None,
             single_consumer=True):
        for n in (src, dst):
            if n not in self._nodes:
                raise ValueError("pattern node %r not defined" % n)
        self._edges.append(_Edge(src, dst, dst_slot, src_slot,
                                 single_consumer))
        return self

    # -- matching ----------------------------------------------------------
    def _topo(self):
        """Pattern nodes in dependency order (edge sources first),
        insertion order as the tie-break; cycles are an error."""
        indeg = {n: 0 for n in self._order}
        for e in self._edges:
            indeg[e.dst] += 1
        out = []
        ready = [n for n in self._order if indeg[n] == 0]
        while ready:
            n = ready.pop(0)
            out.append(n)
            for e in self._edges:
                if e.src == n:
                    indeg[e.dst] -= 1
                    if indeg[e.dst] == 0:
                        ready.append(e.dst)
        if len(out) != len(self._order):
            raise ValueError("pattern has a cycle")
        return out

    def match(self, block):
        ops = list(block.ops)
        order = {id(op): i for i, op in enumerate(ops)}
        cons = _consumers(block)
        topo = self._topo()
        claimed = set()

        def link_ok(e, src_op, dst_op):
            outs = src_op.output_names(e.src_slot) if e.src_slot \
                else src_op.output_names()
            ins = dst_op.input_names(e.dst_slot) if e.dst_slot \
                else dst_op.input_names()
            link = set(outs) & set(ins)
            if e.single_consumer:
                link = {n for n in link
                        if len([u for u in cons.get(n, [])
                                if id(u) in order]) == 1}
            return bool(link)

        def node_ok(name, op, assign):
            types, pred = self._nodes[name]
            if types is not None and op.type not in types:
                return False
            if id(op) in claimed:
                return False
            if any(o is op for o in assign.values()):
                return False  # injective
            if pred is not None and not pred(op):
                return False
            return all(link_ok(e, assign[e.src], op)
                       for e in self._edges
                       if e.dst == name and e.src in assign)

        def extend(assign, k):
            if k == len(topo):
                yield dict(assign)
                return
            name = topo[k]
            in_edges = [e for e in self._edges
                        if e.dst == name and e.src in assign]
            if in_edges:
                e0 = in_edges[0]
                src_op = assign[e0.src]
                outs = src_op.output_names(e0.src_slot) if e0.src_slot \
                    else src_op.output_names()
                seen, cands = set(), []
                for vn in outs:
                    for u in cons.get(vn, []):
                        if id(u) in order and id(u) not in seen:
                            seen.add(id(u))
                            cands.append(u)
            else:
                cands = ops
            for op in sorted(cands, key=lambda o: order[id(o)]):
                if not node_ok(name, op, assign):
                    continue
                assign[name] = op
                yield from extend(assign, k + 1)
                del assign[name]

        for m in extend({}, 0):
            if any(id(op) in claimed for op in m.values()):
                continue
            claimed.update(id(op) for op in m.values())
            yield m


# ---------------------------------------------------------------------------
# built-in passes (the transpilers delegate here)
# ---------------------------------------------------------------------------


@register_pass("conv_bn_fold")
class ConvBNFoldPass(Pass):
    """Fold batch_norm into the preceding conv2d's weights — the algebraic
    inference fold (inference_transpiler.py _fuse_bn). Patterns:
    conv2d -> batch_norm and conv2d -> elementwise_add(bias) ->
    batch_norm. Needs materialized params in `scope` (run startup first);
    unmaterialized matches are skipped, not erred."""

    def apply(self, program, scope=None):
        from .transpiler.inference_transpiler import _fold_bn_weights

        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        changed = False
        # the add variant is a DAG shape: conv feeds the add's X slot
        # specifically (the bias rides Y), and bn consumes the add —
        # expressed declaratively on Pattern (conv_bn_fuse_pass.cc's
        # conv->elementwise_add->batch_norm PDPattern)
        p = Pattern()
        p.op("conv", "conv2d")
        p.op("add", "elementwise_add")
        p.op("bn", "batch_norm")
        p.edge("conv", "add", dst_slot="X")
        p.edge("add", "bn", dst_slot="X")
        for m in p.match(block):
            conv, add, bn = m["conv"], m["add"], m["bn"]
            if _fold_bn_weights(conv, bn, scope, add.input_names("Y")[0]):
                add.outputs["Out"] = bn.outputs["Y"]
                block.ops.remove(bn)
                changed = True
        for conv, bn in match_chain(block, ("conv2d", "batch_norm")):
            if _fold_bn_weights(conv, bn, scope, None):
                conv.outputs["Output"] = bn.outputs["Y"]
                block.ops.remove(bn)
                changed = True
        if changed:
            program._bump_version()
        return program


@register_pass("dropout_remove")
class DropoutRemovePass(Pass):
    """Remove inference-identity dropout ops, rewiring consumers; the
    downgrade_in_infer variant becomes a scale op
    (inference_transpiler.py _fuse_relu_dropout parity)."""

    def apply(self, program, scope=None):
        from .framework import Operator
        from .ir_passes import _fetch_targets, _outside_reads

        block = program.global_block()
        # names the rename rewiring cannot reach: fetch targets (pinned
        # by the compile pipeline) and vars read from sub-blocks — those
        # dropout outputs keep a producer (identity scale) instead.
        # Rename is also only sound under single assignment: if the
        # dropout's out name (or the rename SOURCE) is written again
        # later, rewired readers would observe the rebound value.
        protected = set(_fetch_targets(program) or ()) \
            | _outside_reads(program)
        writes = {}
        for blk in program.blocks:
            for op in blk.ops:
                for n in op.output_names():
                    writes[n] = writes.get(n, 0) + 1
        new_ops = []
        rename = {}
        changed = False
        for op in block.ops:
            if op.type == "dropout":
                changed = True
                src = op.inputs["X"][0]
                src = rename.get(src.name, src)  # chained dropouts
                impl = op.attrs.get("dropout_implementation",
                                    "downgrade_in_infer")
                if impl == "upscale_in_train":
                    outs = op.outputs.get("Out", [])
                    if any(v.name in protected
                           or writes.get(v.name, 0) != 1
                           for v in outs) \
                            or writes.get(src.name, 0) > 1:
                        new_ops.append(Operator(
                            block, "scale", inputs={"X": [src]},
                            outputs={"Out": [outs[0]]},
                            attrs={"scale": 1.0}))
                        continue
                    for outv in outs:
                        rename[outv.name] = src
                    continue
                p = op.attrs.get("dropout_prob", 0.5)
                new_ops.append(Operator(
                    block, "scale", inputs={"X": [src]},
                    outputs={"Out": [op.outputs["Out"][0]]},
                    attrs={"scale": 1.0 - p}))
                continue
            for slot, vs in op.inputs.items():
                op.inputs[slot] = [rename.get(v.name, v) for v in vs]
            new_ops.append(op)
        block.ops = new_ops
        if changed:
            program._bump_version()
        return program


@register_pass("conv_elementwise_add_fuse")
class ConvResidualAddFusePass(Pass):
    """conv2d + same-shape elementwise_add(residual) [+ relu] ->
    conv2d_fusion carrying ResidualData (the reference's
    conv_elementwise_add_fuse_pass.cc / conv_elementwise_add_act_fuse —
    multi-input PDPatterns the linear matcher cannot express: the
    residual operand comes from OUTSIDE the chain). Bias-style adds
    (axis=1 with a 1-D operand) are left for conv_bn_fold."""

    def apply(self, program, scope=None):
        from .framework import Operator
        from .ir_passes import _fetch_targets, _outside_reads

        block = program.global_block()
        # interior outputs (conv's, add's — and the act's when fused)
        # disappear; a match whose interior is fetched or sub-block-read
        # must be left alone (Pattern's single_consumer only counts
        # consuming OPS)
        protected = set(_fetch_targets(program) or ()) \
            | _outside_reads(program)
        changed = False
        for with_act in (True, False):  # longest pattern first
            p = Pattern()
            def _same_shape_residual(op):
                # Fluid's axis-broadcast add (a [N,C] Y at axis=0, a bias
                # at axis=1) is NOT a residual: conv2d_fusion's
                # ResidualData adds element-wise, so only a Y of exactly
                # the conv output's rank+shape may fuse
                xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
                if len(ys) != 1 or not xs:
                    return False
                xshape = getattr(xs[0], "shape", None)
                yshape = getattr(ys[0], "shape", None)
                return (xshape is not None and yshape is not None
                        and tuple(xshape) == tuple(yshape))

            p.op("conv", "conv2d")
            p.op("add", "elementwise_add", pred=_same_shape_residual)
            p.edge("conv", "add", dst_slot="X")
            if with_act:
                p.op("act", "relu")
                p.edge("add", "act", dst_slot="X")
            for m in p.match(block):
                conv, add = m["conv"], m["add"]
                last = m["act"] if with_act else add
                interior = [o for o in (conv, add, m.get("act"))
                            if o is not None and o is not last]
                if any(n in protected for o in interior
                       for n in o.output_names()):
                    continue
                fused_ins = {"Input": conv.inputs["Input"],
                             "Filter": conv.inputs["Filter"],
                             "ResidualData": add.inputs["Y"]}
                if conv.inputs.get("FoldedBias"):
                    # per-channel shift left by a preceding conv+bn fold
                    # — conv2d_fusion applies Bias before the residual
                    # and activation, the same order the unfused ops ran
                    fused_ins["Bias"] = conv.inputs["FoldedBias"]
                fused = Operator(
                    block, "conv2d_fusion",
                    inputs=fused_ins,
                    outputs={"Output": last.outputs["Out"]},
                    attrs=dict(conv.attrs,
                               activation="relu" if with_act
                               else "identity"))
                # splice at the LAST op's position: every input
                # (conv operands + the residual) is produced by then
                block.ops[block.ops.index(last)] = fused
                for o in (conv, add):
                    if o is not last:
                        block.ops.remove(o)
                changed = True
        if changed:
            program._bump_version()
        return program


@register_pass("memory_optimize")
def _memory_optimize_pass(program, scope):
    """Lifetime analysis + reuse-plan annotation
    (memory_optimization_transpiler.memory_optimize as a registered
    pass; XLA performs the actual buffer aliasing). Bumps the version so
    the compile pipeline's change detection keeps the annotated clone."""
    from .transpiler.memory_optimization_transpiler import memory_optimize

    memory_optimize(program)
    program._bump_version()
    return program


# ---------------------------------------------------------------------------
# default compile-time pipeline (ir_passes.py registers fetch_dce / cse /
# constant_fold / fuse_elewise_add_act / conv_bn_fold_baked on import and
# the executors run them on every compile-cache miss — docs/
# COMPILER_PASSES.md)
# ---------------------------------------------------------------------------

from . import ir_passes as _ir_passes  # noqa: E402

build_pipeline = _ir_passes.build_pipeline
optimize_for_execution = _ir_passes.optimize_for_execution
pipeline_enabled = _ir_passes.pipeline_enabled
pipeline_key = _ir_passes.pipeline_key
program_is_inference = _ir_passes.program_is_inference
InplaceInfo = _ir_passes.InplaceInfo

__all__ += ["build_pipeline", "optimize_for_execution", "pipeline_enabled",
            "pipeline_key", "program_is_inference", "InplaceInfo"]
