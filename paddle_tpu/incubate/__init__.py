"""Incubating APIs (parity: python/paddle/fluid/incubate/)."""

from . import fleet  # noqa: F401
