"""Incubating APIs (parity: python/paddle/fluid/incubate/)."""

from . import fleet  # noqa: F401
from . import data_generator  # noqa: F401
