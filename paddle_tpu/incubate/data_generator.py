"""MultiSlot data generators (parity: python/paddle/fluid/incubate/
data_generator/__init__.py — DataGenerator base with generate_sample/
generate_batch hooks, run_from_memory/run_from_stdin drivers, and the
MultiSlot line serializers). The emitted text is exactly what the C++
MultiSlot feed parser (native/data_feed.cc) ingests: per sample, for each
slot, "<name>:<num> v..." in the string variant or "<num> v..." in the
id/float variant."""

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Subclass and implement generate_sample(line) returning an iterator
    of (slot_name, [values]) lists; optionally generate_batch(samples)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        self._line_limit = int(line_limit)

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(self, line) in the subclass")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter

    # -- drivers ------------------------------------------------------------
    def run_from_memory(self, out=None):
        """Drive generate_sample(None) until exhausted, writing serialized
        lines (run_from_memory parity)."""
        out = out or sys.stdout
        batch_samples = []
        fn = self.generate_sample(None)
        for sample in fn():
            batch_samples.append(sample)
            if len(batch_samples) == self.batch_size_:
                for s in self.generate_batch(batch_samples)():
                    out.write(self._gen_str(s))
                batch_samples = []
        if batch_samples:
            for s in self.generate_batch(batch_samples)():
                out.write(self._gen_str(s))

    def run_from_stdin(self, inp=None, out=None):
        """One serialized output line per input line (run_from_stdin
        parity — the hadoop-streaming entry point)."""
        inp = inp or sys.stdin
        out = out or sys.stdout
        batch_samples = []
        n = 0
        for line in inp:
            fn = self.generate_sample(line)
            for sample in fn():
                batch_samples.append(sample)
                if len(batch_samples) == self.batch_size_:
                    for s in self.generate_batch(batch_samples)():
                        out.write(self._gen_str(s))
                    batch_samples = []
            n += 1
            if self._line_limit and n >= self._line_limit:
                break
        if batch_samples:
            for s in self.generate_batch(batch_samples)():
                out.write(self._gen_str(s))

    def _gen_str(self, line):
        raise NotImplementedError


class MultiSlotDataGenerator(DataGenerator):
    """Serializes [(slot_name, [v, ...]), ...] samples as
    "<num> v ... <num> v ...\\n" in first-sample slot order, validating
    slot names/arity stay consistent across samples (the reference's
    proto_info check)."""

    def _gen_str(self, line):
        if not isinstance(line, list) and not isinstance(line, tuple):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        if self._proto_info is None:
            self._proto_info = [name for name, _ in line]
        elif len(line) != len(self._proto_info):
            raise ValueError(
                "the complete field set of two samples are inconsistent.")
        parts = []
        for i, (name, elements) in enumerate(line):
            if self._proto_info[i] != name:
                raise ValueError(
                    "the field name of two samples are not match: expect "
                    "%s, but got %s" % (self._proto_info[i], name))
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Same line format as MultiSlotDataGenerator but values pass through
    as strings with no numeric validation (the fast hadoop-streaming path;
    a later-paddle convenience kept for forward compatibility)."""

    def _gen_str(self, line):
        if not isinstance(line, list) and not isinstance(line, tuple):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        parts = []
        for _name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
