"""parity: incubate/fleet/base/role_maker.py."""

from ....parallel.fleet import (PaddleCloudRoleMaker,  # noqa: F401
                                UserDefinedRoleMaker)

Role = type("Role", (), {"WORKER": 1, "SERVER": 2})

__all__ = ["PaddleCloudRoleMaker", "UserDefinedRoleMaker", "Role"]
