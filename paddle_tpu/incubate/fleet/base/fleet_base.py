"""parity: incubate/fleet/base/fleet_base.py — re-exports the Fleet facade
(implementation: paddle_tpu/parallel/fleet.py)."""

from ....parallel.fleet import (DistributedStrategy, Fleet,  # noqa: F401
                                PaddleCloudRoleMaker, UserDefinedRoleMaker,
                                fleet)

__all__ = ["Fleet", "fleet", "DistributedStrategy", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]
