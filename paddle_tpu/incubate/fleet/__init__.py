from . import base  # noqa: F401
from . import collective  # noqa: F401
from . import parameter_server  # noqa: F401
