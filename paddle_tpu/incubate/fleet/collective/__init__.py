"""parity: incubate/fleet/collective/__init__.py — collective (nccl2-mode)
fleet; on TPU the collectives come from the mesh (SURVEY §5.8)."""

from ....parallel.fleet import (CollectiveOptimizer, DistributedStrategy,
                                fleet)

__all__ = ["fleet", "CollectiveOptimizer", "DistributedStrategy"]
