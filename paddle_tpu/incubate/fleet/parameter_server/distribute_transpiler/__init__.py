"""Transpiler-based PS fleet (parity: incubate/fleet/parameter_server/
distribute_transpiler/__init__.py — fleet.init_server/run_server +
TranspilerOptimizer wrapping DistributeTranspiler)."""

from ..... import framework
from .....parallel.fleet import Fleet as _CollectiveFleet
from .....parallel.fleet import PaddleCloudRoleMaker, UserDefinedRoleMaker
from .....transpiler import DistributeTranspiler, DistributeTranspilerConfig

__all__ = ["fleet", "PSFleet", "TranspilerOptimizer",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class PSFleet(_CollectiveFleet):
    """Fleet facade for pserver-mode training. After
    distributed_optimizer(...).minimize(loss), workers call
    main_program()/startup_program() for their transpiled programs and
    servers call run_server() (which in this single-binary build returns
    the pserver program for the hosting executor)."""

    def __init__(self):
        super().__init__()
        self._transpiler = None
        self._trainer_program = None
        self._server_programs = {}

    # called by TranspilerOptimizer.minimize
    def _set_transpiler(self, t):
        self._transpiler = t
        self._trainer_program = t.get_trainer_program()

    def main_program(self):
        return self._trainer_program

    def server_endpoints(self):
        return (self._transpiler.pserver_endpoints
                if self._transpiler else [])

    def init_server(self, model_dir=None, **kwargs):
        if self._transpiler is None:
            raise RuntimeError("call distributed_optimizer().minimize first")
        ep = (self._role_maker._current if self._role_maker else
              self.server_endpoints()[0])
        prog = self._transpiler.get_pserver_program(ep)
        startup = self._transpiler.get_startup_program(ep, prog)
        self._server_programs[ep] = (prog, startup)
        return prog, startup

    def run_server(self):
        if not self._server_programs:
            self.init_server()
        return next(iter(self._server_programs.values()))

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is None:
            strategy = DistributeTranspilerConfig()
        return TranspilerOptimizer(optimizer, strategy, self)

    def get_sharding_plan(self):
        """TPU-native surface: the pserver layout as a ZeRO-1 plan."""
        return (self._transpiler.get_sharding_plan()
                if self._transpiler else {})


class TranspilerOptimizer:
    """parity: TranspilerOptimizer — minimize() runs the base optimizer then
    transpiles the program for the role set in the role maker."""

    def __init__(self, optimizer, config, fleet_ref):
        self._optimizer = optimizer
        self.config = config
        self._fleet = fleet_ref

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        rm = self._fleet._role_maker
        eps = (",".join(rm._endpoints) if rm and rm._endpoints
               else "127.0.0.1:6170")
        t = DistributeTranspiler(config=self.config)
        t.transpile(trainer_id=self._fleet.worker_index(),
                    program=loss.block.program,
                    pservers=eps,
                    trainers=max(self._fleet.worker_num(), 1),
                    sync_mode=getattr(self.config, "sync_mode", True),
                    startup_program=startup_program)
        self._fleet._set_transpiler(t)
        return result

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


fleet = PSFleet()
