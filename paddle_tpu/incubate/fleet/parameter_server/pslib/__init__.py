"""pslib/Downpour-mode fleet (parity: incubate/fleet/parameter_server/
pslib/__init__.py + optimizer_factory.py:39 DownpourSGD).

The reference wraps Baidu's closed-source pslib PS client
(fleet_wrapper.h:55). The TPU-native equivalent serves the same
capability — CTR-scale sparse embeddings with dense+sparse pull/push —
from host-RAM sharded tables (parallel/host_embedding.py): `DownpourSGD`
routes each sparse table's update into the table's own optimizer and the
dense params through the wrapped optimizer."""

from .....parallel.fleet import Fleet as _CollectiveFleet
from .....parallel.host_embedding import HostEmbeddingTable

__all__ = ["fleet", "PSLib", "DownpourSGD"]


class PSLib(_CollectiveFleet):
    def __init__(self):
        super().__init__()
        self._tables = {}

    def init_server(self, model_dir=None, **kwargs):
        pass  # host tables are created lazily by distributed_embedding

    def init_worker(self):
        pass

    def save_persistables(self, executor, dirname, **kwargs):
        """Snapshot host tables next to the dense persistables
        (fleet pslib save parity)."""
        import os

        import numpy as np

        from ..... import io as io_mod

        io_mod.save_persistables(executor, dirname)
        from .....parallel.host_embedding import _TABLES

        for name, table in _TABLES.items():
            np.savez(os.path.join(dirname, "host_table_%s.npz" % name),
                     **table.state_dict())

    def load_persistables(self, executor, dirname, **kwargs):
        import os

        import numpy as np

        from ..... import io as io_mod

        io_mod.load_persistables(executor, dirname)
        from .....parallel.host_embedding import _TABLES

        for name, table in _TABLES.items():
            path = os.path.join(dirname, "host_table_%s.npz" % name)
            if os.path.exists(path):
                with np.load(path) as d:
                    table.load_state_dict(dict(d))

    def distributed_optimizer(self, optimizer, strategy=None):
        return DownpourSGD(optimizer, self)


class DownpourSGD:
    """parity: optimizer_factory.py DownpourSGD — dense grads through the
    wrapped optimizer; sparse tables update themselves on backward (the
    lookup_table_host op's push)."""

    def __init__(self, optimizer, fleet_ref):
        self._optimizer = optimizer
        self._fleet = fleet_ref

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


fleet = PSLib()
