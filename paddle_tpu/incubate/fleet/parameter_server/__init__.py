"""Parameter-server fleet modes (parity: incubate/fleet/parameter_server/
— the distribute_transpiler mode and the pslib/Downpour mode).

TPU-native mapping (SURVEY §2.3 P4-P7): pserver programs still exist at the
IR level (golden-test parity via DistributeTranspiler), but execution maps
dense param sharding to ZeRO-style opt-state sharding and giant sparse
embeddings to host-RAM tables (parallel/host_embedding.py)."""

from . import distribute_transpiler  # noqa: F401
from . import pslib  # noqa: F401
