"""Runtime flags facade (parity: gflags + the env-var bootstrap of
python/paddle/fluid/__init__.py:104-165 `__bootstrap__` — a curated
FLAGS_* allowlist is read from the environment at import; programmatic
set_flags/get_flags mirror the later fluid API).

Supported flags:
  check_nan_inf       : after every op kernel, verify all floating outputs
                        are finite; raise naming the op/var (reference
                        FLAGS_check_nan_inf, framework/operator.cc:950).
                        The check compiles into the jitted step as
                        isfinite-all reductions, so it costs one fused
                        reduction per op output when on and nothing when off.
  cpu_deterministic   : deterministic reductions (XLA is deterministic by
                        default on TPU; kept for API parity).
  eager_delete_tensor_gb : accepted for parity; XLA buffer liveness already
                        frees intermediates (donation in executor).
"""

import os

_FLAGS = {
    "check_nan_inf": False,
    "cpu_deterministic": True,
    "eager_delete_tensor_gb": 0.0,
    # pserver RPC robustness (grpc_client.h:181-199 parity):
    #   rpc_deadline     — seconds one RPC (incl. reconnect attempts) may
    #                      take before failing loudly (FLAGS_rpc_deadline
    #                      is ms in the reference; seconds here)
    #   rpc_retry_times  — reconnect+resend attempts per RPC
    #                      (FLAGS_rpc_retry_times)
    #   rpc_barrier_grace — how long the server waits on stragglers at a
    #                      sync barrier before erring the round
    "rpc_deadline": 120.0,
    "rpc_retry_times": 3,
    "rpc_barrier_grace": 300.0,
}

_ENV_ALLOWLIST = {
    "FLAGS_check_nan_inf": ("check_nan_inf", lambda s: s not in
                            ("0", "false", "False", "")),
    "FLAGS_cpu_deterministic": ("cpu_deterministic", lambda s: s not in
                                ("0", "false", "False", "")),
    "FLAGS_eager_delete_tensor_gb": ("eager_delete_tensor_gb", float),
    "FLAGS_rpc_deadline": ("rpc_deadline", float),
    "FLAGS_rpc_retry_times": ("rpc_retry_times", int),
    "FLAGS_rpc_barrier_grace": ("rpc_barrier_grace", float),
}


def _bootstrap():
    for env, (name, conv) in _ENV_ALLOWLIST.items():
        if env in os.environ:
            try:
                _FLAGS[name] = conv(os.environ[env])
            except ValueError:
                pass


_bootstrap()


def set_flags(flags):
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _FLAGS:
            raise KeyError("unknown flag %r" % k)
        _FLAGS[key] = v


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    out = {}
    for k in keys:
        key = k[6:] if k.startswith("FLAGS_") else k
        out[k] = _FLAGS[key]
    return out


def flag(name):
    return _FLAGS[name]
