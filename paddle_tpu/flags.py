"""Runtime flags facade (parity: gflags + the env-var bootstrap of
python/paddle/fluid/__init__.py:104-165 `__bootstrap__` — a curated
FLAGS_* allowlist is read from the environment at import; programmatic
set_flags/get_flags mirror the later fluid API).

Supported flags:
  check_nan_inf       : after every op kernel, verify all floating outputs
                        are finite; raise naming the op/var (reference
                        FLAGS_check_nan_inf, framework/operator.cc:950).
                        The check compiles into the jitted step as
                        isfinite-all reductions, so it costs one fused
                        reduction per op output when on and nothing when off.
  cpu_deterministic   : deterministic reductions (XLA is deterministic by
                        default on TPU; kept for API parity).
  eager_delete_tensor_gb : accepted for parity; XLA buffer liveness already
                        frees intermediates (donation in executor).

This module is also the ONE registry for the framework's own `PTPU_*`
environment switches (docs/STATIC_ANALYSIS.md): every in-tree read goes
through `env("PTPU_...")` against a declared (type, default, docstring)
entry — `tools/ptpu_lint.py` rejects direct `os.environ` reads of
`PTPU_*` names and `env()` calls naming an undeclared flag, so a typo'd
flag name fails CI instead of silently reading a default. `describe()`
prints the registry as the reference table. This module must stay
dependency-free (stdlib only) so anything in the package can import it.
"""

import os

__all__ = ["set_flags", "get_flags", "flag", "env", "env_flag",
           "declared_flags", "describe", "EnvFlag"]

_FLAGS = {
    "check_nan_inf": False,
    "cpu_deterministic": True,
    "eager_delete_tensor_gb": 0.0,
    # pserver RPC robustness (grpc_client.h:181-199 parity):
    #   rpc_deadline     — seconds one RPC (incl. reconnect attempts) may
    #                      take before failing loudly (FLAGS_rpc_deadline
    #                      is ms in the reference; seconds here)
    #   rpc_retry_times  — reconnect+resend attempts per RPC
    #                      (FLAGS_rpc_retry_times)
    #   rpc_barrier_grace — how long the server waits on stragglers at a
    #                      sync barrier before erring the round
    "rpc_deadline": 120.0,
    "rpc_retry_times": 3,
    "rpc_barrier_grace": 300.0,
}

_ENV_ALLOWLIST = {
    "FLAGS_check_nan_inf": ("check_nan_inf", lambda s: s not in
                            ("0", "false", "False", "")),
    "FLAGS_cpu_deterministic": ("cpu_deterministic", lambda s: s not in
                                ("0", "false", "False", "")),
    "FLAGS_eager_delete_tensor_gb": ("eager_delete_tensor_gb", float),
    "FLAGS_rpc_deadline": ("rpc_deadline", float),
    "FLAGS_rpc_retry_times": ("rpc_retry_times", int),
    "FLAGS_rpc_barrier_grace": ("rpc_barrier_grace", float),
}


def _bootstrap():
    for env, (name, conv) in _ENV_ALLOWLIST.items():
        if env in os.environ:
            try:
                _FLAGS[name] = conv(os.environ[env])
            except ValueError:
                pass


_bootstrap()


def set_flags(flags):
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _FLAGS:
            raise KeyError("unknown flag %r" % k)
        _FLAGS[key] = v


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    out = {}
    for k in keys:
        key = k[6:] if k.startswith("FLAGS_") else k
        out[k] = _FLAGS[key]
    return out


def flag(name):
    return _FLAGS[name]


# ---------------------------------------------------------------------------
# PTPU_* environment-switch registry
# ---------------------------------------------------------------------------


def env_flag(name, raw=None):
    """Boolean env parsing shared by every PTPU_* switch (the spelling
    semantics parallel/zero.py established): unset/empty -> None,
    1/true/on/yes -> True, 0/false/off/no -> False (case-insensitive),
    anything else raises naming the flag."""
    raw = os.environ.get(name, "") if raw is None else raw
    if raw == "":
        return None
    low = raw.strip().lower()
    if low in ("1", "true", "on", "yes"):
        return True
    if low in ("0", "false", "off", "no"):
        return False
    raise ValueError("%s=%r is not a boolean flag (use 0/1)" % (name, raw))


class EnvFlag:
    """One declared PTPU_* environment switch: name, type ('bool', 'int',
    'float', 'str', 'path'), default (returned when unset/empty),
    docstring. 'path' accepts the boolean OFF spellings as unset —
    `PTPU_TRACE_DIR=0` disables tracing rather than naming a directory
    literally '0', the semantics the pre-registry `_env_on` gate had."""

    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name, type, default, doc):
        self.name = name
        self.type = type
        self.default = default
        self.doc = doc

    def parse(self, raw):
        if raw == "":
            return self.default
        if self.type == "bool":
            val = env_flag(self.name, raw)
            return self.default if val is None else val
        if self.type in ("int", "float"):
            conv = int if self.type == "int" else float
            try:
                return conv(raw)
            except ValueError:
                raise ValueError("%s=%r is not %s %s"
                                 % (self.name, raw,
                                    "an" if self.type == "int" else "a",
                                    self.type))
        if self.type == "path" and raw.strip().lower() in (
                "0", "false", "off", "no"):
            return self.default
        return raw


_ENV_REGISTRY = {}


def _declare(name, type, default, doc):
    _ENV_REGISTRY[name] = EnvFlag(name, type, default, doc)


# -- observability (docs/OBSERVABILITY.md) ----------------------------------
_declare("PTPU_METRICS", "bool", False,
         "enable the instrumented metrics hot paths")
_declare("PTPU_METRICS_OUT", "path", None,
         "dump the metrics registry as JSON to this path at process exit")
_declare("PTPU_TRACE", "bool", False,
         "enable tracing-span recording")
_declare("PTPU_TRACE_DIR", "path", None,
         "enable spans and write <dir>/ptpu_trace.json at process exit")
_declare("PTPU_METRICS_PORT", "int", None,
         "serve live /metrics, /healthz and /varz on this loopback port "
         "(0 = pick an ephemeral port; unset = no endpoint thread)")
_declare("PTPU_BLACKBOX_DIR", "path", None,
         "enable the flight recorder and write its crash dumps "
         "(ptpu_blackbox_*.json) into this directory")
_declare("PTPU_BLACKBOX_EVENTS", "int", None,
         "flight-recorder ring capacity in events (default 4096)")
# -- executor / async engine (docs/ASYNC_EXECUTION.md) ----------------------
_declare("PTPU_ASYNC_STEPS", "int", 12,
         "async in-flight window depth before dispatch backpressures")
_declare("PTPU_CACHE_DIR", "path", None,
         "persistent on-disk XLA compile cache directory")
# -- compiler pipeline (docs/COMPILER_PASSES.md, docs/STATIC_ANALYSIS.md) ---
_declare("PTPU_NO_PROGRAM_OPT", "bool", False,
         "disable the compile-time pass pipeline (exact unoptimized path)")
_declare("PTPU_VERIFY_PASSES", "bool", False,
         "run the Program IR verifier before the pass pipeline and after "
         "each pass, blaming the pass that introduced a violation")
# -- mixed precision (docs/MIXED_PRECISION.md) ------------------------------
_declare("PTPU_AMP", "bool", False,
         "activate the AMP dtype rewrite process-wide")
_declare("PTPU_AMP_LEVEL", "str", "O1",
         "AMP level when activated via PTPU_AMP (O1 or O2)")
_declare("PTPU_AMP_DTYPE", "str", "bfloat16",
         "AMP compute dtype when activated via PTPU_AMP")
_declare("PTPU_AMP_BUCKET_MB", "float", None,
         "gradient-bucket size in MiB for coalesced collectives "
         "(0/unset = per-leaf collectives)")
# -- quantized inference (docs/QUANTIZATION.md) -----------------------------
_declare("PTPU_QUANT", "bool", False,
         "activate the int8 quant_rewrite pass process-wide")
_declare("PTPU_QUANT_MODE", "str", "weight_only",
         "quantization mode when activated via PTPU_QUANT "
         "(weight_only or full_int8)")
_declare("PTPU_QUANT_TABLE", "path", None,
         "calibration-table JSON (quant.CalibrationTable.save) supplying "
         "activation ranges for full_int8")
_declare("PTPU_QUANT_BLACKLIST", "str", None,
         "comma-separated var names whose ops are pinned fp32 by the "
         "quant_rewrite pass")
# -- ZeRO (docs/ZERO.md) ----------------------------------------------------
_declare("PTPU_ZERO_STAGE", "int", None,
         "ZeRO sharding stage for ShardedAdam (1, 2 or 3)")
_declare("PTPU_ZERO_OVERLAP", "bool", False,
         "issue per-bucket collectives in backward order (comm/compute "
         "overlap)")
_declare("PTPU_ZERO_OFFLOAD", "bool", False,
         "keep optimizer state in host RAM between steps")
# -- resilience (docs/RESILIENCE.md) ----------------------------------------
_declare("PTPU_ANOMALY_POLICY", "str", None,
         "ResilientTrainer anomaly policy (warn|skip_batch|rollback|abort; "
         "unset = rollback)")
_declare("PTPU_SPIKE_FACTOR", "float", None,
         "loss-spike threshold as a multiple of the running EMA "
         "(unset = spike detection off)")
_declare("PTPU_FAULT_INJECT", "str", None,
         "deterministic fault-injection spec, e.g. "
         "'nan_at_step:12,ckpt_torn_write:2'")
_declare("PTPU_RETRY_BUDGET", "int", 8,
         "rollback-and-retry attempts per training run")
_declare("PTPU_RETRY_BACKOFF", "float", 0.05,
         "base seconds of exponential backoff between transient retries")
# -- streaming data plane (docs/DATA_PLANE.md) ------------------------------
_declare("PTPU_DATA_ANOMALY_POLICY", "str", None,
         "corrupt-input containment policy for recordio shard readers "
         "(abort|skip_record|quarantine_shard; unset = skip_record)")
_declare("PTPU_DATA_STRICT", "bool", False,
         "abort the sample exchange on a confirmed-dead shuffle peer "
         "instead of re-partitioning across the survivors")
_declare("PTPU_DATA_RETRY_BUDGET", "int", 2,
         "frame retries per CONNECTED shuffle peer (wedged before ack, "
         "torn frame) before it is confirmed dead; never-connected "
         "peers are governed by PTPU_DATA_EXCHANGE_TIMEOUT instead")
_declare("PTPU_DATA_PEER_TIMEOUT", "float", 10.0,
         "seconds one shuffle-peer connection attempt / frame "
         "send+ack may take; also sizes the bounded straggler grace "
         "for SEND-CONFIRMED-DEAD peers' frames (acked-but-silent "
         "peers get the full PTPU_DATA_EXCHANGE_TIMEOUT — a slow "
         "loader holding our bucket is not a dead one)")
_declare("PTPU_DATA_EXCHANGE_TIMEOUT", "float", 300.0,
         "full sample-exchange deadline; a never-connected peer "
         "(listener not up — startup skew or a crashed machine) is "
         "only confirmed dead at this deadline, the legacy tolerance")
# -- serving (docs/SERVING.md) ----------------------------------------------
_declare("PTPU_SERVE_ASYNC_STEPS", "int", 4,
         "decode steps kept in flight ahead of EOS/stream materialization")
_declare("PTPU_SERVE_PREFILL_CHUNK", "int", 0,
         "prompt tokens a prefill row consumes per serving step via the "
         "chunked-prefill fast path (0 = legacy one-token prefill)")
_declare("PTPU_SERVE_PREFIX_CACHE", "bool", False,
         "content-addressed KV block sharing: requests whose prompt "
         "prefix is cached skip its prefill compute and block "
         "allocations (radix prefix caching)")
_declare("PTPU_SERVE_SPEC_K", "int", 0,
         "speculative decoding: draft tokens proposed per serving "
         "decode step and verified in one batched target step "
         "(0 = legacy one-token decode)")
_declare("PTPU_SERVE_SPEC_TREE", "str", None,
         "tree speculation shape 'WxD' (width x depth, e.g. '2x3'): "
         "verify a W-branch token tree of depth D per compiled step "
         "via the in-window tree attention mask; unset/0/off = the "
         "linear PTPU_SERVE_SPEC_K window, bitwise PR-12 behavior")
_declare("PTPU_SERVE_DRAFT_MODEL", "path", None,
         "generation-artifact directory holding the draft model for "
         "speculative decoding: loads a jitted on-device ModelDrafter "
         "per engine model (unset = n-gram prompt-lookup drafting)")
_declare("PTPU_SERVE_DRAFT_CHUNK", "int", 16,
         "prompt tokens per draft-side catch-up prefill chunk when the "
         "jitted ModelDrafter brings a row's draft KV level with its "
         "committed history")
_declare("PTPU_SERVE_REPLICAS", "int", 1,
         "ServingRouter engine-replica count (least-loaded dispatch "
         "with health-checked failover across them)")
_declare("PTPU_SERVE_DEADLINE_S", "float", None,
         "per-request serving deadline in seconds: requests past it "
         "fail with DeadlineExceededError at the next step boundary "
         "(unset = wait forever, the legacy behavior)")
_declare("PTPU_SERVE_RETRY_BUDGET", "int", 3,
         "re-admission attempts the ServingRouter may spend per "
         "request when its replica fails over (exponential backoff; "
         "RetryBudgetExceededError when spent)")
_declare("PTPU_SERVE_CANARY_PCT", "float", None,
         "percentage of new requests the ServingRouter pins to the "
         "canary replica while an OnlineUpdater rollout is in its "
         "canary phase (docs/SERVING.md \"Online updates\"; unset = "
         "no canary gate, router/engine stay bitwise-legacy)")
_declare("PTPU_ONLINE_POLL_S", "float", 0.25,
         "OnlineUpdater checkpoint-directory poll interval in seconds "
         "(the cadence at which a live trainer's newly landed intact "
         "checkpoints are discovered and exported)")
# -- concurrency analysis (docs/STATIC_ANALYSIS.md) -------------------------
_declare("PTPU_LOCK_CHECK", "bool", False,
         "route the runtime's named lock sites through tracked "
         "wrappers: lock-order/deadlock detection, "
         "blocking-while-holding checks and the pool/engine invariant "
         "hooks (unset = plain threading primitives, zero overhead)")
_declare("PTPU_LOCK_HOLD_MS", "float", None,
         "with PTPU_LOCK_CHECK=1, report a long-hold violation when a "
         "tracked lock is held longer than this many milliseconds "
         "(unset = off)")
# -- Pallas kernel dispatch (docs/KERNELS.md) -------------------------------
_declare("PTPU_KERNELS", "bool", None,
         "Pallas kernel dispatch mode: 1 forces every registered kernel "
         "on (interpret mode off-TPU — the CI/test spelling), 0 forces "
         "the lax fallbacks bitwise, unset keeps each kernel's default "
         "platform policy")
_declare("PTPU_KERNELS_DISABLE", "str", None,
         "comma-separated kernel names pinned to their lax fallback "
         "regardless of PTPU_KERNELS (names: docs/KERNELS.md "
         "qualification table)")
# -- recommender embedding fast path (docs/RECOMMENDER.md) ------------------
_declare("PTPU_EMBED_PREFETCH", "bool", False,
         "stage host-embedding rows one step ahead: train_from_dataset "
         "announces batch t+1's ids to a background gather worker and "
         "the compiled step reads the deduped row buffer as an ordinary "
         "device feed instead of a blocking in-step pure_callback pull "
         "(unset = the exact legacy synchronous lookup)")
_declare("PTPU_EMBED_CACHE_ROWS", "int", 0,
         "with PTPU_EMBED_PREFETCH=1, keep this many hot embedding rows "
         "resident in a device-side cache with frequency admission + LRU "
         "eviction; 0 = no cache (prefetch buffer only)")
_declare("PTPU_EMBED_CACHE_ADMIT", "int", 2,
         "admission threshold for the hot-row cache: a row enters the "
         "cache once it has been touched by this many distinct batches")
_declare("PTPU_EMBED_PUSH_QUEUE", "int", 64,
         "Communicator async-push queue bound per table; a full queue "
         "blocks the enqueueing (training) thread until the drain "
         "thread catches up (backpressure, embed/push_queue_depth "
         "gauge)")
# -- tests / CI -------------------------------------------------------------
_declare("PTPU_PARITY_TIMEOUT", "float", 45.0,
         "seconds the TPU-backend parity test waits on its subprocess "
         "before skipping")


def env(name):
    """Read one declared PTPU_* environment switch: the parsed value, or
    the declared default when unset/empty. Reads the environment at CALL
    time (no import-time latch). Unknown names raise — declare the flag
    here first (the linter enforces the same rule statically)."""
    spec = _ENV_REGISTRY.get(name)
    if spec is None:
        raise KeyError(
            "undeclared environment flag %r — add it to the "
            "paddle_tpu.flags registry (see docs/STATIC_ANALYSIS.md)"
            % (name,))
    return spec.parse(os.environ.get(name, ""))


def declared_flags():
    """{name: EnvFlag} snapshot of the PTPU_* registry (the linter's and
    describe()'s source of truth)."""
    return dict(_ENV_REGISTRY)


def describe():
    """The PTPU_* registry as an aligned text table (name, type, default,
    description) — the contract surface docs and the linter check
    against."""
    rows = [("Flag", "Type", "Default", "Description")]
    for name in sorted(_ENV_REGISTRY):
        spec = _ENV_REGISTRY[name]
        rows.append((name, spec.type,
                     "-" if spec.default is None else repr(spec.default),
                     spec.doc))
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    w2 = max(len(r[2]) for r in rows)
    return "\n".join("%-*s  %-*s  %-*s  %s" % (w0, r[0], w1, r[1],
                                               w2, r[2], r[3])
                     for r in rows)
