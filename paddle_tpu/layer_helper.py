"""LayerHelper (parity: python/paddle/fluid/layer_helper.py:42) — the funnel
through which every layer creates params (with startup-program init ops) and
appends ops to the current main-program block.
"""

from . import framework, unique_name
from .framework import Variable, default_main_program, default_startup_program
from .initializer import Constant, Xavier
from .param_attr import ParamAttr

_op_seed_counter = [1000]


def next_op_seed():
    _op_seed_counter[0] += 1
    return _op_seed_counter[0]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        if name is None:
            self.name = unique_name.generate(layer_type)
        else:
            self.name = name

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # -- params -------------------------------------------------------------
    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        attr = self.kwargs.get("bias_attr")
        if attr is False:
            return None
        return ParamAttr._to_attr(attr)

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr] + [
                ParamAttr(**{k: getattr(attr, k) for k in (
                    "initializer", "learning_rate", "regularizer", "trainable",
                    "gradient_clip", "do_model_average")})
                for _ in range(length - 1)
            ]
        if len(attr) != length:
            raise ValueError("param_attr length mismatch")
        return attr

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        suffix = "b" if is_bias else "w"
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, suffix]))
        init = attr.initializer or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else Xavier()
        startup_gb = self.startup_program.global_block()
        main_gb = self.main_program.global_block()
        # the param lives in the main program; its init op goes to startup
        if main_gb.has_var(attr.name):
            return main_gb.var(attr.name)
        param = main_gb.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs()
        )
        param.initializer = init
        sp = framework.Parameter(
            startup_gb, shape=shape, dtype=dtype, name=attr.name,
            trainable=attr.trainable,
        )
        startup_gb.vars[sp.name] = sp
        init(sp, startup_gb)
        return param

    # -- vars ---------------------------------------------------------------
    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.block.create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        gb = self.main_program.global_block()
        return gb.create_var(
            *args,
            persistable=persistable,
            name=kwargs.pop("name", unique_name.generate(".".join([self.name, "tmp"]))),
            **kwargs,
        )

    def create_or_get_global_variable(self, name, *args, **kwargs):
        gb = self.main_program.global_block()
        if gb.has_var(name):
            return gb.var(name)
        return gb.create_var(name=name, *args, **kwargs)

    def set_variable_initializer(self, var, initializer):
        sgb = self.startup_program.global_block()
        if not sgb.has_var(var.name):
            sv = sgb.create_var(
                name=var.name, shape=var.shape, dtype=var.dtype,
                persistable=True,
            )
        else:
            sv = sgb.var(var.name)
        initializer(sv, sgb)
        return var

    # -- ops ----------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        attrs = dict(attrs or {})
        from .ops import registry as _reg

        if _reg.has(type) and _reg.get(type).stateful:
            attrs.setdefault("__op_seed__", next_op_seed())
        return self.block.append_op(
            type=type, inputs=inputs, outputs=outputs, attrs=attrs
        )

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype,
                                  is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        tmp.shape = input_var.shape
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]},
            attrs=act,
        )
        tmp.shape = input_var.shape
        return tmp

    def input_dtype(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name)
        if isinstance(inputs, Variable):
            return inputs.dtype
        return inputs[0].dtype
