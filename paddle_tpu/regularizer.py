"""Weight-decay regularizers (parity: python/paddle/fluid/regularizer.py —
L1Decay/L2Decay; append_regularization_ops)."""

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(
            type="scale", inputs={"X": [param]}, outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        decay.shape = param.shape
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        sign.shape = param.shape
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(
            type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        decay.shape = param.shape
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Add decay terms into gradients (parity: regularizer.py
    append_regularization_ops)."""
    helper = LayerHelper("regularization")
    out = []
    for param, grad in parameters_and_grads:
        regular = getattr(param, "regularizer", None) or regularization
        if grad is None or regular is None:
            out.append((param, grad))
            continue
        block = grad.block
        decay = regular(param, grad, block)
        new_grad = helper.create_variable_for_type_inference(grad.dtype)
        block.append_op(
            type="elementwise_add", inputs={"X": [grad], "Y": [decay]},
            outputs={"Out": [new_grad]},
        )
        new_grad.shape = grad.shape
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
