"""ParamAttr / WeightNormParamAttr (parity: python/paddle/fluid/param_attr.py)."""

from .initializer import Initializer, Xavier

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        gradient_clip=None,
        do_model_average=False,
        shard_spec=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        # TPU-native: explicit PartitionSpec dims over the step mesh, e.g.
        # (None, "tp") column-shards an fc weight. Consumed by
        # parallel/planner.py; None = let the planner auto-derive.
        self.shard_spec = shard_spec

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else ParamAttr(trainable=False)
        raise TypeError("cannot interpret %r as ParamAttr" % (arg,))

    def _to_kwargs(self, with_initializer=False):
        kw = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
            "shard_spec": self.shard_spec,
        }
        if with_initializer:
            kw["initializer"] = self.initializer
        return kw


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
