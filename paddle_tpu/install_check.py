"""Install self-check (parity: python/paddle/fluid/install_check.py —
run_check() trains a tiny linear model single-device and, when more than
one device is visible, data-parallel, then prints the all-clear)."""

import numpy as np

__all__ = ["run_check"]


def run_check():
    """Build + train a 2-layer model one step on one device, and across
    all visible devices when there are several. Raises on failure; prints
    a success message like the reference."""
    import jax

    from . import (CPUPlace, Executor, ParallelExecutor, Program, TPUPlace,
                   layers, optimizer, program_guard)
    from .framework import switch_main_program, switch_startup_program

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data(name="inst_chk_x", shape=[4], dtype="float32")
        y = layers.data(name="inst_chk_y", shape=[1], dtype="float32")
        pred = layers.fc(input=x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.SGD(learning_rate=0.01).minimize(loss)

    place = TPUPlace(0) if jax.default_backend() != "cpu" else CPUPlace()
    exe = Executor(place)
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"inst_chk_x": rng.rand(8, 4).astype(np.float32),
            "inst_chk_y": rng.rand(8, 1).astype(np.float32)}
    out, = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(out)).all(), "single-device check failed"

    n_dev = len(jax.devices())
    if n_dev > 1:
        pe = ParallelExecutor(loss_name=loss.name, main_program=main)
        out, = pe.run(feed=feed, fetch_list=[loss.name])
        assert np.isfinite(np.asarray(out)).all(), "multi-device check failed"
        print("Your paddle_tpu works well on MULTIPLE devices (%d)!" % n_dev)
    else:
        print("Your paddle_tpu works well on SINGLE device.")
    print("Your paddle_tpu is installed successfully! Let's start deep "
          "Learning with paddle_tpu now")
