"""Static autodiff: append_backward (parity: python/paddle/fluid/backward.py:394
+ the C++ GradOpDescMaker machinery, framework/grad_op_desc_maker.h).

Walks the block's op list in reverse from the loss, appending one `<type>_grad`
op per differentiable forward op. Grad ops are *generic*: they carry a
reference to their forward op and are lowered via `jax.vjp` of the forward
kernel (core/lowering.py:_execute_grad_op) — per-op grad kernels are never
hand-written. When a var feeds several ops, its gradient contributions are
accumulated (Fluid inserts `sum` ops; here accumulation is tagged on the grad
op and fused by XLA).
"""

from . import framework
from .framework import grad_var_name
from .ops import registry

__all__ = ["append_backward", "gradients"]


def _is_grad_op(op):
    return "__fwd_op__" in op.attrs


def _base_fwd(op):
    """Peel grad-of-grad chains down to the primitive forward op (shared
    with the lowering — one definition, core/lowering.py)."""
    from .core.lowering import _base_fwd as impl

    return impl(op)


def _collect_need_grad(block, params, no_grad_set, extra_leaves=()):
    """Forward pass: which vars lie on a differentiable path from trainables
    (or from `extra_leaves` — arbitrary vars the caller wants grads for)."""
    need = set()
    for p in params:
        if p.name not in no_grad_set:
            need.add(p.name)
    for name in extra_leaves:
        if name not in no_grad_set:
            need.add(name)
    for op in block.ops:
        if _is_grad_op(op):
            # grad ops ARE differentiable (their kernel is jax.vjp of the
            # forward, itself built from traced primitives) — this is what
            # makes fluid.gradients-of-a-gradient flow. Their outputs are
            # created stop_gradient=True (they're leaves of pass N), so
            # bypass that flag here: pass N+1 may differentiate through.
            nondiff = registry.get(_base_fwd(op).type).nondiff_inputs
            hit = any(
                v.name in need
                for slot, vs in op.inputs.items()
                if slot not in nondiff
                for v in vs)
            if hit:
                for vs in op.outputs.values():
                    for v in vs:
                        if v.name not in no_grad_set:
                            need.add(v.name)
            continue
        if not registry.has(op.type):
            continue
        opdef = registry.get(op.type)
        if not opdef.differentiable:
            continue
        hit = False
        for slot, vs in op.inputs.items():
            if slot in opdef.nondiff_inputs:
                continue
            if any(v.name in need for v in vs):
                hit = True
                break
        if hit:
            for vs in op.outputs.values():
                for v in vs:
                    if not v.stop_gradient and v.name not in no_grad_set:
                        need.add(v.name)
    return need


def _create_grad_var(block, primal, gname):
    if block.has_var(gname):
        return block.var(gname)
    return block.create_var(
        name=gname,
        shape=primal.shape,
        dtype=primal.dtype,
        stop_gradient=True,
    )


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None, _extra_leaves=(),
                    _target_gradients=None, _update_param_map=True):
    """Append grad ops computing d loss / d param for every trainable param.

    Returns list of (param Variable, grad Variable).
    """
    program = loss.block.program
    block = program.global_block()
    no_grad_set = set(no_grad_set or ())

    if parameter_list:
        params = []
        for p in parameter_list:
            name = p if isinstance(p, str) else p.name
            params.append(block.var(name))
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    need_grad = _collect_need_grad(block, params, no_grad_set, _extra_leaves)

    # locate the op producing the loss
    loss_idx = None
    for i in reversed(range(len(block.ops))):
        if loss.name in block.ops[i].output_names():
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError("loss var %r is not produced by any op" % loss.name)

    program._appending_grad_times += 1
    # Repeated backward passes (fluid.gradients of a gradient, or minimize
    # after a gradient-penalty gradients() call) must NOT reuse pass-1's
    # @GRAD names — resolving x@GRAD to the stale first-order var is how
    # the reference's calc_gradient rename machinery (backward.py
    # _rename_grad_) avoids silent wrong answers; here a per-pass suffix
    # does the same.
    _suffix = ("" if program._appending_grad_times <= 1
               else "@%d" % program._appending_grad_times)

    def _g(name):
        return grad_var_name(name) + _suffix

    # seed gradient: d loss / d loss = 1 (or the caller-supplied cotangent)
    loss_grad_name = _g(loss.name)
    loss_grad = _create_grad_var(block, loss, loss_grad_name)
    if _target_gradients is not None:
        block.append_op(
            type="assign",
            inputs={"X": [_target_gradients]},
            outputs={"Out": [loss_grad]},
            attrs={"__op_role__": "backward"},
        )
    else:
        # fill_any_like (not fill_constant) so targets with symbolic -1
        # batch dims get their cotangent shape from the runtime value
        # __loss_seed__ marks ONLY the executor-level training seed (the
        # one ScaleLossGradOpHandle scales in the reference) — gradients()
        # passes _update_param_map=False and its seeds must NOT pick up
        # GradientScaleStrategy scaling, or in-program fluid.gradients
        # values would change under `One`
        block.append_op(
            type="fill_any_like",
            inputs={"X": [loss]},
            outputs={"Out": [loss_grad]},
            attrs={"value": 1.0, "__op_role__": "backward",
                   "__loss_seed__": bool(_update_param_map)},
        )

    grad_map = {loss.name: loss_grad_name}  # primal name -> grad var name

    fwd_ops = list(block.ops[: loss_idx + 1])
    for op in reversed(fwd_ops):
        if _is_grad_op(op):
            # differentiate a grad op appended by an earlier backward pass:
            # generic like any primitive — lowering executes it via
            # vjp-of-vjp (reference registers bespoke *_grad_grad ops,
            # elementwise_add_op.cc:23-72; here every op composes at once)
            opdef = registry.get(_base_fwd(op).type)
        elif not registry.has(op.type):
            continue
        else:
            opdef = registry.get(op.type)
            if not opdef.differentiable:
                continue
        # upstream grads available for any output?
        gout_map = {}
        any_gout = False
        for slot, vs in op.outputs.items():
            names = []
            for v in vs:
                g = grad_map.get(v.name)
                names.append(g)
                if g is not None:
                    any_gout = True
            gout_map[slot] = names
        if not any_gout:
            continue
        if op.type == "while" and not op.attrs.get("max_trip_count"):
            raise RuntimeError(
                "gradient demanded through a While loop with no "
                "max_trip_count: a fully-dynamic lax.while_loop has no "
                "reverse-mode rule. Build it as "
                "fluid.layers.While(cond, max_trip_count=N) (lax.scan of "
                "N masked steps), or use StaticRNN/DynamicRNN for "
                "recurrences.")
        # vars whose upstream cotangent THIS op consumes (it appears as an
        # output with a live grad). When such a var is ALSO an input under
        # the same name (in-place ops: While carries, increment, assign-
        # into), the vjp-computed input grad must REPLACE the grad var —
        # accumulating would double-count the cotangent the op just
        # consumed.
        consumed = set()
        for slot, vs in op.outputs.items():
            for i, v in enumerate(vs):
                if i < len(gout_map[slot]) and gout_map[slot][i] is not None:
                    consumed.add(v.name)

        # inputs that require grads
        gin_map = {}
        accumulate = {}
        grad_out_vars = []
        grad_out_seen = set()
        any_gin = False
        for slot, vs in op.inputs.items():
            if slot in opdef.nondiff_inputs:
                gin_map[slot] = [None] * len(vs)
                continue
            names = []
            for v in vs:
                if v.name not in need_grad or v.name in no_grad_set:
                    names.append(None)
                    continue
                gname = _g(v.name)
                gv = _create_grad_var(block, v, gname)
                if v.name in grad_map and v.name not in consumed:
                    # a later consumer already produced this grad: accumulate
                    accumulate[gname] = True
                else:
                    grad_map[v.name] = gname
                names.append(gname)
                if gname not in grad_out_seen:
                    grad_out_seen.add(gname)
                    grad_out_vars.append(gv)
                any_gin = True
            gin_map[slot] = names
        if not any_gin:
            continue

        grad_inputs = dict(op.inputs)
        gout_vars = {}
        cot_slots = {}
        for slot, vs in op.outputs.items():
            gvs = [block.var(g) for g in gout_map[slot] if g is not None]
            if gvs:
                key = slot + "@GRAD"
                while key in grad_inputs or key in gout_vars:
                    key += "_"   # grad-of-grad: "InputGrads@GRAD" may recur
                gout_vars[key] = gvs
                cot_slots[slot] = key
        grad_inputs = {**grad_inputs, **gout_vars}

        block.append_op(
            type=op.type + "_grad",
            inputs=grad_inputs,
            outputs={"InputGrads": grad_out_vars},
            attrs={
                "__fwd_op__": op,
                "__grad_out_map__": gout_map,
                "__grad_in_map__": gin_map,
                "__accumulate__": accumulate,
                "__cot_slots__": cot_slots,
                "__op_role__": "backward",
            },
        )

    params_and_grads = []
    for p in params:
        gname = grad_map.get(p.name)
        if gname is None:
            continue
        g = block.var(gname)
        params_and_grads.append((p, g))
    program._last_grad_map = dict(grad_map)
    if _update_param_map:
        program.param_grad_map.update(
            {p.name: g.name for p, g in params_and_grads}
        )
    return params_and_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Compute grads of targets wrt arbitrary inputs — data vars and
    activations included, not only parameters (parity: fluid.gradients /
    backward.py calc_gradient)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    leaves = tuple(v.name for v in inputs)
    if len(targets) == 1 and target_gradients is None:
        append_backward(targets[0], parameter_list=None,
                        no_grad_set=no_grad_set, _extra_leaves=leaves,
                        _update_param_map=False)
    else:
        # multiple targets / explicit cotangents: differentiate the scalar
        # L = Σ_i sum(y_i ⊙ tg_i), whose gradient is the accumulated
        # per-target contribution (Fluid calc_gradient semantics)
        from . import layers

        with framework.program_guard(targets[0].block.program):
            parts = []
            for i, y in enumerate(targets):
                tg = None
                if target_gradients is not None and i < len(target_gradients):
                    tg = target_gradients[i]
                term = y if tg is None else layers.elementwise_mul(y, tg)
                parts.append(layers.reduce_sum(term))
            total = parts[0] if len(parts) == 1 else layers.sums(parts)
            append_backward(total, parameter_list=None,
                            no_grad_set=no_grad_set, _extra_leaves=leaves,
                            _update_param_map=False)
    block = targets[0].block
    program = block.program
    # read THIS pass's grad names (suffixed on repeated passes) — never the
    # plain @GRAD lookup, which on a second call resolves to pass 1's var
    grad_map = getattr(program, "_last_grad_map", {})
    outs = []
    for v in inputs:
        gname = grad_map.get(v.name)
        outs.append(block.var(gname) if gname is not None
                    and block.has_var(gname) else None)
    return outs
