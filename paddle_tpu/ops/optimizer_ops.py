"""Optimizer update ops (parity: operators/optimizers/ — sgd_op.cc,
momentum_op.cc, adam_op.h (fused CPU/GPU Adam), adagrad, rmsprop, lamb,
lars_momentum, ftrl, adadelta, adamax, decayed_adagrad, proximal_*).

Each op consumes Param (+ accumulator state) and Grad and produces ParamOut
(+ state outs) aliasing the same persistable variables; the executor writes
them back to the device-resident store with buffer donation, so updates are
in-place at the XLA level. All state math in fp32 regardless of param dtype
(master-weight behavior comes from the mixed-precision decorator).
"""

import jax.numpy as jnp

from .registry import register


def _p(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


def _lr(ins):
    return _p(ins, "LearningRate").reshape(())


def _g32(ins):
    """The incoming gradient cast to fp32 exactly ONCE — under AMP
    (docs/MIXED_PRECISION.md) gradients arrive in bf16 and every update
    applies to the fp32 master math; for fp32 gradients this is a
    no-op (bitwise identical update)."""
    return _p(ins, "Grad").astype(jnp.float32)


@register("sgd", differentiable=False)
def _sgd(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    lr = _lr(ins)
    # update math in fp32 even for bf16 params (a bf16 lr*g product under-
    # flows tiny updates); the rounding happens once, on the write-back
    new = (p.astype(jnp.float32)
           - lr.astype(jnp.float32) * g.astype(jnp.float32))
    return {"ParamOut": [new.astype(p.dtype)]}


@register("momentum", differentiable=False)
def _momentum(ctx, ins, attrs):
    p, g, v = _p(ins, "Param"), _g32(ins), _p(ins, "Velocity")
    lr = _lr(ins)
    mu = attrs.get("mu", 0.9)
    use_nesterov = attrs.get("use_nesterov", False)
    v_out = mu * v + g
    if use_nesterov:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out.astype(p.dtype)], "VelocityOut": [v_out]}


@register("lars_momentum", differentiable=False)
def _lars_momentum(ctx, ins, attrs):
    p, g, v = _p(ins, "Param"), _g32(ins), _p(ins, "Velocity")
    lr = _lr(ins)
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    pn = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
    gn = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
    local_lr = lr * coeff * pn / (gn + decay * pn + 1e-12)
    v_out = mu * v + local_lr * (g + decay * p)
    p_out = p - v_out
    return {"ParamOut": [p_out.astype(p.dtype)], "VelocityOut": [v_out]}


@register("adam", differentiable=False)
def _adam(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    m, v = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p = _p(ins, "Beta1Pow").reshape(())
    b2p = _p(ins, "Beta2Pow").reshape(())
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    m_out = b1 * m + (1.0 - b1) * gf
    v_out = b2 * v + (1.0 - b2) * gf * gf
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    p_out = p.astype(jnp.float32) - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m_out],
        "Moment2Out": [v_out],
        "Beta1PowOut": [(b1p * b1).reshape((1,))],
        "Beta2PowOut": [(b2p * b2).reshape((1,))],
    }


@register("adamax", differentiable=False)
def _adamax(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _g32(ins)
    m, inf_norm = _p(ins, "Moment"), _p(ins, "InfNorm")
    b1p = _p(ins, "Beta1Pow").reshape(())
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1.0 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    p_out = p - (lr / (1.0 - b1p)) * m_out / (inf_out + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out],
            "InfNormOut": [inf_out]}


@register("adagrad", differentiable=False)
def _adagrad(ctx, ins, attrs):
    p, g, mom = _p(ins, "Param"), _g32(ins), _p(ins, "Moment")
    lr = _lr(ins)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = mom + g * g
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [mom_out]}


@register("decayed_adagrad", differentiable=False)
def _decayed_adagrad(ctx, ins, attrs):
    p, g, mom = _p(ins, "Param"), _g32(ins), _p(ins, "Moment")
    lr = _lr(ins)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1.0 - decay) * g * g
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [mom_out]}


@register("adadelta", differentiable=False)
def _adadelta(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _g32(ins)
    avg_sq_g = _p(ins, "AvgSquaredGrad")
    avg_sq_u = _p(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1.0 - rho) * g * g
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1.0 - rho) * upd * upd
    return {"ParamOut": [(p + upd).astype(p.dtype)],
            "AvgSquaredGradOut": [g2], "AvgSquaredUpdateOut": [u2]}


@register("rmsprop", differentiable=False)
def _rmsprop(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _g32(ins)
    ms, mom = _p(ins, "MeanSquare"), _p(ins, "Moment")
    lr = _lr(ins)
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    ms_out = rho * ms + (1.0 - rho) * g * g
    if centered:
        mg = _p(ins, "MeanGrad")
        mg_out = rho * mg + (1.0 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
    else:
        mg_out = None
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    outs = {"ParamOut": [(p - mom_out).astype(p.dtype)],
            "MeanSquareOut": [ms_out], "MomentOut": [mom_out]}
    if mg_out is not None:
        outs["MeanGradOut"] = [mg_out]
    return outs


@register("ftrl", differentiable=False)
def _ftrl(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _g32(ins)
    sq, lin = _p(ins, "SquaredAccumulator"), _p(ins, "LinearAccumulator")
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq + g * g
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** (-power) - sq ** (-power)) / lr
    lin_out = lin + g - sigma * p
    if power == -0.5:
        x = l1 * jnp.sign(lin_out) - lin_out
        y = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        x = l1 * jnp.sign(lin_out) - lin_out
        y = new_sq ** (-power) / lr + 2 * l2
    p_out = jnp.where(jnp.abs(lin_out) > l1, x / y, jnp.zeros_like(p))
    return {"ParamOut": [p_out.astype(p.dtype)], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [lin_out]}


@register("lamb", differentiable=False)
def _lamb(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    m, v = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p = _p(ins, "Beta1Pow").reshape(())
    b2p = _p(ins, "Beta2Pow").reshape(())
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m_out = b1 * m + (1.0 - b1) * gf
    v_out = b2 * v + (1.0 - b2) * gf * gf
    m_hat = m_out / (1.0 - b1p)
    v_hat = v_out / (1.0 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * pf
    p_norm = jnp.sqrt(jnp.sum(pf * pf))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    ratio = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_out = pf - lr * ratio * r
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m_out],
        "Moment2Out": [v_out],
        "Beta1PowOut": [(b1p * b1).reshape((1,))],
        "Beta2PowOut": [(b2p * b2).reshape((1,))],
    }


@register("proximal_gd", differentiable=False)
def _proximal_gd(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _g32(ins)
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (
        1.0 + lr * l2)
    return {"ParamOut": [p_out.astype(p.dtype)]}


@register("proximal_adagrad", differentiable=False)
def _proximal_adagrad(ctx, ins, attrs):
    p, g, mom = _p(ins, "Param"), _g32(ins), _p(ins, "Moment")
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mom_out = mom + g * g
    eff_lr = lr / jnp.sqrt(mom_out)
    prox = p - eff_lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0) / (
        1.0 + eff_lr * l2)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [mom_out]}


@register("dgc_momentum", differentiable=False)
def _dgc_momentum(ctx, ins, attrs):
    # falls back to plain momentum update (the DGC sparse path lives in
    # parallel/dgc.py — top-k compress before the allreduce)
    return _momentum(ctx, ins, attrs)


@register("average_accumulates", differentiable=False)
def _average_accumulates(ctx, ins, attrs):
    param = _p(ins, "param")
    sum1 = _p(ins, "in_sum_1")
    sum2 = _p(ins, "in_sum_2")
    sum3 = _p(ins, "in_sum_3")
    num_acc = _p(ins, "in_num_accumulates").reshape(())
    old_num = _p(ins, "in_old_num_accumulates").reshape(())
    num_upd = _p(ins, "in_num_updates").reshape(())
    avg_window = attrs.get("average_window", 0.15)
    max_avg = attrs.get("max_average_window", 10000)
    min_avg = attrs.get("min_average_window", 10000)
    num_acc = num_acc + 1
    num_upd = num_upd + 1
    sum1 = sum1 + param
    window = jnp.minimum(jnp.maximum(min_avg, num_upd * avg_window), max_avg)
    do_shift = num_acc >= window
    sum2_n = jnp.where(do_shift, sum2 + sum1, sum2)
    sum1_n = jnp.where(do_shift, jnp.zeros_like(sum1), sum1)
    old_num_n = jnp.where(do_shift, num_acc + old_num, old_num)
    num_acc_n = jnp.where(do_shift, 0, num_acc)
    # second-level shift
    do_shift2 = old_num_n >= max_avg
    sum3_n = jnp.where(do_shift2, sum2_n, sum3)
    sum2_nn = jnp.where(do_shift2, jnp.zeros_like(sum2), sum2_n)
    old_num_nn = jnp.where(do_shift2, 0, old_num_n)
    return {
        "out_sum_1": [sum1_n],
        "out_sum_2": [sum2_nn],
        "out_sum_3": [sum3_n],
        "out_num_accumulates": [num_acc_n.astype(jnp.int64).reshape((1,))],
        "out_old_num_accumulates": [old_num_nn.astype(jnp.int64).reshape((1,))],
        "out_num_updates": [num_upd.astype(jnp.int64).reshape((1,))],
    }
