"""Quantization + mixed-precision ops (parity: the fake_quantize_* family
operators/fake_quantize_op.cc, fake_dequantize_op.cc, quantize/dequantize/
requantize mkldnn ops, and the AMP loss-scaling helpers the reference
implements inside contrib/mixed_precision/decorator.py:127-147).

Fake quantization simulates int8/intN rounding in fp32 so QAT gradients
flow (straight-through estimator via jnp.round's zero gradient being
replaced by identity in the custom pair below)."""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _quantize_ste(x, scale, bits):
    """Quantize-dequantize with straight-through gradient."""
    bnt = (1 << (bits - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(x / s, -1.0, 1.0)
    # round with straight-through estimator: grad(round) := 1
    rounded = q + jax.lax.stop_gradient(jnp.round(q * bnt) / bnt - q)
    return rounded * s


@register("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    out = _quantize_ste(x, scale, bits)
    return {"Out": [out], "OutScale": [scale.reshape((1,))]}


@register("fake_channel_wise_quantize_abs_max")
def _fake_channel_wise_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]  # [C_out, ...] conv filter layout
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x.reshape((x.shape[0], -1))), axis=1)
    shape = (-1,) + (1,) * (x.ndim - 1)
    out = _quantize_ste(x, scale.reshape(shape), bits)
    return {"Out": [out], "OutScale": [scale]}


@register("fake_quantize_range_abs_max")
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Train-time: sliding max over a window approximated by the running
    max update rule of the reference (range_abs_max)."""
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    is_test = attrs.get("is_test", False) or ctx.is_test
    in_scale = ins["InScale"][0].reshape(())
    cur = jnp.max(jnp.abs(x))
    scale = in_scale if is_test else jnp.maximum(cur, in_scale)
    out = _quantize_ste(x, scale, bits)
    return {"Out": [out], "OutScale": [scale.reshape((1,))],
            "OutScales": [scale.reshape((1,))]}


@register("fake_quantize_moving_average_abs_max")
def _fake_quantize_moving_average_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    in_scale = ins["InScale"][0].reshape(())
    cur = jnp.max(jnp.abs(x))
    scale = in_scale if is_test else rate * in_scale + (1 - rate) * cur
    out = _quantize_ste(x, scale, bits)
    return {"Out": [out], "OutScale": [scale.reshape((1,))]}


@register("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_moving_average(ctx, ins, attrs):
    return _fake_quantize_moving_average_abs_max(ctx, ins, attrs)


@register("moving_average_abs_max_scale", differentiable=False)
def _moving_average_abs_max_scale(ctx, ins, attrs):
    x = ins["X"][0]
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(())
    cur = jnp.max(jnp.abs(x))
    scale = rate * in_scale + (1 - rate) * cur
    return {"Out": [x], "OutScale": [scale.reshape((1,))]}


@register("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx, ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": [x * scale / max_range]}


@register("fake_channel_wise_dequantize_max_abs")
def _fake_channel_wise_dequantize_max_abs(ctx, ins, attrs):
    x = ins["X"][0]
    scales = ins["Scales"]
    quant_bits = attrs.get("quant_bits", [8])
    out = x
    s0 = scales[0].reshape((-1,) + (1,) * (x.ndim - 1))
    out = out * s0 / float((1 << (quant_bits[0] - 1)) - 1)
    if len(scales) > 1 and len(quant_bits) > 1:
        out = out * scales[1].reshape(()) / float(
            (1 << (quant_bits[1] - 1)) - 1)
    return {"Out": [out]}


@register("quantize", differentiable=False)
def _quantize(ctx, ins, attrs):
    x = ins["Input"][0]
    scale = attrs.get("Scale", 1.0)
    return {"Output": [jnp.clip(jnp.round(x * scale), -128, 127)
                       .astype(jnp.int8)]}


@register("dequantize", differentiable=False)
def _dequantize(ctx, ins, attrs):
    x = ins["Input"][0]
    scale = attrs.get("Scale", 1.0)
    out = x.astype(jnp.float32) / scale
    # out_dtype keeps a converted fp16/bf16 weight at its declared dtype
    # (convert_to_int8 sets it; reference preserves the weight var dtype)
    od = attrs.get("out_dtype")
    if od is not None:
        out = out.astype(od)
    return {"Output": [out]}


@register("dequantize_linear", differentiable=False)
def _dequantize_linear(ctx, ins, attrs):
    """Per-channel linear dequantization (the quant_rewrite pass's
    counterpart to `quantize`): Output = float(Input) * Scale, where
    Scale is an array already SHAPED for plain numpy broadcasting onto
    Input — per-output-column vectors for matmul/mul weights and
    accumulators, (C_out, 1, ..) for conv filters/outputs (paddle_tpu/
    quant.py bakes it that way, dequantize_linear in the reference op
    set)."""
    x = ins["Input"][0]
    scale = ins["Scale"][0]
    out = x.astype(jnp.float32) * scale
    od = attrs.get("out_dtype")
    if od is not None and str(od) != "float32":
        out = out.astype(od)
    return {"Output": [out]}


@register("requantize", differentiable=False)
def _requantize(ctx, ins, attrs):
    x = ins["Input"][0]
    s_in = attrs.get("Scale_in", 1.0)
    s_out = attrs.get("Scale_out", 1.0)
    return {"Output": [jnp.clip(jnp.round(x.astype(jnp.float32)
                                          / s_in * s_out), -128, 127)
                       .astype(jnp.int8)]}


# ---------------------------------------------------------------------------
# AMP loss-scaling helpers (contrib/mixed_precision parity; the reference
# does this in python graph ops, amp_ops in later versions)
# ---------------------------------------------------------------------------


@register("check_finite_and_unscale")
def _check_finite_and_unscale(ctx, ins, attrs):
    grads = ins["X"]
    scale = ins["Scale"][0].reshape(())
    finite = jnp.asarray(True)
    for g in grads:
        finite = finite & jnp.all(jnp.isfinite(g))
    outs = [jnp.where(finite, g / scale, jnp.zeros_like(g)) for g in grads]
    return {"Out": outs, "FoundInfinite": [(~finite).reshape((1,))]}


@register("update_loss_scaling", differentiable=False)
def _update_loss_scaling(ctx, ins, attrs):
    """Dynamic loss scaling state machine (decorator.py:127-147): double the
    scale after incr_every_n consecutive finite steps, halve on overflow."""
    scale = ins["PrevLossScaling"][0].reshape(())
    good = ins["InGoodSteps"][0].reshape(()).astype(jnp.int32)
    bad = ins["InBadSteps"][0].reshape(()).astype(jnp.int32)
    found_inf = ins["FoundInfinite"][0].reshape(()).astype(bool)
    incr_every = attrs.get("incr_every_n_steps", 1000)
    decr_every = attrs.get("decr_every_n_nan_or_inf", 2)
    incr_ratio = attrs.get("incr_ratio", 2.0)
    decr_ratio = attrs.get("decr_ratio", 0.5)

    good_n = jnp.where(found_inf, 0, good + 1)
    bad_n = jnp.where(found_inf, bad + 1, 0)
    grow = (~found_inf) & (good_n >= incr_every)
    shrink = found_inf & (bad_n >= decr_every)
    new_scale = jnp.where(grow, scale * incr_ratio,
                          jnp.where(shrink,
                                    jnp.maximum(scale * decr_ratio, 1.0),
                                    scale))
    good_n = jnp.where(grow, 0, good_n)
    bad_n = jnp.where(shrink, 0, bad_n)
    return {"LossScaling": [new_scale.reshape((1,))],
            "OutGoodSteps": [good_n.reshape((1,))],
            "OutBadSteps": [bad_n.reshape((1,))]}
