"""Sequence ops (parity: operators/sequence_ops/, 46 files — SURVEY §5.7).

TPU-native representation: a batch of sequences is a padded dense tensor
[B, T, ...] plus an optional per-sequence Length tensor [B] (the LoD offset
table of the reference becomes lengths/masks — static shapes for XLA).
When no Length input is given, every row is treated as full length.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _mask(x, ins, time_axis=1):
    """[B, T] validity mask from the optional Length input."""
    B, T = x.shape[0], x.shape[time_axis]
    if ins.get("Length"):
        lens = ins["Length"][0].reshape((-1,))
        return (jnp.arange(T)[None, :] < lens[:, None]).astype(jnp.float32), lens
    return jnp.ones((B, T), jnp.float32), jnp.full((B,), T, jnp.int32)


@register("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, D]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask, lens = _mask(x, ins)
    m = mask[..., None]
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(lens[:, None], 1)
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(
            jnp.maximum(lens[:, None], 1).astype(jnp.float32))
    elif ptype == "MAX":
        out = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32),
                                  axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return {"Out": [out], "MaxIndex": [jnp.zeros(out.shape, jnp.int32)]}


@register("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window conv over time (sequence_conv_op.cc): filter
    [ctx_len*D, F]."""
    x = ins["X"][0]  # [B, T, D]
    w = ins["Filter"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    B, T, D = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(x, -off, axis=1)
        if off < 0:
            pad_mask = jnp.arange(T)[None, :, None] >= -off
        else:
            pad_mask = jnp.arange(T)[None, :, None] < T - off
        cols.append(jnp.where(pad_mask, shifted, 0.0))
    ctx_mat = jnp.concatenate(cols, axis=-1)  # [B, T, ctx_len*D]
    out = jnp.einsum("btc,cf->btf", ctx_mat, w)
    return {"Out": [out]}


@register("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T] or [B, T, 1]
    squeeze = x.ndim == 3
    xs = x[..., 0] if squeeze else x
    mask, _ = _mask(xs, ins)
    logits = jnp.where(mask > 0, xs, -1e30)
    out = jax.nn.softmax(logits, axis=1) * mask
    return {"Out": [out[..., None] if squeeze else out]}


@register("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Row-wise expand of X by Y's repeat structure. Padded-dense version:
    X [B, ...] tiled along a new time axis to match Y's T."""
    x, y = ins["X"][0], ins["Y"][0]
    if x.shape[0] == y.shape[0] and x.ndim < y.ndim:
        reps = y.shape[1]
        return {"Out": [jnp.repeat(x[:, None], reps, axis=1)]}
    return {"Out": [jnp.broadcast_to(x, y.shape[: x.ndim])]}


@register("sequence_expand_as")
def _sequence_expand_as(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if x.shape[0] == y.shape[0] and x.ndim == 2 and y.ndim == 3:
        return {"Out": [jnp.repeat(x[:, None], y.shape[1], axis=1)]}
    return {"Out": [jnp.broadcast_to(x, y.shape)]}


@register("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, D]
    new_dim = attrs["new_dim"]
    B = x.shape[0]
    return {"Out": [x.reshape(B, -1, new_dim)]}


@register("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    if ins.get("Length"):
        lens = ins["Length"][0].reshape((-1,))
        T = x.shape[1]
        idx = jnp.arange(T)[None, :]
        rev_idx = jnp.where(idx < lens[:, None], lens[:, None] - 1 - idx, idx)
        out = jnp.take_along_axis(
            x, rev_idx[..., None].astype(jnp.int32).repeat(x.shape[-1], -1),
            axis=1) if x.ndim == 3 else jnp.take_along_axis(
                x, rev_idx.astype(jnp.int32), axis=1)
        return {"Y": [out]}
    return {"Y": [jnp.flip(x, axis=1)]}


@register("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=1)]}


@register("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    x = ins["X"][0]
    offset = int(np.asarray(attrs.get("offset_val", 0)))
    length = int(np.asarray(attrs.get("length_val", x.shape[1])))
    return {"Out": [jax.lax.dynamic_slice_in_dim(x, offset, length, axis=1)]}


@register("sequence_pad", nondiff_inputs=("PadValue",))
def _sequence_pad(ctx, ins, attrs):
    # inputs already padded-dense in this representation: identity + length
    x = ins["X"][0]
    mask, lens = _mask(x, ins)
    return {"Out": [x], "Length": [lens.astype(jnp.int64)]}


@register("sequence_unpad", nondiff_inputs=("Length",))
def _sequence_unpad(ctx, ins, attrs):
    x = ins["X"][0]
    lens = ins["Length"][0].reshape((-1,))
    mask = (jnp.arange(x.shape[1])[None, :] < lens[:, None])
    for _ in range(x.ndim - 2):
        mask = mask[..., None]
    return {"Out": [jnp.where(mask, x, 0.0)]}


@register("sequence_mask", differentiable=False)
def _sequence_mask(ctx, ins, attrs):
    x = ins["X"][0].reshape((-1,))
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        maxlen = int(attrs["__static_maxlen__"])
    from .registry import np_dtype

    dt = np_dtype(attrs.get("out_dtype", attrs.get("dtype", "int64")))
    out = (jnp.arange(maxlen)[None, :] < x[:, None]).astype(dt)
    return {"Y": [out]}


@register("sequence_enumerate", differentiable=False)
def _sequence_enumerate(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T] int
    win = attrs["win_size"]
    pad = attrs.get("pad_value", 0)
    B, T = x.shape[:2]
    cols = []
    for i in range(win):
        shifted = jnp.roll(x, -i, axis=1)
        valid = jnp.arange(T)[None, :] < T - i
        cols.append(jnp.where(valid, shifted, pad))
    return {"Out": [jnp.stack(cols, axis=-1)]}


@register("sequence_erase", differentiable=False)
def _sequence_erase(ctx, ins, attrs):
    """Padded-dense variant: erased tokens are REPLACED by a pad marker
    (-1) — static shapes forbid true removal; downstream masks skip them."""
    x = ins["X"][0]
    tokens = attrs.get("tokens", [])
    bad = jnp.zeros_like(x, dtype=jnp.bool_)
    for t in tokens:
        bad = bad | (x == t)
    return {"Out": [jnp.where(bad, -1, x)]}


@register("sequence_scatter", nondiff_inputs=("Ids",))
def _sequence_scatter(ctx, ins, attrs):
    x = ins["X"][0]
    ids = ins["Ids"][0]
    upd = ins["Updates"][0]
    B = x.shape[0]
    bidx = jnp.arange(B)[:, None].repeat(ids.shape[1], 1)
    return {"Out": [x.at[bidx.reshape(-1),
                         ids.reshape(-1).astype(jnp.int32)].add(
        upd.reshape(-1, *upd.shape[2:]))]}


@register("similarity_focus", differentiable=False)
def _similarity_focus(ctx, ins, attrs):
    """similarity_focus_op.cc: for each selected channel, mark the max cell
    per (row, col) producing a focus mask over [B, C, H, W]."""
    x = ins["X"][0]
    axis = attrs["axis"]
    indexes = attrs["indexes"]
    if axis != 1:
        raise NotImplementedError("similarity_focus supports axis=1 (C)")
    B, C, H, W = x.shape
    out = jnp.zeros_like(x)
    for idx in indexes:
        ch = x[:, idx]  # [B, H, W]
        row_max = (ch == ch.max(axis=2, keepdims=True))
        col_max = (ch == ch.max(axis=1, keepdims=True))
        mask = (row_max | col_max).astype(x.dtype)  # [B, H, W]
        out = jnp.maximum(out, mask[:, None, :, :])
    return {"Out": [out]}
