"""NN layer ops: softmax, dropout, embedding, norms, fc (parity:
operators/{softmax_op,dropout_op,lookup_table_op,layer_norm_op,batch_norm_op,
group_norm_op,data_norm_op,lrn_op,maxout_op}.cc).

TPU notes: softmax/layer_norm are left to XLA fusion (bandwidth-bound chains
fuse into one pass); batch_norm keeps functional moving-stat updates (the
executor writes MeanOut/VarianceOut back to the persistable store);
lookup_table is a dense take() whose VJP is a scatter-add — the SelectedRows
sparse-grad path of the reference maps to sorted segment-sum under XLA.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.jax_compat import optimization_barrier
from .registry import register, simple_op, np_dtype


@register("softmax")
def _softmax(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.softmax(x, axis=axis)]}


@register("log_softmax")
def _log_softmax(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jax.nn.log_softmax(x, axis=attrs.get("axis", -1))]}


@register("dropout", stateful=True)
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl_type = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl_type == "upscale_in_train":
            return {"Out": [x], "Mask": [jnp.ones_like(x)]}
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    key = ctx.rng(attrs)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl_type == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = x * mask
    return {"Out": [out], "Mask": [mask]}


@register("lookup_table", nondiff_inputs=("Ids",))
def _lookup_table(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    # Fluid ids have trailing [..., 1] dim
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": [out]}


@register("lookup_table_v2", nondiff_inputs=("Ids",))
def _lookup_table_v2(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    out = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": [out]}


@register("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    # keep the stats reduces OUT of the producer's fusion: without this
    # barrier XLA fuses the mean/var epilogue into a preceding matmul
    # fusion, which measurably serializes the dot (flagship FFN pair:
    # 4.06 ms fused-with-stats vs ~1.8 ms behind a barrier — a 2.2x
    # slowdown on the hottest fusions in the step)
    x = optimization_barrier(x)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    feat_shape = x.shape[begin:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(feat_shape).astype(jnp.float32)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(feat_shape).astype(jnp.float32)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [mean.reshape((-1,))],
        "Variance": [var.reshape((-1,))],
    }


@register("batch_norm", stateful=True)
def _batch_norm(ctx, ins, attrs):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    data_layout = attrs.get("data_layout", "NCHW")
    use_global = attrs.get("use_global_stats", False) or is_test
    ch_axis = 1 if data_layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = tuple(x.shape[ch_axis] if i == ch_axis else 1 for i in range(x.ndim))
    xf = x.astype(jnp.float32)
    if use_global:
        mean, var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
        saved_mean = mean
        saved_var = var
    else:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(xf * xf, axis=axes) - mean * mean
        if ctx.data_axis is not None:
            # sync_batch_norm parity (operators/sync_batch_norm_op.cu):
            # cross-replica stats ride an ICI psum instead of NCCL
            mean = jax.lax.pmean(mean, ctx.data_axis)
            var = jax.lax.pmean(var, ctx.data_axis)
        mean_out = mean_in * momentum + mean * (1.0 - momentum)
        var_out = var_in * momentum + var * (1.0 - momentum)
        saved_mean = mean
        saved_var = var
    y = (xf - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + eps)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    return {
        "Y": [y.astype(x.dtype)],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


@register("group_norm")
def _group_norm(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    groups = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + spatial).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.mean((xg - mean) ** 2, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * len(spatial)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [mean.reshape((n, groups))],
        "Variance": [var.reshape((n, groups))],
    }


@register("data_norm")
def _data_norm(ctx, ins, attrs):
    x = ins["X"][0]
    bsize = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsqs = ins["BatchSquareSum"][0]
    eps = attrs.get("epsilon", 1e-4)
    mean = bsum / bsize
    scale = jax.lax.rsqrt(bsqs / bsize - mean * mean + eps)
    y = (x - mean) * scale
    return {"Y": [y], "Means": [mean], "Scales": [scale]}


@register("lrn")
def _lrn(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = x * x
    half = n // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + pad[:, i : i + x.shape[1]]
    mid = (k + alpha * acc) ** beta
    return {"Out": [x / mid], "MidOut": [mid]}


@register("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


@register("norm")
def _norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


@register("maxout")
def _maxout(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    groups = attrs["groups"]
    n, c, h, w = x.shape
    out = x.reshape((n, c // groups, groups, h, w)).max(axis=2)
    return {"Out": [out]}


@register("pixel_shuffle")
def _pixel_shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    out = x.reshape((n, c // (r * r), r, r, h, w))
    out = out.transpose((0, 1, 4, 2, 5, 3)).reshape((n, c // (r * r), h * r, w * r))
    return {"Out": [out]}


@register("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    x = ins["X"][0]
    b = attrs["blocksize"]
    n, c, h, w = x.shape
    out = x.reshape((n, c, h // b, b, w // b, b))
    out = out.transpose((0, 3, 5, 1, 2, 4)).reshape((n, c * b * b, h // b, w // b))
    return {"Out": [out]}


@register("shuffle_channel")
def _shuffle_channel(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    out = x.reshape((n, g, c // g, h, w)).transpose((0, 2, 1, 3, 4)).reshape(x.shape)
    return {"Out": [out]}


@register("temporal_shift")
def _temporal_shift(ctx, ins, attrs):
    x = ins["X"][0]
    seg_num = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape((n, seg_num, c, h, w))
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    pre = jnp.pad(xr[:, :-1, :c1], [(0, 0), (1, 0), (0, 0), (0, 0), (0, 0)])
    post = jnp.pad(xr[:, 1:, c1:c2], [(0, 0), (0, 1), (0, 0), (0, 0), (0, 0)])
    rest = xr[:, :, c2:]
    out = jnp.concatenate([pre, post, rest], axis=2).reshape(x.shape)
    return {"Out": [out]}


@register("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, D]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, t, d = x.shape
    pos = np.arange(t)[:, None]
    i = np.arange(d // 2)[None, :]
    angle = pos / np.power(10000.0, 2 * i / d)
    pe = np.concatenate([np.sin(angle), np.cos(angle)], axis=1)
    return {"Out": [alpha * x + beta * jnp.asarray(pe, x.dtype)[None]]}


@register("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1

    def sample(yy, xx):
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        bidx = jnp.arange(n)[:, None, None]
        return x[bidx, :, yy, xx]  # [n, gh, gw, c]

    wa = ((x1 - gx) * (y1 - gy))[..., None]
    wb = ((x1 - gx) * (gy - y0))[..., None]
    wc = ((gx - x0) * (y1 - gy))[..., None]
    wd = ((gx - x0) * (gy - y0))[..., None]
    out = (sample(y0, x0) * wa + sample(y1, x0) * wb + sample(y0, x1) * wc
           + sample(y1, x1) * wd)
    return {"Output": [out.transpose((0, 3, 1, 2))]}


@register("affine_channel")
def _affine_channel(ctx, ins, attrs):
    x = ins["X"][0]
    layout = attrs.get("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    bshape = tuple(x.shape[ch_axis] if i == ch_axis else 1 for i in range(x.ndim))
    return {"Out": [x * ins["Scale"][0].reshape(bshape)
                    + ins["Bias"][0].reshape(bshape)]}


@register("affine_grid")
def _affine_grid(ctx, ins, attrs):
    theta = ins["Theta"][0]  # [N, 2, 3]
    h, w = attrs["output_shape"][-2:]
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
    grid = jnp.einsum("hwk,nak->nhwa", base, theta)
    return {"Output": [grid]}
