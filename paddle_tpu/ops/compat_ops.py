"""Remaining Appendix-A operator registrations (SURVEY Appendix A — the
reference ops without a dedicated home module: fused/fusion variants,
pserver sharding helpers, SSD mining, SPP/unpool, and misc losses).

Ops the reference registers but which this architecture deliberately
handles OUTSIDE the kernel registry are NOT here: feed/fetch/save/load/
save_combine/load_combine (executor + io.py), while/conditional_block/
recurrent and the tensor-array/LoD-structure ops (layers/control_flow.py
lowers them to lax control flow + Python tensor arrays), delete_var/
get_places (scope/platform). See PARITY.md §2.2.
"""

import jax
import jax.numpy as jnp

from .registry import register, get, simple_op


# ---- simple math / losses -------------------------------------------------

@simple_op("minus", in_slots=("X", "Y"))
def _minus(ctx, x, y, **attrs):
    return x - y


@register("fill", differentiable=False)
def _fill(ctx, ins, attrs):
    """fill_op.cc: materialize a constant tensor from attr data."""
    import numpy as np

    from .registry import np_dtype

    shape = tuple(attrs.get("shape", []))
    dt = np_dtype(attrs.get("dtype", "float32"))
    # convert in numpy at the TARGET dtype — a float32 intermediate would
    # corrupt int64 values above 2^24
    return {"Out": [jnp.asarray(
        np.asarray(attrs.get("value", [0.0]), dt).reshape(shape))]}


@register("fill_zeros_like2", differentiable=False)
def _fill_zeros_like2(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register("modified_huber_loss", nondiff_inputs=("Y",))
def _modified_huber_loss(ctx, ins, attrs):
    """modified_huber_loss_op.cc: y in {0,1} -> {-1,1}; quadratic inside
    the margin, linear outside."""
    x = ins["X"][0]
    y = 2.0 * ins["Y"][0].astype(jnp.float32) - 1.0
    yf = y * x
    loss = jnp.where(yf >= -1.0,
                     jnp.square(jnp.maximum(0.0, 1.0 - yf)),
                     -4.0 * yf)
    return {"Out": [loss], "IntermediateVal": [yf]}


@simple_op("conv_shift", in_slots=("X", "Y"))
def _conv_shift(ctx, x, y, **attrs):
    """Circular correlation (conv_shift_op.cc): X [B, W], Y [B, N] with N
    odd; out[b, i] = sum_j Y[b, j] * X[b, (i + j - N//2) mod W]."""
    W = x.shape[1]
    N = y.shape[1]
    shifts = jnp.stack([jnp.roll(x, (N // 2) - j, axis=1)
                        for j in range(N)], axis=1)  # [B, N, W]
    return jnp.einsum("bn,bnw->bw", y, shifts)


# ---- pooling family -------------------------------------------------------

@register("spp")
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (spp_op.cc): pyramid_height levels of
    bin-pooled features, flattened and concatenated."""
    x = ins["X"][0]
    levels = attrs.get("pyramid_height", 2)
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lvl in range(levels):
        bins = 2 ** lvl
        pooled = jnp.zeros((n, c, bins, bins), x.dtype)
        for i in range(bins):
            for j in range(bins):
                hs, he = (h * i) // bins, max((h * (i + 1) + bins - 1) // bins,
                                              (h * i) // bins + 1)
                ws, we = (w * j) // bins, max((w * (j + 1) + bins - 1) // bins,
                                              (w * j) // bins + 1)
                block = x[:, :, hs:he, ws:we]
                red = (block.max(axis=(2, 3)) if ptype == "max"
                       else block.mean(axis=(2, 3)))
                pooled = pooled.at[:, :, i, j].set(red)
        outs.append(pooled.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    from .conv import _pool_max_with_index

    out, mask = _pool_max_with_index(ins["X"][0], attrs, 3)
    return {"Out": [out], "Mask": [mask]}


@register("unpool", nondiff_inputs=("Indices",))
def _unpool(ctx, ins, attrs):
    """Max-unpooling (unpool_op.cc): scatter pooled values back to the
    positions recorded in Indices (flat h*w offsets per channel). Output
    size follows the reference formula (in-1)*stride + ksize - 2*pad."""
    x = ins["X"][0]
    idx = ins["Indices"][0].astype(jnp.int32)
    n, c, h, w = x.shape
    ksize = list(attrs.get("ksize", [2, 2]))
    strides = list(attrs.get("strides", ksize))
    pads = list(attrs.get("paddings", [0, 0]))
    oh = (h - 1) * strides[0] + ksize[0] - 2 * pads[0]
    ow = (w - 1) * strides[1] + ksize[1] - 2 * pads[1]
    flat_out = jnp.zeros((n, c, oh * ow), x.dtype)
    flat_x = x.reshape(n, c, h * w)
    flat_idx = idx.reshape(n, c, h * w)
    bi = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    flat_out = flat_out.at[bi, ci, flat_idx].set(flat_x)
    return {"Out": [flat_out.reshape(n, c, oh, ow)]}


# ---- metrics / mining -----------------------------------------------------

@register("positive_negative_pair", differentiable=False)
def _positive_negative_pair(ctx, ins, attrs):
    """positive_negative_pair_op.cc: per-query counts of correctly ordered
    (positive), wrongly ordered (negative), and tied prediction pairs."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q, dtype=bool), 1)
    valid = same_q & upper & (label[:, None] != label[None, :])
    s_diff = score[:, None] - score[None, :]
    l_diff = (label[:, None] - label[None, :]).astype(jnp.float32)
    pos = jnp.sum((valid & (s_diff * l_diff > 0)).astype(jnp.float32))
    neg = jnp.sum((valid & (s_diff * l_diff < 0)).astype(jnp.float32))
    neu = jnp.sum((valid & (s_diff == 0)).astype(jnp.float32))
    acc = ins.get("AccumulatePositivePair")
    if acc:
        pos = pos + ins["AccumulatePositivePair"][0].reshape(())
        neg = neg + ins["AccumulateNegativePair"][0].reshape(())
        neu = neu + ins["AccumulateNeutralPair"][0].reshape(())
    return {"PositivePair": [pos.reshape((1,))],
            "NegativePair": [neg.reshape((1,))],
            "NeutralPair": [neu.reshape((1,))]}


@register("mine_hard_examples", differentiable=False)
def _mine_hard_examples(ctx, ins, attrs):
    """SSD hard-negative mining (mine_hard_examples_op.cc): per image keep
    the neg_pos_ratio * num_pos highest-loss negatives. Padded-dense: the
    output is an updated MatchIndices where un-selected negatives stay -1
    and selected hard negatives are marked -2 (NegIndices mask rides along
    as a dense 0/1 tensor instead of a LoD list)."""
    cls_loss = ins["ClsLoss"][0]
    match_indices = ins["MatchIndices"][0]
    loss = cls_loss.reshape(match_indices.shape)
    if ins.get("LocLoss"):
        loss = loss + ins["LocLoss"][0].reshape(match_indices.shape)
    ratio = attrs.get("neg_pos_ratio", 3.0)
    is_neg = match_indices < 0
    num_pos = jnp.sum(~is_neg, axis=1, keepdims=True)
    num_neg = jnp.minimum((num_pos * ratio).astype(jnp.int32),
                          jnp.sum(is_neg, axis=1, keepdims=True))
    neg_loss = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    selected = is_neg & (rank < num_neg)
    updated = jnp.where(selected, -2, match_indices)
    return {"NegIndices": [selected.astype(jnp.int32)],
            "UpdatedMatchIndices": [updated]}


@register("sample_logits", nondiff_inputs=("Labels", "CustomizedSamples"))
def _sample_logits(ctx, ins, attrs):
    """sample_logits_op.cc: gather the label logits plus num_samples
    uniformly sampled negative-class logits (sampled-softmax front half)."""
    logits = ins["Logits"][0]
    labels = ins["Labels"][0].astype(jnp.int32)
    b, n_classes = logits.shape
    num_samples = attrs.get("num_samples", 16)
    if ins.get("CustomizedSamples"):
        samples = ins["CustomizedSamples"][0].astype(jnp.int32)
    else:
        key = ctx.rng(attrs)
        neg = jax.random.randint(key, (b, num_samples), 0, n_classes)
        samples = jnp.concatenate([labels.reshape(b, -1), neg], axis=1)
    sampled = jnp.take_along_axis(logits, samples, axis=1)
    n_true = labels.reshape(b, -1).shape[1]
    sampled_labels = jnp.arange(n_true, dtype=jnp.int32)[None, :].repeat(
        b, axis=0)
    return {"SampledLogits": [sampled], "Samples": [samples],
            "SampledLabels": [sampled_labels],
            "Probabilities": [jnp.full(samples.shape,
                                       1.0 / n_classes, jnp.float32)],
            "LogitsDim": [jnp.asarray(logits.shape, jnp.int32)],
            "LabelsDim": [jnp.asarray(labels.shape, jnp.int32)]}


# ---- pserver sharding helpers --------------------------------------------

@register("split_ids", differentiable=False)
def _split_ids(ctx, ins, attrs):
    """split_ids_op.cc: route ids to N shards by id %% N (padded-dense:
    each shard output keeps its ids, others set to -1)."""
    ids = ins["Ids"][0]
    n = attrs.get("num_shards", 1)
    outs = [jnp.where(ids % n == s, ids, -1) for s in range(n)]
    return {"Out": outs}


@register("merge_ids", differentiable=False)
def _merge_ids(ctx, ins, attrs):
    """merge_ids_op.cc capability: gather per-shard rows back into the
    original id order. Rows[i] holds the embedding rows for ids routed to
    shard i (id %% n == i), in that shard's id order."""
    ids = ins["Ids"][0].reshape(-1)
    rows = ins["X"]
    n = len(rows)
    dim = rows[0].shape[-1]
    out = jnp.zeros((ids.shape[0], dim), rows[0].dtype)
    for s in range(n):
        mask = ids % n == s
        # position of each id within its shard = cumulative count - 1
        pos = jnp.cumsum(mask) - 1
        gathered = rows[s][jnp.clip(pos, 0, rows[s].shape[0] - 1)]
        out = jnp.where(mask[:, None], gathered, out)
    return {"Out": [out]}


@register("split_selected_rows", differentiable=False)
def _split_selected_rows(ctx, ins, attrs):
    """split_selected_rows_op.cc: slice a dense (row-major) tensor into
    height_sections row blocks."""
    x = ins["X"][0]
    sections = attrs.get("height_sections", [x.shape[0]])
    outs, start = [], 0
    for h in sections:
        outs.append(x[start:start + h])
        start += h
    return {"Out": outs}


@register("lookup_sparse_table", nondiff_inputs=("Ids",))
def _lookup_sparse_table(ctx, ins, attrs):
    """lookup_sparse_table_op.cc: same lowering as lookup_table (the
    auto-growth sparse-table behavior belongs to the host embedding store
    — parallel/host_embedding.py)."""
    return get("lookup_table").impl(ctx, {"W": ins["W"], "Ids": ins["Ids"]},
                                    attrs)


# ---- fused / fusion variants ---------------------------------------------

@register("fused_embedding_seq_pool", nondiff_inputs=("Ids",))
def _fused_embedding_seq_pool(ctx, ins, attrs):
    """fused_embedding_seq_pool_op.cc: lookup + sum-pool over time in one
    op (Ids [B, T] padded; pad entries use padding_idx semantics)."""
    table = ins["W"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    if ids.ndim == 3:
        ids = ids[..., 0]
    emb = table[jnp.clip(ids, 0, table.shape[0] - 1)]
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        emb = jnp.where((ids == padding_idx)[..., None], 0.0, emb)
    return {"Out": [jnp.sum(emb, axis=1)]}


@register("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, ins, attrs):
    """fused_elemwise_activation_op.cc: functor_list[0] is the OUTER
    functor — ["binary", "unary"] computes Binary(X, Unary(Y)),
    ["unary", "binary"] computes Unary(Binary(X, Y)). IntermediateOut is
    the inner functor's result."""
    functors = [f.split(",")[0] for f in attrs.get("functor_list", [])]
    x, y = ins["X"][0], ins["Y"][0]
    binary = {"elementwise_add": jnp.add, "elementwise_sub": jnp.subtract,
              "elementwise_mul": jnp.multiply}
    unary = {"relu": jax.nn.relu, "scale": lambda v: v * attrs.get(
        "scale", 1.0), "tanh": jnp.tanh, "sigmoid": jax.nn.sigmoid}
    if len(functors) != 2:
        raise ValueError("fused_elemwise_activation needs functor_list of "
                         "two entries, got %r" % (functors,))
    f0, f1 = functors
    if f0 in binary:
        inner = unary[f1](y)
        out = binary[f0](x, inner)
    else:
        inner = binary[f1](x, y)
        out = unary[f0](inner)
    return {"Out": [out], "IntermediateOut": [inner]}


def _project_then(op_name, extra_out_slots):
    """fusion_gru/fusion_lstm = X @ WeightX (+bias) then the plain RNN
    kernel (fusion_*_op.cc fuse the input GEMM into the recurrence)."""

    def impl(ctx, ins, attrs):
        x = ins["X"][0]
        wx = ins["WeightX"][0]
        projected = jnp.einsum("btm,mk->btk", x, wx)
        inner_ins = {"Input": [projected], "Weight": ins["WeightH"]}
        if ins.get("Bias"):
            inner_ins["Bias"] = ins["Bias"]
        if ins.get("H0"):
            inner_ins["H0"] = ins["H0"]
        if ins.get("C0"):
            inner_ins["C0"] = ins["C0"]
        out = get(op_name).impl(ctx, inner_ins, attrs)
        res = {"Hidden": out["Hidden"], "XX": [projected]}
        for slot, src in extra_out_slots.items():
            res[slot] = out[src]
        return res

    return impl


register("fusion_gru")(_project_then("gru", {}))
register("fusion_lstm")(_project_then("lstm", {"Cell": "Cell"}))


@register("lstmp")
def _lstmp(ctx, ins, attrs):
    """Projection LSTM (lstmp_op.cc): standard LSTM whose output is
    projected through ProjWeight each step; recurrence runs on the
    projection."""
    x = ins["Input"][0]
    w = ins["Weight"][0]          # [P, 4D]
    w_proj = ins["ProjWeight"][0]  # [D, P]
    d = w_proj.shape[0]
    p = w_proj.shape[1]
    b = x.shape[0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, p), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, d), x.dtype)
    xt_seq = jnp.swapaxes(x, 0, 1)

    def step(carry, xt):
        h_prev, c_prev = carry
        g = xt + h_prev @ w
        if bias is not None:
            g = g + bias
        i, f, cand, o = jnp.split(g, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(cand)
        h = (jax.nn.sigmoid(o) * jnp.tanh(c)) @ w_proj
        return (h, c), (h, c)

    (_hl, _cl), (hs, cs) = jax.lax.scan(step, (h0, c0), xt_seq)
    return {"Projection": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "BatchGate": [jnp.swapaxes(hs, 0, 1)],
            "BatchCellPreAct": [jnp.swapaxes(cs, 0, 1)],
            "BatchHidden": [jnp.swapaxes(hs, 0, 1)]}


@register("cudnn_lstm")
def _cudnn_lstm(ctx, ins, attrs):
    """cudnn_lstm_op.cu.cc capability: the fused long-sequence LSTM is the
    same lax.scan kernel — XLA fuses the steps (no cuDNN analog needed)."""
    return get("lstm").impl(ctx, ins, attrs)


@register("attention_lstm")
def _attention_lstm(ctx, ins, attrs):
    """attention_lstm_op.cc: per step, softmax attention over the source
    sequence conditioned on the previous cell state, then one LSTM step on
    the attended vector."""
    x = ins["X"][0]                   # [B, T, M]
    att_w = ins["AttentionWeight"][0]  # [M+D, 1]
    lstm_w = ins["LSTMWeight"][0]      # [M+D, 4D]
    lstm_b = ins["LSTMBias"][0]        # [1, 4D]
    b_sz, t_len, m = x.shape
    d = lstm_w.shape[1] // 4
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b_sz, d), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b_sz, d), x.dtype)

    def step(carry, _):
        h_prev, c_prev = carry
        ctx_in = jnp.concatenate(
            [x, jnp.repeat(c_prev[:, None, :], t_len, axis=1)], axis=-1)
        scores = jnp.einsum("btk,ko->bto", ctx_in, att_w)[..., 0]
        alpha = jax.nn.softmax(scores, axis=1)
        attended = jnp.einsum("bt,btm->bm", alpha, x)
        g = jnp.concatenate([attended, h_prev], axis=-1) @ lstm_w + lstm_b
        i, f, cand, o = jnp.split(g, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(cand)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    (h_last, c_last), hs = jax.lax.scan(step, (h0, c0), None, length=t_len)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "Cell": [c_last],
            "AttentionedX": [x], "AttentionFCOut": [h_last],
            "LSTMX": [h_last], "LSTMOUT": [h_last]}


# ---- gradient compression / buffer fusion --------------------------------

@register("dgc", differentiable=False, stateful=True)
def _dgc(ctx, ins, attrs):
    """dgc_op.cc: momentum-corrected top-k sparsification. U carries the
    momentum-accumulated residual, V the unsent mass; the dense masked
    gradient goes out for the (sparse) allreduce."""
    from ..parallel.dgc import topk_sparsify

    grad = ins["Grad"][0]
    u = ins["U"][0]
    v = ins["V"][0]
    m = attrs.get("m", 0.9)
    ratio = 1.0 - attrs.get("sparsity", [0.999])[-1]
    k = max(1, int(grad.size * ratio))
    u_out = m * u + grad
    v_out = v + u_out
    vals, idx, residual = topk_sparsify(v_out, k)
    dense = v_out - residual          # the sent (top-k) mass
    sent = dense != 0
    # the encode buffer is float32: indices ride BITCAST (a numeric cast
    # would corrupt indices above 2^24), values numerically cast
    idx_bits = jax.lax.bitcast_convert_type(idx.astype(jnp.int32),
                                            jnp.float32)
    return {"U_out": [jnp.where(sent, 0.0, u_out)],
            "V_out": [residual],
            "EncodeGrad": [jnp.concatenate(
                [idx_bits, vals.astype(jnp.float32)])],
            "Grad_out": [dense],
            "GatherBuff": [dense]}


@register("dgc_clip_by_norm", differentiable=False)
def _dgc_clip_by_norm(ctx, ins, attrs):
    """dgc_clip_by_norm_op.cc: clip_by_norm gated on the rampup window."""
    step = ins["current_step"][0].reshape(()) if ins.get(
        "current_step") else jnp.asarray(0.0)
    rampup = attrs.get("rampup_begin_step", 0.0)
    clipped = get("clip_by_norm").impl(ctx, {"X": ins["X"]}, attrs)["Out"][0]
    out = jnp.where(step >= rampup, clipped, ins["X"][0])
    return {"Out": [out]}


@register("alloc_continuous_space", differentiable=False)
def _alloc_continuous_space(ctx, ins, attrs):
    """alloc_continuous_space_op.cc: fuse a list of tensors into one flat
    buffer (gradient-bucketing ancestor). Outputs the per-input views plus
    the fused flat buffer; XLA's buffer assignment owns actual placement."""
    xs = ins["Input"]
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    if attrs.get("set_constant", False):
        flat = jnp.full_like(flat, attrs.get("constant", 0.0))
        outs, start = [], 0
        for x in xs:
            outs.append(flat[start:start + x.size].reshape(x.shape))
            start += x.size
    else:
        outs = list(xs)
    return {"Output": outs, "FusedOutput": [flat]}


@register("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    """Fused attention exposed as a graph op. Q/K/V layout is [B, H, T, Dh]
    (attr layout="bhtd", default) or [B, T, H, Dh] ("bthd" — transpose-free
    from a reshape of [B, T, D], XLA folds the layout into the dots).

    Dispatches to the tuned TPU flash kernel whenever the shape tiles
    (in-model profile on v5e at B128/H8/T512/D64: flash fwd ~1.8 ms vs the
    XLA-fused softmax path's ~1 GB materialized score/prob buffers); the
    XLA path covers shapes the blocked kernels can't tile.
    Differentiable through the kernels' own VJPs."""
    from .pallas_kernels import flash_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    if attrs.get("__amp_bf16__") and q.dtype == jnp.float32:
        # AMP white-list marking: bf16 QKV matmuls (softmax stays fp32
        # inside the kernels); output stays bf16 like every white-list op
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out_dtype = q.dtype
    causal = attrs.get("causal", False)
    scale = attrs.get("sm_scale", None)
    layout = attrs.get("layout", "bhtd")
    t_axis = 2 if layout == "bhtd" else 1
    Dh = q.shape[-1]
    T = q.shape[t_axis]

    # Sequence parallelism through the descriptor path (SURVEY §5.7, the
    # scale-sequence-length axis): when the step mesh carries an "sp" axis
    # (BuildStrategy.sequence_parallel_degree), self-attention runs as
    # RING attention — K/V blocks rotate over the sp ranks via ppermute
    # while each rank accumulates its Q-shard online-softmax, so the full
    # [T, T] score matrix never exists on any chip. The shard_map is
    # manual over sp only; dp/tp stay GSPMD-auto, and its seq-sharded
    # out_specs seed sharding propagation through the residual stream.
    mesh = getattr(ctx, "mesh", None)
    sp = dict(mesh.shape).get("sp", 1) if mesh is not None else 1
    if sp > 1:
        if T % sp == 0 and q.shape == k.shape \
                and not getattr(ctx, "no_pair_collectives", False):
            from ..parallel.ring_attention import ring_attention_sharded

            qb, kb, vb = ((jnp.swapaxes(t, 1, 2) for t in (q, k, v))
                          if layout == "bthd" else (q, k, v))
            out = ring_attention_sharded(qb, kb, vb, mesh, causal=causal,
                                         sm_scale=scale,
                                         partial_manual=True)
            if layout == "bthd":
                out = jnp.swapaxes(out, 1, 2)
            return {"Out": [out.astype(out_dtype)]}
        if T % sp == 0 and q.shape == k.shape:
            # inside a pipeline stage branch: the ring's ppermute would
            # deadlock (pair collectives rendezvous across all devices),
            # so use the ALL-GATHER sequence-parallel formulation — Q and
            # the output stay seq-sharded over sp (scores O(T^2/sp) per
            # chip), K/V gather to replicated (group-safe) — expressed
            # purely through GSPMD constraints around the shared XLA
            # attention math, no manual collectives
            from jax.sharding import NamedSharding as _NS
            from jax.sharding import PartitionSpec as _P

            from ..parallel.mesh import current_abstract_mesh

            cmesh = current_abstract_mesh(mesh)
            U = _P.UNCONSTRAINED
            seq_spec = (_P(U, "sp", U, U) if layout == "bthd"
                        else _P(U, U, "sp", U))
            repl_spec = (_P(U, None, U, U) if layout == "bthd"
                         else _P(U, U, None, U))
            q = jax.lax.with_sharding_constraint(q, _NS(cmesh, seq_spec))
            k = jax.lax.with_sharding_constraint(k, _NS(cmesh, repl_spec))
            v = jax.lax.with_sharding_constraint(v, _NS(cmesh, repl_spec))
            out = _xla_softmax_attention(q, k, v, layout, causal, scale, Dh)
            out = jax.lax.with_sharding_constraint(out, _NS(cmesh, seq_spec))
            return {"Out": [out.astype(out_dtype)]}
        import warnings

        form = ("all-gather sequence parallelism (pipeline-stage form)"
                if getattr(ctx, "no_pair_collectives", False)
                else "ring attention")
        warnings.warn(
            "sequence_parallel_degree=%d is set but %s cannot engage for "
            "this op (seq %d %% sp != 0, or cross-attention q/k shapes "
            "differ): falling back to per-chip full attention — the sp "
            "mesh ranks replicate this work and the [T, T] scores "
            "materialize per chip" % (sp, form, T),
            RuntimeWarning)

    # registry-dispatched: the tuned kernel when the shape qualifies
    # (the old ad-hoc gate here required q.shape == k.shape, silently
    # dropping the tuned path for cross-attention — the registry's
    # qualification allows non-causal Tq != Tk and logs any
    # disqualification once), lax softmax attention otherwise
    from .kernel_registry import choose as _choose_kernel

    if _choose_kernel("flash_attention", T=T, Tk=k.shape[t_axis],
                      head_dim=Dh, causal=causal):
        if layout == "bthd":
            q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        out = flash_attention(q, k, v, causal, scale)
        if layout == "bthd":
            out = jnp.swapaxes(out, 1, 2)
    else:
        out = _xla_softmax_attention(q, k, v, layout, causal, scale, Dh)
    return {"Out": [out.astype(out_dtype)]}


@register("fused_multihead_attention")
def _fused_multihead_attention(ctx, ins, attrs):
    """The whole self-attention sublayer as ONE op: per-head q/k/v
    projections, (flash) attention, and the output projection. TPU-native
    analogue of the reference's fused attention inference kernels
    (multihead_matmul_op.cu, fused/multihead_matmul_fuse_pass semantics)
    — but used in TRAINING too, because on TPU the fusion is a layout
    property, not just an op-count one: the projections are einsums
    `btd,dhx->bthx` whose output keeps heads as real dot dimensions, so
    the [B,H,T,Dh] operand order the flash kernel needs folds into the
    dot's output layout. The unfused fc+split formulation flattens the
    projection to a 2D dot, the head permutation cannot be a bitcast of
    any 2D layout, and every q/k/v materializes an HBM copy — measured
    ~34 ms/step (10% of device time) at flagship scale.

    Inputs: X [B,T,D]; WQ/WK/WV [D,H,Dh]; WO [H,Dh,D]; optional BQ/BK/BV
    [H,Dh] and BO [D]. Attrs: causal, sm_scale (default Dh^-0.5).
    Output: [B,T,D]. Attention itself (ring-sp dispatch, Pallas/XLA
    fallback) is delegated to the flash_attention op in bthd layout."""
    x = ins["X"][0]
    wq, wk, wv = ins["WQ"][0], ins["WK"][0], ins["WV"][0]
    wo = ins["WO"][0]
    if attrs.get("__amp_bf16__") and x.dtype == jnp.float32:
        x = x.astype(jnp.bfloat16)
    cdt = x.dtype
    Dh = wq.shape[-1]

    def proj(w, b):
        y = jnp.einsum("btd,dhx->bthx", x, w.astype(cdt))
        if b is not None:
            y = y + b.astype(cdt)
        return y

    q = proj(wq, (ins.get("BQ") or [None])[0])
    k = proj(wk, (ins.get("BK") or [None])[0])
    v = proj(wv, (ins.get("BV") or [None])[0])
    ctx_out = get("flash_attention").impl(ctx, {"Q": [q], "K": [k],
                                               "V": [v]}, {
        "causal": bool(attrs.get("causal", False)),
        "sm_scale": attrs.get("sm_scale") or Dh ** -0.5,
        "layout": "bthd"})["Out"][0]
    out = jnp.einsum("bthx,hxd->btd", ctx_out, wo.astype(cdt))
    bo = (ins.get("BO") or [None])[0]
    if bo is not None:
        out = out + bo.astype(cdt)
    return {"Out": [out]}


def _xla_softmax_attention(q, k, v, layout, causal, scale, Dh):
    """XLA-fused softmax attention with the head layout folded into the
    dots — shared by the non-Pallas fallback and the pipeline-safe
    all-gather sequence-parallel path."""
    s = scale if scale is not None else Dh ** -0.5
    qs, ks, vs = (("bhqd", "bhkd", "bhkd") if layout == "bhtd"
                  else ("bqhd", "bkhd", "bkhd"))
    logits = jnp.einsum("%s,%s->bhqk" % (qs, ks), q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        Tq, Tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out_spec = "bhqd" if layout == "bhtd" else "bqhd"
    return jnp.einsum("bhqk,%s->%s" % (vs, out_spec), p, v)
