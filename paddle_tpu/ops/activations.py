"""Activation op family (parity: operators/activation_op.cc — the ~37
activations registered via REGISTER_ACTIVATION_OP; SURVEY Appendix A list).

All elementwise; XLA fuses them into producers/consumers so per-op kernels
would be pure overhead — each is one jnp/lax expression.
"""

import jax
import jax.numpy as jnp

from .registry import elementwise_unary, register


def _a(name, fn, differentiable=True):
    elementwise_unary(name, fn, differentiable=differentiable)


_a("abs", lambda x, a: jnp.abs(x))
_a("acos", lambda x, a: jnp.arccos(x))
_a("asin", lambda x, a: jnp.arcsin(x))
_a("atan", lambda x, a: jnp.arctan(x))
_a("ceil", lambda x, a: jnp.ceil(x), differentiable=False)
_a("floor", lambda x, a: jnp.floor(x), differentiable=False)
_a("round", lambda x, a: jnp.round(x), differentiable=False)
_a("cos", lambda x, a: jnp.cos(x))
_a("sin", lambda x, a: jnp.sin(x))
_a("exp", lambda x, a: jnp.exp(x))
_a("log", lambda x, a: jnp.log(x))
_a("sqrt", lambda x, a: jnp.sqrt(x))
_a("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_a("square", lambda x, a: x * x)
_a("reciprocal", lambda x, a: 1.0 / x)
_a("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_a("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_a("tanh", lambda x, a: jnp.tanh(x))
_a("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_a("relu", lambda x, a: jax.nn.relu(x))
_a("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_a("gelu", lambda x, a: jax.nn.gelu(x, approximate=False))
_a("softplus", lambda x, a: jax.nn.softplus(x))
_a("softsign", lambda x, a: jax.nn.soft_sign(x))
_a("softshrink", lambda x, a: jnp.where(
    x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
    jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5),
              jnp.zeros_like(x))))
_a("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, jnp.zeros_like(x)))
_a("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_a("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_a("leaky_relu", lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x))
_a("elu", lambda x, a: jnp.where(
    x > 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1.0)))
_a("selu", lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
    x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1.0)))
_a("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
    a.get("scale_a", 0.67) * x))
_a("soft_relu", lambda x, a: jnp.log(
    1.0 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))))
_a("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_a("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, jnp.zeros_like(x)))
_a("pow", lambda x, a: x ** a.get("factor", 1.0))


@register("prelu")
def _prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "all":
        al = alpha.reshape(())
    elif mode == "channel":
        al = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        al = alpha.reshape((1,) + x.shape[1:])
    return {"Out": [jnp.where(x >= 0, x, al * x)]}
