"""Convolution / pooling / interpolation ops (parity: operators/conv_op.cc,
conv_cudnn_op.cu.cc, pool_op.cc, interpolate_op.cc, spectral_norm_op.cc).

TPU-native: all convs lower to `lax.conv_general_dilated` which XLA maps onto
the MXU (the cuDNN algo-search of the reference is subsumed by XLA autotuning,
SURVEY §7 hard-parts note). NCHW layout is kept at the API for Fluid parity;
XLA relayouts internally for the TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _conv_nd(x, w, strides, paddings, dilations, groups, nd, transpose=False,
             preferred=None):
    dn_str = {2: ("NCHW", "OIHW", "NCHW"), 3: ("NCDHW", "OIDHW", "NCDHW")}[nd]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, dn_str)
    pads = [(p, p) for p in paddings]
    if not transpose:
        # NOTE: `preferred` stays None on float convs — the transpose
        # rule of preferred_element_type can't match a trailing cast
        # (mixed-dtype grad error), and XLA accumulates bf16 convs in
        # fp32 on the MXU regardless. Non-None is the NON-differentiable
        # int8 quantized path (quant_rewrite: int8 operands, int32
        # accumulation); passing None is identical to omitting the
        # kwarg (its default).
        return jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=pads,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=preferred,
        )
    # conv transpose: fractionally-strided conv. Fluid filter layout is
    # [C_in, C_out/groups, *k]; flip spatial dims and swap io.
    w_t = jnp.swapaxes(w, 0, 1)  # [C_out/groups, C_in, *k]
    w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + nd)))
    k_eff = [d * (k - 1) + 1 for k, d in zip(w.shape[2:], dilations)]
    pads_t = [(ke - 1 - p, ke - 1 - p) for ke, p in zip(k_eff, paddings)]
    if groups > 1:
        # grouped transpose: block-diagonal over groups
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(w, groups, axis=0)
        outs = []
        for xg, wg in zip(xs, ws):
            wg_t = jnp.flip(jnp.swapaxes(wg, 0, 1), axis=tuple(range(2, 2 + nd)))
            outs.append(jax.lax.conv_general_dilated(
                xg, wg_t, window_strides=(1,) * nd, padding=pads_t,
                lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=dn))
        return jnp.concatenate(outs, axis=1)
    return jax.lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nd, padding=pads_t,
        lhs_dilation=strides, rhs_dilation=dilations, dimension_numbers=dn,
    ).astype(x.dtype)


def _amp_bf16_pair(x, w, attrs):
    """AMP white-list marking (contrib/mixed_precision): bf16 inputs with
    fp32 accumulation — exactly the MXU's native mode. Differentiable
    because the cast sits inside the op's own vjp. Mixed operands (one
    already bf16 from an upstream white op) cast down together —
    lax.conv requires matching dtypes."""
    if attrs.get("__amp_bf16__") \
            and x.dtype in (jnp.float32, jnp.bfloat16) \
            and w.dtype in (jnp.float32, jnp.bfloat16):
        return x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    return x, w


def _make_conv(name, nd, transpose=False):
    def impl(ctx, ins, attrs):
        x, w = ins["Input"][0], ins["Filter"][0]
        x, w = _amp_bf16_pair(x, w, attrs)
        quant = (attrs.get("__quant_int8__")
                 and jnp.issubdtype(x.dtype, jnp.integer)
                 and jnp.issubdtype(w.dtype, jnp.integer))
        out = _conv_nd(
            x, w,
            tuple(attrs.get("strides", [1] * nd)),
            tuple(attrs.get("paddings", [0] * nd)),
            tuple(attrs.get("dilations", [1] * nd)),
            attrs.get("groups", 1) or 1, nd, transpose,
            preferred=jnp.int32 if quant else None,
        )
        # white-list AMP output stays bf16 (reference fp16 semantics): the
        # following batch_norm (black list) upcasts to fp32 itself
        if ins.get("FoldedBias"):
            # per-out-channel shift left behind by conv+bn folding
            # (transpiler/inference_transpiler.py)
            b = ins["FoldedBias"][0].reshape((1, -1) + (1,) * nd)
            out = out + b
        return {"Output": [out]}

    register(name)(impl)


_make_conv("conv2d", 2)
_make_conv("conv3d", 3)
_make_conv("depthwise_conv2d", 2)
_make_conv("conv2d_transpose", 2, transpose=True)
_make_conv("conv3d_transpose", 3, transpose=True)
_make_conv("depthwise_conv2d_transpose", 2, transpose=True)


def _pool_nd(x, attrs, nd):
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [2] * nd))
    strides = list(attrs.get("strides", [1] * nd))
    paddings = list(attrs.get("paddings", [0] * nd))
    exclusive = attrs.get("exclusive", True)
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
        strides = ksize
        paddings = [0] * nd
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides_full,
                                    pads)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                                  window, strides_full, pads)
        if exclusive and any(p > 0 for p in paddings):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides_full, pads)
            out = s / cnt
        else:
            out = s / float(np.prod(ksize))
    return out


@register("pool2d")
def _pool2d(ctx, ins, attrs):
    return {"Out": [_pool_nd(ins["X"][0], attrs, 2)]}


@register("pool3d")
def _pool3d(ctx, ins, attrs):
    return {"Out": [_pool_nd(ins["X"][0], attrs, 3)]}


def _adaptive_pool(x, out_sizes, ptype):
    spatial = x.shape[2:]
    # adaptive pooling with uniform windows when divisible (common case);
    # falls back to mean/max over index buckets otherwise
    if all(s % o == 0 for s, o in zip(spatial, out_sizes)):
        ks = [s // o for s, o in zip(spatial, out_sizes)]
        attrs = {"pooling_type": ptype, "ksize": ks, "strides": ks,
                 "paddings": [0] * len(ks)}
        return _pool_nd(x, attrs, len(ks))
    # bucket-gather fallback (2-D only)
    h, w = spatial
    oh, ow = out_sizes
    out_rows = []
    for i in range(oh):
        hs, he = (i * h) // oh, -(-((i + 1) * h) // oh)
        row = []
        for j in range(ow):
            ws_, we = (j * w) // ow, -(-((j + 1) * w) // ow)
            patch = x[:, :, hs:he, ws_:we]
            if ptype == "max":
                row.append(patch.max(axis=(2, 3)))
            else:
                row.append(patch.mean(axis=(2, 3)))
        out_rows.append(jnp.stack(row, axis=-1))
    return jnp.stack(out_rows, axis=-2)


@register("adaptive_pool2d")
def _adaptive_pool2d(ctx, ins, attrs):
    return {"Out": [_adaptive_pool(ins["X"][0], attrs["ksize"],
                                   attrs.get("pooling_type", "max"))]}


@register("adaptive_pool3d")
def _adaptive_pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    ks = attrs["ksize"]
    if all(s % o == 0 for s, o in zip(x.shape[2:], ks)):
        kk = [s // o for s, o in zip(x.shape[2:], ks)]
        a = {"pooling_type": attrs.get("pooling_type", "max"), "ksize": kk,
             "strides": kk, "paddings": [0, 0, 0]}
        return {"Out": [_pool_nd(x, a, 3)]}
    raise NotImplementedError("non-divisible adaptive_pool3d")


def _pool_max_with_index(x, attrs, nd):
    """Max pool returning (values, argmax Mask of flat indices into the
    input's spatial volume — max_pool_with_index_op.cc semantics)."""
    ksize = list(attrs.get("ksize", [2] * nd))
    strides = list(attrs.get("strides", ksize))
    paddings = list(attrs.get("paddings", [0] * nd))
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
        strides = ksize
        paddings = [0] * nd
    spatial = x.shape[2:]
    # pad explicitly with -inf so padding cells never win the argmax
    widths = [(0, 0), (0, 0)] + [(p, p) for p in paddings]
    xp = jnp.pad(x, widths, constant_values=-jnp.inf)
    patches = jax.lax.conv_general_dilated_patches(
        xp, filter_shape=ksize, window_strides=strides,
        padding=[(0, 0)] * nd)          # [N, C*prod(k), *out_spatial]
    n, c = x.shape[:2]
    k_total = int(np.prod(ksize))
    out_sp = patches.shape[2:]
    patches = patches.reshape((n, c, k_total) + out_sp)
    out = jnp.max(patches, axis=2)
    win_off = jnp.argmax(patches, axis=2)  # flat offset within the window
    # input coordinate = window_start - pad + in-window offset, per dim
    flat = jnp.zeros_like(win_off)
    rem = win_off
    for d in range(nd):
        stride_rest = int(np.prod(ksize[d + 1:]))
        off_d = rem // stride_rest
        rem = rem % stride_rest
        grid = jnp.arange(out_sp[d]) * strides[d] - paddings[d]
        shape = [1] * (2 + nd)
        shape[2 + d] = out_sp[d]
        coord = grid.reshape(shape) + off_d
        coord = jnp.clip(coord, 0, spatial[d] - 1)
        flat = flat * spatial[d] + coord
    return out, flat.astype(jnp.int32)


@register("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    out, mask = _pool_max_with_index(ins["X"][0], attrs, 2)
    return {"Out": [out], "Mask": [mask]}


def _resize_2d(x, oh, ow, method, align_corners):
    n, c, h, w = x.shape
    if method == "nearest":
        if align_corners:
            ys = jnp.round(jnp.linspace(0, h - 1, oh)).astype(jnp.int32)
            xs = jnp.round(jnp.linspace(0, w - 1, ow)).astype(jnp.int32)
        else:
            ys = jnp.floor(jnp.arange(oh) * (h / oh)).astype(jnp.int32)
            xs = jnp.floor(jnp.arange(ow) * (w / ow)).astype(jnp.int32)
        return x[:, :, ys][:, :, :, xs]
    # bilinear
    if align_corners and oh > 1 and ow > 1:
        fy = jnp.linspace(0.0, h - 1.0, oh)
        fx = jnp.linspace(0.0, w - 1.0, ow)
    else:
        fy = (jnp.arange(oh) + 0.5) * (h / oh) - 0.5
        fx = (jnp.arange(ow) + 0.5) * (w / ow) - 0.5
    y0 = jnp.clip(jnp.floor(fy), 0, h - 1).astype(jnp.int32)
    x0 = jnp.clip(jnp.floor(fx), 0, w - 1).astype(jnp.int32)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = jnp.clip(fy - y0, 0.0, 1.0)
    wx = jnp.clip(fx - x0, 0.0, 1.0)
    top = x[:, :, y0][:, :, :, x0] * (1 - wx) + x[:, :, y0][:, :, :, x1] * wx
    bot = x[:, :, y1][:, :, :, x0] * (1 - wx) + x[:, :, y1][:, :, :, x1] * wx
    return top * (1 - wy[:, None]) + bot * wy[:, None]


@register("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [_resize_2d(x, attrs["out_h"], attrs["out_w"], "bilinear",
                               attrs.get("align_corners", True))]}


@register("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [_resize_2d(x, attrs["out_h"], attrs["out_w"], "nearest",
                               attrs.get("align_corners", True))]}


@register("spectral_norm")
def _spectral_norm(ctx, ins, attrs):
    w, u, v = ins["Weight"][0], ins["U"][0], ins["V"][0]
    dim = attrs.get("dim", 0)
    power_iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    if dim != 0:
        perm = [dim] + [i for i in range(w.ndim) if i != dim]
        wm = jnp.transpose(w, perm)
    else:
        wm = w
    h = wm.shape[0]
    mat = wm.reshape((h, -1))
    for _ in range(power_iters):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ (mat @ v)
    return {"Out": [w / sigma]}


@register("random_crop", differentiable=False, stateful=True)
def _random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]
    key = ctx.rng(attrs)
    nd = len(shape)
    starts = []
    for i, s in enumerate(shape):
        d = x.shape[x.ndim - nd + i]
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, max(d - s + 1, 1)))
    idx = [slice(None)] * (x.ndim - nd)
    out = jax.lax.dynamic_slice(
        x,
        tuple([0] * (x.ndim - nd)) + tuple(starts),
        tuple(x.shape[: x.ndim - nd]) + tuple(shape),
    )
    return {"Out": [out]}


@register("im2sequence")
def _im2sequence(ctx, ins, attrs):
    x = ins["X"][0]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pt, pl, pb, pr = (attrs.get("paddings", [0, 0, 0, 0]) + [0, 0, 0, 0])[:4]
    xp = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
    n, c, h, w = xp.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(xp[:, :, i : i + oh * sh : sh, j : j + ow * sw : sw])
    stacked = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
    out = stacked.transpose((0, 3, 4, 1, 2)).reshape((n * oh * ow, c * kh * kw))
    return {"Out": [out]}


@register("unfold")
def _unfold(ctx, ins, attrs):
    x = ins["X"][0]
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    pt, pl, pb, pr = (attrs.get("paddings", [0, 0, 0, 0]) + [0, 0, 0, 0])[:4]
    dh, dw = attrs.get("dilations", [1, 1])
    xp = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
    n, c, h, w = xp.shape
    keh = dh * (kh - 1) + 1
    kew = dw * (kw - 1) + 1
    oh = (h - keh) // sh + 1
    ow = (w - kew) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            ii, jj = i * dh, j * dw
            patches.append(
                xp[:, :, ii : ii + oh * sh : sh, jj : jj + ow * sw : sw])
    stacked = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
    return {"Y": [stacked.reshape((n, c * kh * kw, oh * ow))]}


@register("mean_iou", differentiable=False)
def _mean_iou(ctx, ins, attrs):
    pred = ins["Predictions"][0].reshape((-1,)).astype(jnp.int32)
    label = ins["Labels"][0].reshape((-1,)).astype(jnp.int32)
    n = attrs["num_classes"]
    conf = jnp.zeros((n, n), jnp.int32).at[label, pred].add(1)
    inter = jnp.diag(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1), 0.0)
    miou = iou.sum() / jnp.maximum(valid.sum(), 1)
    wrong = conf.sum(1) - inter
    return {"OutMeanIou": [miou.astype(jnp.float32)],
            "OutWrong": [wrong.astype(jnp.int32)],
            "OutCorrect": [inter.astype(jnp.int32)]}
