"""Recurrent cell ops (parity: operators/gru_unit_op.cc, lstm_unit_op.cc,
gru_op.cc, lstm_op.cc — the fused recurrences lower to lax.scan over MXU
matmul steps).
"""

import jax
import jax.numpy as jnp

from .registry import register


def _act(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
    }[name]


@register("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """One GRU step (gru_unit_op.cc). Input: [B, 3D] projected input;
    HiddenPrev [B, D]; Weight [D, 3D] (gates [D, 2D] | candidate [D, D])."""
    x = ins["Input"][0]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    d = h_prev.shape[-1]
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    act = _act(attrs.get("activation", "tanh"))
    origin_mode = attrs.get("origin_mode", False)

    xg = x
    if ins.get("Bias"):
        xg = xg + ins["Bias"][0]
    w_gates = w[:, : 2 * d]
    w_cand = w[:, 2 * d :]
    gates = gate_act(xg[:, : 2 * d] + h_prev @ w_gates)
    u, r = gates[:, :d], gates[:, d:]
    reset_h = r * h_prev
    cand = act(xg[:, 2 * d :] + reset_h @ w_cand)
    if origin_mode:
        h = u * h_prev + (1.0 - u) * cand
    else:
        h = (1.0 - u) * h_prev + u * cand
    return {"Hidden": [h], "Gate": [jnp.concatenate([u, r, cand], -1)],
            "ResetHiddenPrev": [reset_h]}


@register("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """One LSTM step (lstm_unit_op.cc): X [B, 4D] pre-projected, C_prev."""
    x = ins["X"][0]
    c_prev = ins["C_prev"][0]
    d = c_prev.shape[-1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i, j, f, o = jnp.split(x, 4, axis=-1)
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + jax.nn.sigmoid(
        i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    return {"C": [c], "H": [h]}


@register("gru")
def _gru(ctx, ins, attrs):
    """Full-sequence GRU (gru_op.cc): Input [B, T, 3D] pre-projected,
    lax.scan over time."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    d = w.shape[0]
    b = x.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, d), x.dtype)
    bias = ins["Bias"][0] if ins.get("Bias") else None
    is_reverse = attrs.get("is_reverse", False)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    act = _act(attrs.get("activation", "tanh"))
    origin_mode = attrs.get("origin_mode", False)
    xt_seq = jnp.swapaxes(x, 0, 1)  # [T, B, 3D]
    if is_reverse:
        xt_seq = jnp.flip(xt_seq, 0)

    w_gates = w[:, : 2 * d]
    w_cand = w[:, 2 * d :]

    def step(h_prev, xt):
        if bias is not None:
            xt = xt + bias
        gates = gate_act(xt[:, : 2 * d] + h_prev @ w_gates)
        u, r = gates[:, :d], gates[:, d:]
        cand = act(xt[:, 2 * d :] + (r * h_prev) @ w_cand)
        if origin_mode:
            h = u * h_prev + (1.0 - u) * cand
        else:
            h = (1.0 - u) * h_prev + u * cand
        return h, h

    h_last, hs = jax.lax.scan(step, h0, xt_seq)
    if is_reverse:
        hs = jnp.flip(hs, 0)
    hidden = jnp.swapaxes(hs, 0, 1)  # [B, T, D]
    return {"Hidden": [hidden], "BatchGate": [hidden],
            "BatchResetHiddenPrev": [hidden], "BatchHidden": [hidden]}


@register("lstm")
def _lstm(ctx, ins, attrs):
    """Full-sequence LSTM (lstm_op.cc): Input [B, T, 4D] pre-projected."""
    x = ins["Input"][0]
    w = ins["Weight"][0]  # [D, 4D]
    d = w.shape[0]
    b = x.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, d), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, d), x.dtype)
    bias = ins["Bias"][0] if ins.get("Bias") else None
    is_reverse = attrs.get("is_reverse", False)
    xt_seq = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xt_seq = jnp.flip(xt_seq, 0)

    def step(carry, xt):
        h_prev, c_prev = carry
        g = xt + h_prev @ w
        if bias is not None:
            g = g + bias[:, : 4 * d] if bias.ndim == 2 else g + bias
        i, f, cand, o = jnp.split(g, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(cand)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), (h, c)

    (h_last, c_last), (hs, cs) = jax.lax.scan(step, (h0, c0), xt_seq)
    if is_reverse:
        hs = jnp.flip(hs, 0)
        cs = jnp.flip(cs, 0)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "BatchGate": [jnp.swapaxes(hs, 0, 1)],
            "BatchCellPreAct": [jnp.swapaxes(cs, 0, 1)]}
