"""The fused/fusion op-registry tail (round-3 VERDICT missing #4): the nine
reference fused op types that were still absent, so a saved reference
program containing them now loads and runs.

TPU-native stance: these ops exist in the reference as hand-written CPU-JIT
or cuDNN kernels (operators/fused/*.cc); here each is a COMPOSITE of the
already-registered kernels — XLA fuses the composition on its own, so the
value of registering them is format compatibility, not speed. Semantics
are the reference kernels', checked against unfused compositions in
tests/test_fused_tail_ops.py.
"""

import jax
import jax.numpy as jnp

from .registry import register, get

_ACTS = {
    "identity": lambda x: x,
    "": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0, 6),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}


def _act(name):
    key = (name or "identity").strip().lower()
    if key not in _ACTS:
        raise ValueError(
            "fused op activation %r is not supported (choose from %s)"
            % (name, sorted(k for k in _ACTS if k)))
    return _ACTS[key]


@register("conv2d_fusion")
def _conv2d_fusion(ctx, ins, attrs):
    """conv + bias + (residual add) + activation [+ channel split]
    (conv_fusion_op.cc Conv2DFusionOpMaker; cuDNN's
    ConvolutionBiasActivationForward)."""
    out = get("conv2d").impl(ctx, {"Input": ins["Input"],
                                   "Filter": ins["Filter"]},
                             attrs)["Output"][0]
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1, 1, 1).astype(out.dtype)
    if ins.get("ResidualData"):
        out = out + ins["ResidualData"][0].astype(out.dtype)
    out = _act(attrs.get("activation", "relu"))(out)
    split = [int(s) for s in attrs.get("split_channels", []) or []]
    if split:
        pieces, start = [], 0
        for s in split:
            pieces.append(out[:, start:start + s])
            start += s
        return {"Output": [out], "Outputs": pieces}
    return {"Output": [out]}


@register("conv2d_inception_fusion")
def _conv2d_inception_fusion(ctx, ins, attrs):
    """Inception module with the reference kernel's exact dataflow
    (fusion_conv_inception_op.cc InferShape:40-49, .cu kernel): all convs
    stride 1; branch 0 = 3x3 pool (``pooling_type``/``exclusive`` attrs,
    pad 1) then 1x1 conv; branch 1 = 1x1 conv of the input whose FIRST
    oc1 = w1[0]-2*w2[1] output channels join the result and whose last
    2*w2[1] channels feed branch 2 — a 3x3 conv with groups=2 (.cu:159);
    branch 2's first oc2 = w2[0]-w3[1] channels join the result and its
    last w3[1] feed branch 3 (3x3 conv). Bias+activation applies to every
    conv's FULL output (ConvolutionBiasActivationForward), including the
    pass-through channels. TempOutput = [pool output, branch-2 full
    output] — the kernel's scratch-tensor contract (.cu:61,:208).

    The kernel hardcodes pads {0,0,1,1} for the four convs, which is
    same-spatial only for kernel sizes {1,1,3,3} (InferShape asserts the
    output is N,C,H,W) — other shapes are rejected rather than silently
    computed differently."""
    x = ins["Input"][0]
    w0, w1, w2, w3 = ins["Filter"]
    biases = ins.get("Bias") or [None] * 4
    act = _act(attrs.get("activation", "relu"))
    ks = tuple(tuple(int(s) for s in w.shape[-2:])
               for w in (w0, w1, w2, w3))
    if ks != ((1, 1), (1, 1), (3, 3), (3, 3)):
        raise ValueError(
            "conv2d_inception_fusion models the reference kernel's fixed "
            "1x1/1x1/3x3/3x3 branch shapes (fusion_conv_inception_op.cu "
            "pads {0,0,1,1}); got kernel sizes %r" % (ks,))
    ic2 = int(w2.shape[1])          # per-group in-channels, groups=2
    oc1 = int(w1.shape[0]) - 2 * ic2
    oc2 = int(w2.shape[0]) - int(w3.shape[1])
    if oc1 < 0 or oc2 < 0:
        raise ValueError(
            "conv2d_inception_fusion channel contract violated: need "
            "w1[0] >= 2*w2[1] and w2[0] >= w3[1] (InferShape:45-47); got "
            "filters %r" % ([tuple(w.shape) for w in (w0, w1, w2, w3)],))

    def conv(inp, w, pad, groups=1):
        return get("conv2d").impl(ctx, {"Input": [inp], "Filter": [w]}, {
            "strides": [1, 1], "paddings": [pad, pad],
            "dilations": [1, 1], "groups": groups})["Output"][0]

    def bias_act(o, b):
        if b is not None:
            o = o + b.reshape(1, -1, 1, 1).astype(o.dtype)
        return act(o)

    pool_out = get("pool2d").impl(ctx, {"X": [x]}, {
        "pooling_type": attrs.get("pooling_type", "avg"),
        "ksize": [3, 3], "strides": [1, 1], "paddings": [1, 1],
        "exclusive": bool(attrs.get("exclusive", True))})["Out"][0]
    b0 = bias_act(conv(pool_out, w0, pad=0), biases[0])
    t1 = bias_act(conv(x, w1, pad=0), biases[1])
    b1, u = t1[:, :oc1], t1[:, oc1:]
    t2 = bias_act(conv(u, w2, pad=1, groups=2), biases[2])
    b2, v = t2[:, :oc2], t2[:, oc2:]
    b3 = bias_act(conv(v, w3, pad=1), biases[3])
    out = jnp.concatenate([b0, b1, b2, b3], axis=1)
    return {"Output": [out], "TempOutput": [pool_out, t2]}


@register("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """Embedding lookup folded into the LSTM input projection: the
    Embeddings table is the pre-multiplied [vocab, 4D] gate projection, so
    the lookup IS the fc (fused_embedding_fc_lstm_op.cc)."""
    ids = ins["Ids"][0]
    emb = ins["Embeddings"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    xx = jnp.take(emb, ids.astype(jnp.int32), axis=0)  # [B, T, 4D]
    lstm_ins = {"Input": [xx], "Weight": ins["WeightH"],
                "Bias": ins.get("Bias", [])}
    for slot in ("H0", "C0"):
        if ins.get(slot):
            lstm_ins[slot] = ins[slot]
    out = get("lstm").impl(ctx, lstm_ins, attrs)
    out["XX"] = [xx]
    return out


@register("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ctx, ins, attrs):
    """Chain of fc+relu layers, relu after EVERY fc including the last
    (fusion_repeated_fc_relu_op.cc fc_relu per layer)."""
    x = ins["X"][0]
    ws = ins["W"]
    bs = ins.get("Bias", [None] * len(ws))
    relu_outs = []
    for i, w in enumerate(ws):
        x2 = x.reshape(-1, x.shape[-1]) if x.ndim > 2 else x
        y = x2 @ w
        if bs[i] is not None:
            y = y + bs[i].reshape(-1)
        x = jax.nn.relu(y)
        if i < len(ws) - 1:
            relu_outs.append(x)
    return {"Out": [x], "ReluOut": relu_outs}


@register("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """sequence_conv + bias add + relu
    (fusion_seqconv_eltadd_relu_op.cc)."""
    conv = get("sequence_conv").impl(
        ctx, {"X": ins["X"], "Filter": ins["Filter"],
              **({"SeqLen": ins["SeqLen"]} if ins.get("SeqLen") else {})},
        attrs)["Out"][0]
    out = jax.nn.relu(conv + ins["Bias"][0].reshape(-1))
    # ColMat: the REAL im2col matrix [B*T, ctx_len*D] — context windows
    # unfolded the same way sequence_conv consumes them (zero-padded at
    # sequence edges)
    x = ins["X"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    B, T, D = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(x, -off, axis=1)
        t_idx = jnp.arange(T)
        valid = (t_idx + off >= 0) & (t_idx + off < T)
        cols.append(jnp.where(valid[None, :, None], shifted, 0))
    colmat = jnp.concatenate(cols, axis=-1).reshape(B * T, ctx_len * D)
    return {"Out": [out], "ColMat": [colmat]}


@register("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """First X is the time-major sequence [B, T, D0]; the rest are per-row
    vectors broadcast over T; concat on features then fc + activation
    (fusion_seqexpand_concat_fc_op.cc)."""
    xs = ins["X"]
    ref = xs[0]
    T = ref.shape[1]
    parts = [ref]
    for x in xs[1:]:
        if x.ndim == 2:
            parts.append(jnp.broadcast_to(
                x[:, None, :], (x.shape[0], T, x.shape[1])))
        else:
            parts.append(x)
    cat = jnp.concatenate(parts, axis=-1)
    y = cat @ ins["FCWeight"][0]
    if ins.get("FCBias"):
        y = y + ins["FCBias"][0].reshape(-1)
    out = _act(attrs.get("fc_activation", "identity"))(y)
    return {"Out": [out], "FCOut": [y]}


@register("fusion_seqpool_concat")
def _fusion_seqpool_concat(ctx, ins, attrs):
    """sequence_pool each input then concat along `axis`
    (fusion_seqpool_concat_op.cc)."""
    pooled = [
        get("sequence_pool").impl(ctx, {"X": [x]}, {
            "pooltype": attrs.get("pooltype", "SUM")})["Out"][0]
        for x in ins["X"]
    ]
    return {"Out": [jnp.concatenate(pooled, axis=attrs.get("axis", 1))]}


@register("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ctx, ins, attrs):
    """Out = scalar * ((X@Y)^2 - (X^2)@(Y^2))
    (fusion_squared_mat_sub_op.cc — the DeepFM second-order interaction)."""
    x, y = ins["X"][0], ins["Y"][0]
    scalar = attrs.get("scalar", 1.0)
    sx = x * x
    sy = y * y
    sxy = (x @ y) ** 2
    out = scalar * (sxy - sx @ sy)
    return {"Out": [out], "SquaredX": [sx], "SquaredY": [sy],
            "SquaredXY": [sxy]}


@register("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ctx, ins, attrs):
    """Per input: transpose by trans_axis, flatten from flatten_axis, then
    concat along concat_axis (fusion_transpose_flatten_concat_op.cc)."""
    trans = [int(a) for a in attrs.get("trans_axis", [])]
    flatten_axis = int(attrs.get("flatten_axis", 1))
    concat_axis = int(attrs.get("concat_axis", 1))
    outs = []
    for x in ins["X"]:
        if trans:
            x = jnp.transpose(x, trans)
        lead = 1
        for d in x.shape[:flatten_axis]:
            lead *= d
        outs.append(x.reshape(lead, -1))
    return {"Out": [jnp.concatenate(outs, axis=concat_axis)]}
