"""In-graph metric ops (parity: operators/metrics/ — accuracy_op.cc,
auc_op.cc, precision_recall_op.cc)."""

import jax.numpy as jnp

from .registry import register


@register("accuracy", differentiable=False)
def _accuracy(ctx, ins, attrs):
    """Top-k accuracy (accuracy_op.cc): Out=topk values, Indices=topk ids,
    Label=[N,1] int labels -> Accuracy [1], Correct [1], Total [1]."""
    indices = ins["Indices"][0]
    label = ins["Label"][0].reshape((-1, 1))
    correct_mask = jnp.any(indices == label, axis=1)
    correct = jnp.sum(correct_mask.astype(jnp.float32))
    total = jnp.asarray(indices.shape[0], jnp.float32)
    acc = (correct / total).reshape((1,))
    return {
        "Accuracy": [acc],
        "Correct": [correct.reshape((1,)).astype(jnp.int32)],
        "Total": [total.reshape((1,)).astype(jnp.int32)],
    }


@register("auc", differentiable=False)
def _auc(ctx, ins, attrs):
    """Streaming AUC by threshold histogram (auc_op.cc): positive/negative
    counts bucketed over `num_thresholds` prediction bins, carried in
    persistable StatPos/StatNeg vars that this op updates functionally."""
    predict = ins["Predict"][0]
    label = ins["Label"][0].reshape((-1,))
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    num_thresholds = stat_pos.shape[0] - 1

    # probability of the positive class: column 1 of [N,2] softmax, or the
    # raw score when 1-D
    score = predict[:, 1] if predict.ndim == 2 and predict.shape[1] >= 2 \
        else predict.reshape((-1,))
    bins = jnp.clip((score * num_thresholds).astype(jnp.int32),
                    0, num_thresholds)
    is_pos = (label > 0).astype(stat_pos.dtype)
    stat_pos = stat_pos.at[bins].add(is_pos)
    stat_neg = stat_neg.at[bins].add(1.0 - is_pos)

    # trapezoid rule over the ROC curve swept from the highest bin down
    pos_flip = stat_pos[::-1]
    neg_flip = stat_neg[::-1]
    tp = jnp.cumsum(pos_flip)
    fp = jnp.cumsum(neg_flip)
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros((1,), tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros((1,), fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    denom = tot_pos * tot_neg
    auc = jnp.where(denom > 0, area / jnp.maximum(denom, 1.0), 0.0)
    return {
        "AUC": [auc.reshape((1,))],
        "StatPosOut": [stat_pos],
        "StatNegOut": [stat_neg],
    }


@register("precision_recall", differentiable=False)
def _precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall/F1, macro + micro averaged
    (precision_recall_op.cc). MaxProbs-free variant: takes Indices (predicted
    class ids) + Labels; accumulates into StatesInfo [C,4] rows of
    (TP, FP, TN, FN)."""
    idx = ins["Indices"][0].reshape((-1,))
    label = ins["Labels"][0].reshape((-1,))
    states = ins["StatesInfo"][0]
    ncls = states.shape[0]

    onehot_pred = (idx[:, None] == jnp.arange(ncls)[None, :])
    onehot_lab = (label[:, None] == jnp.arange(ncls)[None, :])
    tp = jnp.sum(onehot_pred & onehot_lab, axis=0).astype(states.dtype)
    fp = jnp.sum(onehot_pred & ~onehot_lab, axis=0).astype(states.dtype)
    fn = jnp.sum(~onehot_pred & onehot_lab, axis=0).astype(states.dtype)
    tn = jnp.sum(~onehot_pred & ~onehot_lab, axis=0).astype(states.dtype)
    states = states + jnp.stack([tp, fp, tn, fn], axis=1)

    def prf(tp_, fp_, fn_):
        prec = tp_ / jnp.maximum(tp_ + fp_, 1.0)
        rec = tp_ / jnp.maximum(tp_ + fn_, 1.0)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
        return prec, rec, f1

    # batch metrics from this batch only; accum metrics from updated states
    b = prf(tp, fp, fn)
    a = prf(states[:, 0], states[:, 1], states[:, 3])
    batch_metrics = jnp.concatenate([jnp.mean(m).reshape((1,)) for m in b]
                                    + [jnp.sum(tp).reshape((1,))])
    accum_metrics = jnp.concatenate([jnp.mean(m).reshape((1,)) for m in a]
                                    + [jnp.sum(states[:, 0]).reshape((1,))])
    return {
        "BatchMetrics": [batch_metrics],
        "AccumMetrics": [accum_metrics],
        "AccumStatesInfo": [states],
    }
