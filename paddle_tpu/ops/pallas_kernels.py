"""Pallas TPU kernels for the hot ops XLA doesn't fuse optimally
(SURVEY §7 design mapping: "hand-written Pallas kernels only where XLA
underperforms — attention/softmax fusions, top-k/DGC").

flash_attention: blocked causal attention with online softmax — the
  O(T) -memory replacement for the naive [T, T] score matrix. Forward is a
  Pallas kernel (grid over (batch*heads, q blocks, kv blocks), VMEM
  accumulators carried across the innermost kv dimension); backward is the
  standard recompute formulation via jax.custom_vjp, left to XLA fusion.

Kernels run under interpret=True off-TPU so the CPU test mesh exercises the
same code path (tests/test_pallas.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                sm_scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    # skip fully-masked kv blocks (strictly above the causal diagonal)
    run = True
    if causal:
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = k_pos < kv_len  # padded keys
        if causal:
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=128,
                    block_k=128):
    """Blocked attention (q, k, v: [B, H, T, D]). Single dispatch point:
    on a real TPU backend this routes to the jax library's TPU flash kernel
    (fully-blocked Pallas backward, no [T, T] residuals — measured ~20%
    faster in-model with seq-wide blocks than the 128 defaults); everywhere
    else (CPU mesh, interpret mode) it runs the portable in-repo kernel
    below, whose backward recomputes attention through XLA."""
    if jax.default_backend() == "tpu":
        T = q.shape[2]
        blk = next((b for b in (512, 256, 128) if T % b == 0 and b <= T),
                   None)
        if blk is not None:
            try:
                from jax.experimental.pallas.ops.tpu.flash_attention import (
                    BlockSizes, flash_attention as tpu_flash)
            except ImportError:
                tpu_flash = None
            if tpu_flash is not None:
                bs = BlockSizes(
                    block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
                    block_q_major_dkv=blk, block_k_major_dkv=blk,
                    block_k_dkv=blk, block_q_dkv=blk,
                    block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
                if sm_scale is None:
                    sm_scale = q.shape[-1] ** -0.5
                return tpu_flash(q, k, v, causal=causal, sm_scale=sm_scale,
                                 block_sizes=bs)
    return flash_attention_portable(q, k, v, causal, sm_scale, block_q,
                                    block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_portable(q, k, v, causal=True, sm_scale=None,
                             block_q=128, block_k=128):
    """The in-repo blocked kernel, O(block) VMEM (q, k, v: [B, H, T, D])."""
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    B, H, T, D = q.shape
    Tk = k.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    interpret = jax.default_backend() != "tpu"

    qp = _pad_to(q.reshape(B * H, T, D), 1, block_q)
    kp = _pad_to(k.reshape(B * H, Tk, D), 1, block_k)
    vp = _pad_to(v.reshape(B * H, Tk, D), 1, block_k)
    Tq_p, Tk_p = qp.shape[1], kp.shape[1]
    grid = (B * H, Tq_p // block_q, Tk_p // block_k)

    if pltpu is not None:
        scratch = [
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ]
    else:  # pragma: no cover - CPU-only install without the tpu module
        scratch = [
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, D), jnp.float32),
        ]

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :T].reshape(B, H, T, D)


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    """Backward by recompute (standard flash-attention formulation); the
    [T, T] intermediate is rematerialized and XLA-fused, trading FLOPs for
    the HBM the naive backward would burn."""
    q, k, v = res
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else D ** -0.5

    def attn(q32, k32, v32):
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
        if causal:
            Tq, Tk = s.shape[-2], s.shape[-1]
            mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
            s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v32)

    f32 = jnp.float32
    _, vjp = jax.vjp(attn, q.astype(f32), k.astype(f32), v.astype(f32))
    dq, dk, dv = vjp(g.astype(f32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention_portable.defvjp(_flash_fwd_rule, _flash_bwd_rule)
