"""Pallas TPU kernel library — the hot ops XLA doesn't fuse optimally
(SURVEY §7 design mapping: "hand-written Pallas kernels only where XLA
underperforms — attention/softmax fusions, top-k/DGC"; the reference
framework's per-op CUDA kernel corpus, re-grown TPU-native).

The kernels (each registered in ops/kernel_registry with its lax
fallback, shape qualification and platform policy — docs/KERNELS.md):

flash_attention: blocked causal attention with online softmax — the
  O(T) -memory replacement for the naive [T, T] score matrix. Forward is a
  Pallas kernel (grid over (batch*heads, q blocks, kv blocks), VMEM
  accumulators carried across the innermost kv dimension); backward is the
  standard recompute formulation via jax.custom_vjp, left to XLA fusion.

paged_attention: decode-side attention that reads the serving
  ``KVBlockPool`` pages THROUGH the block table (the block-sparse gather
  happens inside the kernel via scalar-prefetch BlockSpec index maps, the
  PagedAttention formulation) — the per-step contiguous
  ``kv[block_tables].reshape(...)`` gather the XLA path materializes
  disappears. One kernel serves both the one-token decode window (C=1,
  ``kernel 'paged_decode'``) and the speculative verify window (C=k+1,
  ``kernel 'spec_window'``).

int8_matmul: fused int8×int8→int32 matmul for the full-int8 quant path —
  the activation quantizes IN-KERNEL (per-tensor scale), the dot
  accumulates int32 on the MXU int8 path, and the per-output-channel
  dequantize applies on the final K block, so the separate
  quantize/dequantize_linear HLOs around each rewritten matmul vanish.

Kernels run under interpret=True off-TPU so the CPU test mesh exercises
the same code path (tests/test_pallas.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


__all__ = ["flash_attention", "flash_attention_portable",
           "attention_reference", "paged_attention",
           "paged_attention_reference", "paged_attention_tree",
           "paged_attention_tree_reference", "int8_matmul",
           "int8_matmul_reference"]

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                sm_scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    # skip fully-masked kv blocks (strictly above the causal diagonal)
    run = True
    if causal:
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = k_pos < kv_len  # padded keys
        if causal:
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=128,
                    block_k=128):
    """Blocked attention (q, k, v: [B, H, T, D]). Single dispatch point:
    on a real TPU backend this routes to the jax library's TPU flash kernel
    (fully-blocked Pallas backward, no [T, T] residuals — measured ~20%
    faster in-model with seq-wide blocks than the 128 defaults); everywhere
    else (CPU mesh, interpret mode) it runs the portable in-repo kernel
    below, whose backward recomputes attention through XLA."""
    # library path only for the self-attention shape it was profiled on;
    # cross-attention (Tk != Tq) runs the portable kernel, whose kv_len
    # masking handles ragged kv blocks
    if jax.default_backend() == "tpu" and q.shape == k.shape:
        T = q.shape[2]
        blk = next((b for b in (512, 256, 128) if T % b == 0 and b <= T),
                   None)
        if blk is not None:
            try:
                from jax.experimental.pallas.ops.tpu.flash_attention import (
                    BlockSizes, flash_attention as tpu_flash)
            except ImportError:
                tpu_flash = None
            if tpu_flash is not None:
                bs = BlockSizes(
                    block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
                    block_q_major_dkv=blk, block_k_major_dkv=blk,
                    block_k_dkv=blk, block_q_dkv=blk,
                    block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
                if sm_scale is None:
                    sm_scale = q.shape[-1] ** -0.5
                return tpu_flash(q, k, v, causal=causal, sm_scale=sm_scale,
                                 block_sizes=bs)
    return flash_attention_portable(q, k, v, causal, sm_scale, block_q,
                                    block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_portable(q, k, v, causal=True, sm_scale=None,
                             block_q=128, block_k=128):
    """The in-repo blocked kernel, O(block) VMEM (q, k, v: [B, H, T, D])."""
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    B, H, T, D = q.shape
    Tk = k.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    interpret = jax.default_backend() != "tpu"

    qp = _pad_to(q.reshape(B * H, T, D), 1, block_q)
    kp = _pad_to(k.reshape(B * H, Tk, D), 1, block_k)
    vp = _pad_to(v.reshape(B * H, Tk, D), 1, block_k)
    Tq_p, Tk_p = qp.shape[1], kp.shape[1]
    grid = (B * H, Tq_p // block_q, Tk_p // block_k)

    if pltpu is not None:
        scratch = [
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ]
    else:  # pragma: no cover - CPU-only install without the tpu module
        scratch = [
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, D), jnp.float32),
        ]

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :T].reshape(B, H, T, D)


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    """Backward by recompute (standard flash-attention formulation); the
    [T, T] intermediate is rematerialized and XLA-fused, trading FLOPs for
    the HBM the naive backward would burn."""
    q, k, v = res
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else D ** -0.5

    def attn(q32, k32, v32):
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
        if causal:
            Tq, Tk = s.shape[-2], s.shape[-1]
            mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
            s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v32)

    f32 = jnp.float32
    _, vjp = jax.vjp(attn, q.astype(f32), k.astype(f32), v.astype(f32))
    dq, dk, dv = vjp(g.astype(f32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention_portable.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def attention_reference(q, k, v, causal=True, sm_scale=None):
    """The unfused lax reference for flash_attention (q, k, v:
    [B, H, T, D]) — the registry fallback and the numerics oracle the
    kernel tests pin against."""
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else D ** -0.5
    f32 = jnp.float32
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(f32), k.astype(f32)) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(f32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# paged attention: decode / speculative verify windows over KVBlockPool
# pages, block tables resolved INSIDE the kernel (scalar-prefetch index
# maps — the PagedAttention formulation)
# ---------------------------------------------------------------------------


def _paged_attn_kernel(tables_ref, lastpos_ref, q_ref, k_ref, v_ref,
                       pos_ref, o_ref, m_scr, l_scr, acc_scr, *,
                       sm_scale, block_size):
    """Grid (B, H, Mb); j (the block-table slot) is innermost, carrying
    the online-softmax state across one row's pages. The k/v BlockSpec
    index maps already resolved table slot j to its PHYSICAL page (null
    pages land here too — harmless, their logical positions are masked
    or the whole block is skipped)."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    C = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages wholly past the row's LAST query position hold nothing any
    # window slot may attend to — skip their compute (their table
    # entries are the null page anyway)
    @pl.when(j * block_size <= lastpos_ref[b])
    def _body():
        q = q_ref[0, :, 0, :].astype(jnp.float32)       # [C, Dh]
        k = k_ref[0, :, 0, :].astype(jnp.float32)       # [bs, Dh]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [C, bs]
        # logical positions covered by table slot j vs each window
        # slot's own position (causal within the window)
        t_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (C, block_size), 1)
        mask = t_pos <= pos_ref[0]                      # pos: [C, 1]
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0, :, 0, :] = (acc_scr[:]
                             / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def paged_attention(k_pages, v_pages, q, block_tables, positions,
                    sm_scale=None):
    """Attention over a paged KV cache, block tables resolved in-kernel.

    k_pages/v_pages: ``[num_blocks+1, block_size, H, Dh]`` — ONE layer of
    the ``KVBlockPool`` device arrays (page 0 is the null page).
    q: ``[B, C, H, Dh]`` query window (C=1 for plain decode, C=k+1 for
    the speculative verify window). block_tables: ``[B, Mb]`` int32 —
    table slot j holds the physical page covering logical positions
    ``[j*bs, (j+1)*bs)``; unallocated slots hold the null page.
    positions: ``[B, C]`` int32 — window slot c attends to logical
    positions ``t <= positions[b, c]`` (the row's k/v for the whole
    window are written before the call, exactly like the XLA path).

    Returns the ``[B, C, H, Dh]`` fp32 context. Numerics: online softmax
    (flash formulation) — token-identical to the gathered reference, not
    bitwise (docs/KERNELS.md)."""
    if pltpu is None:  # pragma: no cover - guarded by registry qualify
        raise RuntimeError("paged_attention needs pallas TPU support "
                           "(scalar-prefetch grid specs)")
    B, C, H, Dh = q.shape
    bs = k_pages.shape[1]
    Mb = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = Dh ** -0.5
    interpret = jax.default_backend() != "tpu"

    tables = block_tables.astype(jnp.int32)
    pos = jnp.maximum(positions, 0).astype(jnp.int32)    # [B, C]
    last_pos = pos[:, C - 1]                             # [B]
    pos3 = pos[:, :, None]                               # [B, C, 1]

    grid = (B, H, Mb)
    kernel = functools.partial(_paged_attn_kernel, sm_scale=sm_scale,
                               block_size=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, C, 1, Dh),
                         lambda b, h, j, tables, lp: (b, 0, h, 0)),
            pl.BlockSpec((1, bs, 1, Dh),
                         lambda b, h, j, tables, lp: (tables[b, j],
                                                      0, h, 0)),
            pl.BlockSpec((1, bs, 1, Dh),
                         lambda b, h, j, tables, lp: (tables[b, j],
                                                      0, h, 0)),
            pl.BlockSpec((1, C, 1),
                         lambda b, h, j, tables, lp: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, 1, Dh),
                               lambda b, h, j, tables, lp: (b, 0, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, 128), jnp.float32),
            pltpu.VMEM((C, 128), jnp.float32),
            pltpu.VMEM((C, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, C, H, Dh), jnp.float32),
        interpret=interpret,
    )(tables, last_pos, q, k_pages, v_pages, pos3)


def paged_attention_reference(k_pages, v_pages, q, block_tables,
                              positions, sm_scale=None):
    """The unfused lax fallback: contiguous gather through the block
    table, then masked softmax attention — element-for-element the
    serving model's historical XLA decode-attention path."""
    B, C, H, Dh = q.shape
    bs = k_pages.shape[1]
    max_ctx = block_tables.shape[1] * bs
    if sm_scale is None:
        sm_scale = Dh ** -0.5
    k_ctx = k_pages[block_tables].reshape(B, max_ctx, H, Dh)
    v_ctx = v_pages[block_tables].reshape(B, max_ctx, H, Dh)
    scores = jnp.einsum("bchd,bthd->bcht", q, k_ctx) * sm_scale
    t_ids = jnp.arange(max_ctx)[None, None, :]
    valid = t_ids <= positions[:, :, None]
    scores = jnp.where(valid[:, :, None, :], scores, -jnp.inf)
    w = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    w = w / jnp.sum(w, axis=-1, keepdims=True)
    return jnp.einsum("bcht,bthd->bchd", w, v_ctx)


# ---------------------------------------------------------------------------
# tree-mask spec window: the paged verify window generalized to a token
# TREE — visibility inside the window follows the ancestor matrix, not
# the linear causal diagonal (committed prefix stays fully visible)
# ---------------------------------------------------------------------------


def _paged_attn_tree_kernel(tables_ref, lastpos_ref, q_ref, k_ref, v_ref,
                            pos_ref, anc_ref, o_ref, m_scr, l_scr,
                            acc_scr, *, sm_scale, block_size):
    """Grid (B, H, Mb), j innermost — the linear spec-window kernel with
    the in-window causal diagonal swapped for the ancestor mask. Window
    slot c sits at CACHE position pos0+c; a key at logical position t is
    visible to slot c iff t < pos0 (committed prefix, strict — slot 0's
    own write is window-visible via anc[0, 0], never prefix-visible) or
    t-pos0 is an ancestor of c in the tree. The ancestor lookup runs as
    a one-hot matmul against the [C, C] float ancestor matrix — no
    in-kernel gathers."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    C = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j * block_size <= lastpos_ref[b])
    def _body():
        q = q_ref[0, :, 0, :].astype(jnp.float32)       # [C, Dh]
        k = k_ref[0, :, 0, :].astype(jnp.float32)       # [bs, Dh]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [C, bs]

        # logical positions covered by table slot j, relative to the
        # window base (pos_ref holds the CACHE position of each window
        # slot: pos0 + c)
        t_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (C, block_size), 1)
        pos0 = pos_ref[0, 0]                            # pos: [C, 1]
        rel = t_pos - pos0                              # row-constant
        # anc[c, rel] via one-hot matmul: onehot[r, t] = (rel_t == r)
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (C, block_size), 0)
                  == rel).astype(jnp.float32)
        win_vis = jax.lax.dot_general(
            anc_ref[:], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.0   # [C, bs]
        mask = (rel < 0) | win_vis
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0, :, 0, :] = (acc_scr[:]
                             / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def paged_attention_tree(k_pages, v_pages, q, block_tables, positions,
                         anc, sm_scale=None):
    """Tree-mask verify window over the paged KV cache, one kernel.

    Same contract as :func:`paged_attention` except the window is a
    speculation TREE: positions: ``[B, C]`` int32, the CACHE position of
    each window slot (``positions[b, c] = pos0_b + c`` — level-order slot
    c writes cache position pos0+c regardless of its tree depth). anc:
    ``[C, C]`` — ``anc[c, t]`` truthy iff window slot t is c or an
    ancestor of c (passed as float so the kernel can resolve it as a
    one-hot matmul). A key at logical position t is visible to slot c
    iff ``t < pos0`` (committed prefix, STRICT) or ``anc[c, t-pos0]``.

    With the linear-chain ancestor matrix (lower-triangular ones) this
    is numerically identical to the linear spec window. Returns the
    ``[B, C, H, Dh]`` fp32 context; online-softmax numerics, token-
    identical (not bitwise) to the gathered reference."""
    if pltpu is None:  # pragma: no cover - guarded by registry qualify
        raise RuntimeError("paged_attention_tree needs pallas TPU "
                           "support (scalar-prefetch grid specs)")
    B, C, H, Dh = q.shape
    bs = k_pages.shape[1]
    Mb = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = Dh ** -0.5
    interpret = jax.default_backend() != "tpu"

    tables = block_tables.astype(jnp.int32)
    pos = jnp.maximum(positions, 0).astype(jnp.int32)    # [B, C]
    last_pos = pos[:, C - 1]                             # [B] = pos0+C-1
    pos3 = pos[:, :, None]                               # [B, C, 1]
    anc_f = jnp.asarray(anc, jnp.float32)

    grid = (B, H, Mb)
    kernel = functools.partial(_paged_attn_tree_kernel, sm_scale=sm_scale,
                               block_size=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, C, 1, Dh),
                         lambda b, h, j, tables, lp: (b, 0, h, 0)),
            pl.BlockSpec((1, bs, 1, Dh),
                         lambda b, h, j, tables, lp: (tables[b, j],
                                                      0, h, 0)),
            pl.BlockSpec((1, bs, 1, Dh),
                         lambda b, h, j, tables, lp: (tables[b, j],
                                                      0, h, 0)),
            pl.BlockSpec((1, C, 1),
                         lambda b, h, j, tables, lp: (b, 0, 0)),
            pl.BlockSpec((C, C),
                         lambda b, h, j, tables, lp: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, 1, Dh),
                               lambda b, h, j, tables, lp: (b, 0, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, 128), jnp.float32),
            pltpu.VMEM((C, 128), jnp.float32),
            pltpu.VMEM((C, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, C, H, Dh), jnp.float32),
        interpret=interpret,
    )(tables, last_pos, q, k_pages, v_pages, pos3, anc_f)


def paged_attention_tree_reference(k_pages, v_pages, q, block_tables,
                                   positions, anc, sm_scale=None):
    """The unfused lax fallback: contiguous gather through the block
    table, tree-masked softmax — element-for-element the serving model's
    XLA tree-window attention branch."""
    B, C, H, Dh = q.shape
    bs = k_pages.shape[1]
    max_ctx = block_tables.shape[1] * bs
    if sm_scale is None:
        sm_scale = Dh ** -0.5
    k_ctx = k_pages[block_tables].reshape(B, max_ctx, H, Dh)
    v_ctx = v_pages[block_tables].reshape(B, max_ctx, H, Dh)
    scores = jnp.einsum("bchd,bthd->bcht", q, k_ctx) * sm_scale
    anc_b = jnp.asarray(anc) > 0
    pos0 = positions[:, 0]                               # [B]
    t_ids = jnp.arange(max_ctx)[None, None, :]           # [1, 1, T]
    rel = t_ids - pos0[:, None, None]                    # [B, 1, T]
    in_win = (rel >= 0) & (rel < C)
    rel_c = jnp.clip(rel, 0, C - 1)
    anc_t = anc_b[jnp.arange(C)[None, :, None], rel_c]   # [B, C, T]
    valid = (rel < 0) | (in_win & anc_t)
    scores = jnp.where(valid[:, :, None, :], scores, -jnp.inf)
    w = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    w = w / jnp.sum(w, axis=-1, keepdims=True)
    return jnp.einsum("bcht,bthd->bchd", w, v_ctx)


# ---------------------------------------------------------------------------
# fused int8 matmul: in-kernel activation quantize, int8×int8→int32 MXU
# dot, per-output-channel dequantize on the last K block
# ---------------------------------------------------------------------------


def _int8_mm_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, act_scale):
    kk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # the quantize op's exact grid: round-half-even, clip, int8 (zero
    # padding quantizes to zero and contributes nothing to the dot)
    qa = jnp.clip(jnp.round(x_ref[:] * act_scale), -128, 127) \
        .astype(jnp.int8)
    acc_scr[:] += jax.lax.dot_general(
        qa, w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(kk == nk - 1)
    def _finish():
        o_ref[:] = acc_scr[:].astype(jnp.float32) * s_ref[:]


def int8_matmul(x, w_int8, dq_scale, act_scale, block_m=32, block_k=128,
                block_n=128):
    """Fused full-int8 matmul: ``dequant(quant(x) @ w_int8)`` in one
    kernel. x: ``[M, K]`` fp32 activation; w_int8: ``[K, N]`` int8
    weight; dq_scale: ``[N]`` fp32 combined per-output-channel
    dequantize scale (``(w_scales/127) * (s_act/127)``); act_scale: the
    activation quantize scale (``127/s_act``). Returns ``[M, N]`` fp32.

    int32 accumulation is exact over any K split, so the result matches
    the unfused quantize→dot→dequantize_linear path bitwise up to the
    final fp32 scale multiply (docs/KERNELS.md numerics policy)."""
    M, K = x.shape
    N = w_int8.shape[1]
    interpret = jax.default_backend() != "tpu"

    xp = _pad_to(_pad_to(x, 0, block_m), 1, block_k)
    wp = _pad_to(_pad_to(w_int8, 0, block_k), 1, block_n)
    sp = _pad_to(jnp.asarray(dq_scale, jnp.float32).reshape(1, N), 1,
                 block_n)
    Mp, Kp = xp.shape
    Np = wp.shape[1]
    grid = (Mp // block_m, Np // block_n, Kp // block_k)

    if pltpu is not None:
        scratch = [pltpu.VMEM((block_m, block_n), jnp.int32)]
    else:  # pragma: no cover - CPU-only install without the tpu module
        scratch = [jax.ShapeDtypeStruct((block_m, block_n), jnp.int32)]

    out = pl.pallas_call(
        functools.partial(_int8_mm_kernel, act_scale=act_scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(xp, wp, sp)
    return out[:M, :N]


def int8_matmul_reference(x, w_int8, dq_scale, act_scale):
    """The unfused lax fallback — bitwise the quantize →
    int8-dot(int32) → dequantize_linear op chain the quant_rewrite pass
    emits when the fused kernel is off."""
    qa = jnp.clip(jnp.round(x * act_scale), -128, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(qa, w_int8, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * jnp.asarray(dq_scale, jnp.float32)


# ---------------------------------------------------------------------------
# registry entries (ops/kernel_registry — docs/KERNELS.md qualification
# table; importing this module is what populates the registry)
# ---------------------------------------------------------------------------


def _on_tpu():
    return jax.default_backend() == "tpu"


def _flash_qualify(T=None, Tk=None, head_dim=None, causal=False):
    """The compat_ops.py gate, promoted and FIXED: the historical check
    required q.shape == k.shape, silently dropping the tuned path for
    every cross-attention-shaped call — non-causal cross attention
    (Tq != Tk) tiles fine (the kernel masks by kv length). Causal still
    requires Tq == Tk: the blocked diagonal assumes aligned starts."""
    Tk = T if Tk is None else Tk
    if T is None or T % 128 or Tk % 128:
        return False, "seq len not a multiple of 128"
    if head_dim is None or head_dim < 64:
        return False, "head_dim < 64"
    if causal and Tk != T:
        return False, "causal cross-attention (Tq != Tk)"
    return True, None


def _paged_qualify(head_dim=None, block_size=None, window=None):
    if pltpu is None:
        return False, "pallas TPU support (scalar prefetch) unavailable"
    return True, None


def _int8_qualify(x=None, w=None, *args, **kwargs):
    xs = getattr(x, "shape", None)
    ws = getattr(w, "shape", None)
    if xs is None or ws is None or len(xs) != 2 or len(ws) != 2:
        return False, "operands are not 2-D"
    return True, None


def _register_all():
    from .kernel_registry import register_kernel

    register_kernel(
        "flash_attention", flash_attention, attention_reference,
        qualify=_flash_qualify, default_on=None,
        doc="blocked online-softmax attention ([B,H,T,D]); default: on "
            "everywhere (interpret off-TPU, its historical dispatch)")
    register_kernel(
        "paged_decode", paged_attention, paged_attention_reference,
        qualify=_paged_qualify, default_on=_on_tpu,
        doc="one-token decode attention reading KVBlockPool pages "
            "through the block table in-kernel; default: TPU only")
    register_kernel(
        "spec_window", paged_attention, paged_attention_reference,
        qualify=_paged_qualify, default_on=_on_tpu,
        doc="speculative verify-window (k+1 query positions) over the "
            "paged cache in one kernel; default: TPU only")
    register_kernel(
        "spec_window_tree", paged_attention_tree,
        paged_attention_tree_reference,
        qualify=_paged_qualify, default_on=_on_tpu,
        doc="tree-mask verify window (width x depth token tree, one "
            "kernel) over the paged cache — in-window visibility by "
            "ancestor matrix via one-hot matmul; default: TPU only")
    register_kernel(
        "int8_matmul", int8_matmul, int8_matmul_reference,
        qualify=_int8_qualify, default_on=_on_tpu,
        doc="fused quantize + int8 dot (int32 acc) + per-channel "
            "dequantize for full-int8 programs; default: TPU only")


_register_all()
