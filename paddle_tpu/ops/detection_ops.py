"""Detection ops (parity: operators/detection/, 56 files — prior_box,
multiclass_nms, yolo_box, yolov3_loss, box_coder, iou_similarity,
bipartite_match, target_assign, box_clip, anchor_generator,
density_prior_box, detection_map ...).

Static-shape doctrine: ops that emit variable-length results in the
reference (NMS, detection_map matches) emit fixed-capacity tensors padded
with -1 labels / zero scores plus masks — the XLA-compilable equivalent of
LoD outputs.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _iou_matrix(a, b):
    """a [N,4] b [M,4] xyxy -> [N, M] IoU."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register("iou_similarity", differentiable=False)
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [_iou_matrix(x, y)]}


@register("prior_box", differentiable=False)
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes over the feature map grid (detection/prior_box_op)."""
    feat = ins["Input"][0]  # [N, C, H, W]
    image = ins["Image"][0]  # [N, C, IH, IW]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ars_in = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    sw = step_w or IW / W
    sh = step_h or IH / H
    ars = [1.0]
    for ar in ars_in:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        if max_sizes:
            Ms = max_sizes[ms_i]
            s = np.sqrt(ms * Ms) / 2.0
            boxes.append((s, s))
    nb = len(boxes)
    cx = (np.arange(W) + offset) * sw
    cy = (np.arange(H) + offset) * sh
    gx, gy = np.meshgrid(cx, cy)  # [H, W]
    out = np.zeros((H, W, nb, 4), np.float32)
    for i, (bw, bh) in enumerate(boxes):
        out[:, :, i, 0] = (gx - bw) / IW
        out[:, :, i, 1] = (gy - bh) / IH
        out[:, :, i, 2] = (gx + bw) / IW
        out[:, :, i, 3] = (gy + bh) / IH
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          out.shape).copy()
    return {"Boxes": [jnp.asarray(out)], "Variances": [jnp.asarray(var)]}


@register("density_prior_box", differentiable=False)
def _density_prior_box(ctx, ins, attrs):
    feat = ins["Input"][0]
    image = ins["Image"][0]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    densities = attrs.get("densities", [1])
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    sw = step_w or IW / W
    sh = step_h or IH / H
    all_boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = 1.0 / density
            for di in range(density):
                for dj in range(density):
                    cx_off = (dj + 0.5) * step - 0.5
                    cy_off = (di + 0.5) * step - 0.5
                    all_boxes.append((cx_off, cy_off, bw, bh))
    nb = len(all_boxes)
    cx = (np.arange(W) + offset) * sw
    cy = (np.arange(H) + offset) * sh
    gx, gy = np.meshgrid(cx, cy)
    out = np.zeros((H, W, nb, 4), np.float32)
    for i, (cxo, cyo, bw, bh) in enumerate(all_boxes):
        ccx = gx + cxo * sw
        ccy = gy + cyo * sh
        out[:, :, i, 0] = (ccx - bw / 2) / IW
        out[:, :, i, 1] = (ccy - bh / 2) / IH
        out[:, :, i, 2] = (ccx + bw / 2) / IW
        out[:, :, i, 3] = (ccy + bh / 2) / IH
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32), out.shape).copy()
    return {"Boxes": [jnp.asarray(out)], "Variances": [jnp.asarray(var)]}


@register("anchor_generator", differentiable=False)
def _anchor_generator(ctx, ins, attrs):
    feat = ins["Input"][0]
    anchor_sizes = attrs["anchor_sizes"]
    aspect_ratios = attrs["aspect_ratios"]
    stride = attrs["stride"]
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    base = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            w = s * np.sqrt(ar)
            h = s / np.sqrt(ar)
            base.append((w, h))
    nb = len(base)
    cx = (np.arange(W) + offset) * stride[0]
    cy = (np.arange(H) + offset) * stride[1]
    gx, gy = np.meshgrid(cx, cy)
    out = np.zeros((H, W, nb, 4), np.float32)
    for i, (w, h) in enumerate(base):
        out[:, :, i, 0] = gx - w / 2
        out[:, :, i, 1] = gy - h / 2
        out[:, :, i, 2] = gx + w / 2
        out[:, :, i, 3] = gy + h / 2
    var = np.broadcast_to(np.asarray(variances, np.float32), out.shape).copy()
    return {"Anchors": [jnp.asarray(out)], "Variances": [jnp.asarray(var)]}


@register("box_coder", differentiable=False)
def _box_coder(ctx, ins, attrs):
    prior = ins["PriorBox"][0]  # [M, 4]
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    pv = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    add = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + add
    ph = prior[:, 3] - prior[:, 1] + add
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pv is None:
        pv = jnp.ones((4,), jnp.float32)
        pvx, pvy, pvw, pvh = pv[0], pv[1], pv[2], pv[3]
    elif pv.ndim == 1:
        pvx, pvy, pvw, pvh = pv[0], pv[1], pv[2], pv[3]
    else:
        pvx, pvy, pvw, pvh = pv[:, 0], pv[:, 1], pv[:, 2], pv[:, 3]
    if code_type.lower() == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + add
        th = target[:, 3] - target[:, 1] + add
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        ox = (tcx[:, None] - pcx[None]) / pw[None] / pvx
        oy = (tcy[:, None] - pcy[None]) / ph[None] / pvy
        ow = jnp.log(jnp.maximum(tw[:, None] / pw[None], 1e-10)) / pvw
        oh = jnp.log(jnp.maximum(th[:, None] / ph[None], 1e-10)) / pvh
        out = jnp.stack([ox, oy, ow, oh], axis=-1)  # [N, M, 4]
    else:  # decode_center_size
        # target: [N, M, 4] deltas (or [N, 4] broadcast)
        t = target if target.ndim == 3 else target[:, None, :]
        dcx = pvx * t[..., 0] * pw + pcx
        dcy = pvy * t[..., 1] * ph + pcy
        dw = jnp.exp(jnp.minimum(pvw * t[..., 2], 20.0)) * pw
        dh = jnp.exp(jnp.minimum(pvh * t[..., 3], 20.0)) * ph
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - add, dcy + dh / 2 - add], axis=-1)
    return {"OutputBox": [out]}


@register("box_clip", differentiable=False)
def _box_clip(ctx, ins, attrs):
    x = ins["Input"][0]
    im_info = ins["ImInfo"][0]  # [N, 3] (h, w, scale)
    h = im_info[:, 0] - 1
    w = im_info[:, 1] - 1
    while h.ndim < x.ndim - 1:
        h = h[:, None]
        w = w[:, None]
    out = jnp.stack([
        jnp.clip(x[..., 0], 0, w), jnp.clip(x[..., 1], 0, h),
        jnp.clip(x[..., 2], 0, w), jnp.clip(x[..., 3], 0, h)], axis=-1)
    return {"Output": [out]}


@register("bipartite_match", differentiable=False)
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (detection/bipartite_match_op.cc):
    DistMat [M, N] (gt x prior)."""
    dist = ins["DistMat"][0]
    M, N = dist.shape

    def body(carry, _):
        d, match_idx, match_dist = carry
        flat = jnp.argmax(d)
        i, j = flat // N, flat % N
        best = d[i, j]
        do = best > -1e9
        match_idx = jnp.where(do, match_idx.at[j].set(i), match_idx)
        match_dist = jnp.where(do, match_dist.at[j].set(best), match_dist)
        d = jnp.where(do, d.at[i, :].set(-1e10).at[:, j].set(-1e10), d)
        return (d, match_idx, match_dist), None

    init = (dist, -jnp.ones((N,), jnp.int32), jnp.zeros((N,), jnp.float32))
    (_, match_idx, match_dist), _ = jax.lax.scan(
        body, init, None, length=min(M, N))
    mtype = attrs.get("match_type", "bipartite")
    if mtype == "per_prediction":
        thr = attrs.get("dist_threshold", 0.5)
        col_best = jnp.argmax(dist, axis=0)
        col_val = jnp.max(dist, axis=0)
        extra = (match_idx < 0) & (col_val >= thr)
        match_idx = jnp.where(extra, col_best.astype(jnp.int32), match_idx)
        match_dist = jnp.where(extra, col_val, match_dist)
    return {"ColToRowMatchIndices": [match_idx[None]],
            "ColToRowMatchDist": [match_dist[None]]}


@register("multiclass_nms", differentiable=False)
def _multiclass_nms(ctx, ins, attrs):
    """Per-class NMS with fixed-capacity output [keep_top_k, 6]
    (label, score, x1, y1, x2, y2), padded with label=-1."""
    boxes = ins["BBoxes"][0]    # [N, M, 4]
    scores = ins["Scores"][0]   # [N, C, M]
    bg = attrs.get("background_label", 0)
    score_thr = attrs.get("score_threshold", 0.0)
    nms_thr = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 100)
    N, C, M = scores.shape

    def one_image(b, s):
        # b [M,4], s [C,M]
        results = []
        k = min(nms_top_k, M)
        for c in range(C):
            if c == bg:
                continue
            sc = s[c]
            vals, idx = jax.lax.top_k(sc, k)
            bb = b[idx]
            keep = _nms_mask(bb, vals, nms_thr) & (vals > score_thr)
            lab = jnp.full((k,), c, jnp.float32)
            results.append(jnp.concatenate(
                [lab[:, None], jnp.where(keep, vals, -1.0)[:, None], bb],
                axis=1))
        allr = jnp.concatenate(results, axis=0)  # [(C-1)*k, 6]
        order = jnp.argsort(-allr[:, 1])
        allr = allr[order][:keep_top_k]
        valid = allr[:, 1] > score_thr
        out = jnp.where(valid[:, None],
                        allr,
                        jnp.asarray([-1., 0., 0., 0., 0., 0.]))
        # pad to keep_top_k
        pad = keep_top_k - out.shape[0]
        if pad > 0:
            out = jnp.concatenate(
                [out, jnp.tile(jnp.asarray([[-1., 0., 0., 0., 0., 0.]]),
                               (pad, 1))], axis=0)
        return out

    outs = jax.vmap(one_image)(boxes, scores)  # [N, keep_top_k, 6]
    return {"Out": [outs]}


def _nms_mask(boxes, scores, thr):
    """boxes sorted by score desc; True = kept."""
    n = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes)

    def body(keep, i):
        sup = (iou[i] > thr) & keep[i] & (jnp.arange(n) > i)
        return keep & ~sup, None

    keep0 = jnp.ones((n,), jnp.bool_)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
    return keep


@register("yolo_box", differentiable=False)
def _yolo_box(ctx, ins, attrs):
    x = ins["X"][0]  # [N, A*(5+C), H, W]
    img_size = ins["ImgSize"][0]  # [N, 2]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    N, _, H, W = x.shape
    A = len(anchors) // 2
    x = x.reshape(N, A, 5 + class_num, H, W)
    gx, gy = jnp.meshgrid(jnp.arange(W), jnp.arange(H))
    bx = (jax.nn.sigmoid(x[:, :, 0]) + gx[None, None]) / W
    by = (jax.nn.sigmoid(x[:, :, 1]) + gy[None, None]) / H
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    input_size = downsample * max(H, W)
    bw = jnp.exp(x[:, :, 2]) * aw / (W * downsample)
    bh = jnp.exp(x[:, :, 3]) * ah / (H * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    mask = (conf.reshape(N, -1) > conf_thresh)[..., None]
    scores = jnp.where(mask, scores, 0.0)
    return {"Boxes": [boxes], "Scores": [scores]}


@register("yolov3_loss", nondiff_inputs=("GTBox", "GTLabel"))
def _yolov3_loss(ctx, ins, attrs):
    """Simplified dense yolov3 loss: objectness + box + class terms on the
    best-matching anchor per gt (detection/yolov3_loss_op.cc semantics on
    padded gt arrays)."""
    x = ins["X"][0]  # [N, A*(5+C), H, W]
    gt_box = ins["GTBox"][0]  # [N, G, 4] (cx, cy, w, h) normalized
    gt_label = ins["GTLabel"][0]  # [N, G]
    anchors = attrs["anchors"]
    anchor_mask = attrs.get("anchor_mask", list(range(len(anchors) // 2)))
    class_num = attrs["class_num"]
    ignore_thresh = attrs.get("ignore_thresh", 0.7)
    downsample = attrs.get("downsample_ratio", 32)
    N, _, H, W = x.shape
    A = len(anchor_mask)
    x = x.reshape(N, A, 5 + class_num, H, W)
    tx, ty, tw, th = x[:, :, 0], x[:, :, 1], x[:, :, 2], x[:, :, 3]
    obj = x[:, :, 4]
    cls = x[:, :, 5:]

    # build dense targets from padded gt (gt with w<=0 are padding)
    gw = gt_box[..., 2]
    valid = gw > 1e-6  # [N, G]
    gi = jnp.clip((gt_box[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gt_box[..., 1] * H).astype(jnp.int32), 0, H - 1)
    # best anchor per gt by wh IoU
    aw = jnp.asarray([anchors[2 * i] for i in anchor_mask],
                     jnp.float32) / (W * downsample)
    ah = jnp.asarray([anchors[2 * i + 1] for i in anchor_mask],
                     jnp.float32) / (H * downsample)
    inter = jnp.minimum(gt_box[..., 2:3], aw) * jnp.minimum(
        gt_box[..., 3:4], ah)
    union = (gt_box[..., 2:3] * gt_box[..., 3:4] + aw * ah - inter)
    wh_iou = inter / jnp.maximum(union, 1e-10)  # [N, G, A]
    best_a = jnp.argmax(wh_iou, axis=-1)  # [N, G]

    obj_target = jnp.zeros((N, A, H, W))
    bidx = jnp.arange(N)[:, None].repeat(gt_box.shape[1], 1)
    obj_target = obj_target.at[bidx, best_a, gj, gi].max(
        valid.astype(jnp.float32))
    obj_loss = jnp.mean(
        jnp.maximum(obj, 0) - obj * obj_target
        + jnp.log1p(jnp.exp(-jnp.abs(obj))))
    # box loss on assigned cells
    px = jax.nn.sigmoid(tx[bidx, best_a, gj, gi])
    py = jax.nn.sigmoid(ty[bidx, best_a, gj, gi])
    tgt_x = gt_box[..., 0] * W - gi
    tgt_y = gt_box[..., 1] * H - gj
    box_loss = jnp.sum(valid * ((px - tgt_x) ** 2 + (py - tgt_y) ** 2)) / N
    # class loss
    logits = cls[bidx, best_a, :, gj, gi]  # [N, G, C]
    onehot = jax.nn.one_hot(gt_label, class_num)
    cls_bce = jnp.maximum(logits, 0) - logits * onehot + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    cls_loss = jnp.sum(valid[..., None] * cls_bce) / N
    loss = obj_loss + box_loss + cls_loss
    return {"Loss": [jnp.full((N,), loss / N)]}


@register("target_assign", differentiable=False)
def _target_assign(ctx, ins, attrs):
    x = ins["X"][0]          # [M, K] (e.g. gt labels per row)
    match = ins["MatchIndices"][0]  # [N, P]
    mismatch_value = attrs.get("mismatch_value", 0)
    N, P = match.shape
    xx = x if x.ndim == 2 else x.reshape(x.shape[0], -1)
    safe = jnp.maximum(match, 0)
    out = xx[safe]  # [N, P, K]
    neg = (match < 0)[..., None]
    out = jnp.where(neg, mismatch_value, out)
    wt = jnp.where(match < 0, 0.0, 1.0)
    return {"Out": [out], "OutWeight": [wt[..., None]]}


@register("polygon_box_transform", differentiable=False)
def _polygon_box_transform(ctx, ins, attrs):
    x = ins["Input"][0]  # [N, geo, H, W]
    n, g, h, w = x.shape
    gx = jnp.tile(jnp.arange(w), (h, 1)) * 4.0
    gy = jnp.tile(jnp.arange(h)[:, None], (1, w)) * 4.0
    out = x.at[:, 0::2].set(gx[None, None] - x[:, 0::2])
    out = out.at[:, 1::2].set(gy[None, None] - x[:, 1::2])
    return {"Output": [out]}


@register("detection_map", differentiable=False)
def _detection_map(ctx, ins, attrs):
    """mAP over fixed-capacity detections (detection/detection_map_op.cc).
    DetectRes [N, K, 6] (label, score, box), GTLabel [N, G], GTBox [N,G,4]."""
    det = ins["DetectRes"][0]
    gt_label = ins["Label"][0]
    gt_box = ins["GTBox"][0]
    overlap = attrs.get("overlap_threshold", 0.5)
    class_num = attrs["class_num"]
    N, K, _ = det.shape
    G = gt_label.shape[1]

    def per_image(d, gl, gb):
        # count matches per class
        dl = d[:, 0].astype(jnp.int32)
        ds = d[:, 1]
        dbox = d[:, 2:6]
        valid_d = dl >= 0
        valid_g = gl >= 0
        iou = _iou_matrix(dbox, gb)  # [K, G]
        same = dl[:, None] == gl[None, :]
        ok = (iou > overlap) & same & valid_d[:, None] & valid_g[None, :]
        tp = jnp.any(ok, axis=1) & valid_d
        return tp, ds, dl, valid_d, valid_g, gl

    tp, ds, dl, vd, vg, gl = jax.vmap(per_image)(det, gt_label, gt_box)
    # flatten and compute AP (area under PR, integral style) per class, mean
    tp = tp.reshape(-1)
    ds = ds.reshape(-1)
    dl = dl.reshape(-1)
    vd = vd.reshape(-1)
    order = jnp.argsort(-jnp.where(vd, ds, -jnp.inf))
    tp_sorted = tp[order]
    vd_sorted = vd[order]
    dl_sorted = dl[order]
    aps = []
    for c in range(class_num):
        in_c = (dl_sorted == c) & vd_sorted
        npos = jnp.sum((gl.reshape(-1) == c)
                       & vg.reshape(-1)).astype(jnp.float32)
        ctp = jnp.cumsum(jnp.where(in_c, tp_sorted, 0))
        cfp = jnp.cumsum(jnp.where(in_c, ~tp_sorted & in_c, 0))
        recall = ctp / jnp.maximum(npos, 1)
        precision = ctp / jnp.maximum(ctp + cfp, 1)
        d_rec = jnp.diff(recall, prepend=0.0)
        ap = jnp.sum(precision * d_rec * jnp.where(in_c, 1.0, 0.0))
        aps.append(jnp.where(npos > 0, ap, -1.0))
    aps = jnp.stack(aps)
    have = aps >= 0
    mAP = jnp.sum(jnp.where(have, aps, 0)) / jnp.maximum(
        jnp.sum(have), 1)
    return {"MAP": [mAP.reshape((1,))],
            "AccumPosCount": [jnp.zeros((1,), jnp.int32)],
            "AccumTruePos": [jnp.zeros((1, 2), jnp.float32)],
            "AccumFalsePos": [jnp.zeros((1, 2), jnp.float32)]}


@register("generate_proposals", differentiable=False)
def _generate_proposals(ctx, ins, attrs):
    """RPN proposal generation with fixed post_nms_topN output."""
    scores = ins["Scores"][0]       # [N, A, H, W]
    deltas = ins["BboxDeltas"][0]   # [N, A*4, H, W]
    im_info = ins["ImInfo"][0]      # [N, 3]
    anchors = ins["Anchors"][0]     # [H, W, A, 4]
    variances = ins["Variances"][0]
    pre_n = attrs.get("pre_nms_topN", 6000)
    post_n = attrs.get("post_nms_topN", 1000)
    nms_thr = attrs.get("nms_thresh", 0.7)
    N = scores.shape[0]
    A = scores.shape[1]
    H, W = scores.shape[2], scores.shape[3]
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4)

    def per_image(sc, dl, ii):
        sc = sc.transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        dl = dl.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_n, sc.shape[0])
        vals, idx = jax.lax.top_k(sc, k)
        a = anc[idx]
        v = var[idx]
        d = dl[idx]
        aw = a[:, 2] - a[:, 0] + 1
        ah = a[:, 3] - a[:, 1] + 1
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, ii[1] - 1),
            jnp.clip(boxes[:, 1], 0, ii[0] - 1),
            jnp.clip(boxes[:, 2], 0, ii[1] - 1),
            jnp.clip(boxes[:, 3], 0, ii[0] - 1)], axis=1)
        keep = _nms_mask(boxes, vals, nms_thr)
        score_keep = jnp.where(keep, vals, -jnp.inf)
        vals2, idx2 = jax.lax.top_k(score_keep, post_n)
        return boxes[idx2], vals2

    rois, rscores = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [rscores]}


@register("roi_align")
def _roi_align(ctx, ins, attrs):
    x = ins["X"][0]          # [N, C, H, W]
    rois = ins["ROIs"][0]    # [R, 4] (x1,y1,x2,y2), batch idx via RoisLod/BatchId
    pooled_h = attrs.get("pooled_height", 1)
    pooled_w = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    sampling = attrs.get("sampling_ratio", -1)
    batch_ids = (ins["BatchId"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    N, C, H, W = x.shape

    def one_roi(roi, bid):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pooled_w
        bin_h = rh / pooled_h
        s = sampling if sampling > 0 else 2
        py = jnp.arange(pooled_h)
        px = jnp.arange(pooled_w)
        sy = jnp.arange(s)
        sx = jnp.arange(s)
        yy = y1 + (py[:, None] + (sy[None, :] + 0.5) / s) * bin_h  # [ph, s]
        xx = x1 + (px[:, None] + (sx[None, :] + 0.5) / s) * bin_w  # [pw, s]
        yy = yy.reshape(-1)
        xx = xx.reshape(-1)
        y0 = jnp.clip(jnp.floor(yy), 0, H - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xx), 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy - y0, 0, 1)   # [ph*s]
        wx = jnp.clip(xx - x0, 0, 1)   # [pw*s]
        img = x[bid]  # [C, H, W]

        # full sample grid = OUTER product of the y samples and x samples:
        # gather rows then columns -> [C, ph*s, pw*s] per corner
        def grid(yi, xi):
            return img[:, yi, :][:, :, xi]

        wy_ = wy[None, :, None]
        wx_ = wx[None, None, :]
        val = (grid(y0, x0) * (1 - wy_) * (1 - wx_)
               + grid(y0, x1i) * (1 - wy_) * wx_
               + grid(y1i, x0) * wy_ * (1 - wx_)
               + grid(y1i, x1i) * wy_ * wx_)  # [C, ph*s, pw*s]
        val = val.reshape(C, pooled_h, s, pooled_w, s).mean(axis=(2, 4))
        return val

    out = jax.vmap(one_roi)(rois, batch_ids)  # [R, C, ph, pw]
    return {"Out": [out]}


@register("roi_pool", nondiff_inputs=("ROIs", "BatchId"))
def _roi_pool(ctx, ins, attrs):
    """Differentiable like the reference's roi_pool (CPU/CUDA grad kernels
    scatter through the argmax): the gather+max formulation below gets its
    max-pool subgradient from jax; ROIs take no gradient (reference
    parity)."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    pooled_h = attrs.get("pooled_height", 1)
    pooled_w = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    batch_ids = (ins["BatchId"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    N, C, H, W = x.shape

    def one_roi(roi, bid):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = x[bid]
        # sample a fixed grid then max over it
        gy = y1 + (jnp.arange(pooled_h * 2) * rh) // (pooled_h * 2)
        gx = x1 + (jnp.arange(pooled_w * 2) * rw) // (pooled_w * 2)
        gy = jnp.clip(gy, 0, H - 1)
        gx = jnp.clip(gx, 0, W - 1)
        patch = img[:, gy][:, :, gx]  # [C, 2ph, 2pw]
        return patch.reshape(C, pooled_h, 2, pooled_w, 2).max(axis=(2, 4))

    out = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, jnp.int32)]}


# ---------------------------------------------------------------------------
# RCNN training target assignment + FPN routing (operators/detection/
# rpn_target_assign_op.cc, generate_proposal_labels_op.cc,
# generate_mask_labels_op.cc, collect_fpn_proposals_op.cc,
# distribute_fpn_proposals_op.cc, box_decoder_and_assign_op.cc,
# psroi_pool_op.cc, roi_perspective_transform_op.cc).
#
# TPU-native contract: the reference emits dynamically-sized sampled index
# lists (LoD); here every output is fixed-size — sampling pads to the
# configured quota and companion weight outputs zero out the padding, so
# XLA sees static shapes.
# ---------------------------------------------------------------------------


def _topk_mask_indices(key, mask, k):
    """Indices of up to k true entries of `mask` (random order), padded by
    repeating the first picked index. Returns (idx [k], valid [k])."""
    noise = jax.random.uniform(key, mask.shape)
    score = jnp.where(mask, 1.0 + noise, noise - 2.0)
    kk = min(k, mask.shape[0])
    _, idx = jax.lax.top_k(score, kk)
    valid = jnp.take(mask, idx)
    if kk < k:  # quota exceeds candidate count: pad (never valid)
        idx = jnp.concatenate([idx, jnp.zeros((k - kk,), idx.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((k - kk,), bool)])
    first = idx[0]
    idx = jnp.where(valid, idx, first)
    return idx.astype(jnp.int32), valid


@register("rpn_target_assign", differentiable=False, stateful=True)
def _rpn_target_assign(ctx, ins, attrs):
    anchors = ins["Anchor"][0].reshape((-1, 4))
    gt = ins["GtBoxes"][0].reshape((-1, 4))
    batch = attrs.get("rpn_batch_size_per_im", 256)
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)
    pos_thr = attrs.get("rpn_positive_overlap", 0.7)
    neg_thr = attrs.get("rpn_negative_overlap", 0.3)
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    fg_max = int(batch * fg_frac)
    A = anchors.shape[0]

    iou = _iou_matrix(anchors, gt)           # [A, G]
    # crowd gt regions are excluded from matching entirely (their columns
    # zeroed); anchors whose best box is crowd become plain background
    if ins.get("IsCrowd"):
        crowd = ins["IsCrowd"][0].reshape((-1,)).astype(bool)
        iou = jnp.where(crowd[None, :], 0.0, iou)
    best_gt = jnp.argmax(iou, axis=1)        # [A]
    best_iou = jnp.max(iou, axis=1)
    # anchors with best overlap per gt are fg regardless of threshold
    per_gt_best = jnp.max(iou, axis=0)       # [G]
    is_best_of_gt = jnp.any(
        (iou == per_gt_best[None, :]) & (per_gt_best[None, :] > 0), axis=1)
    inside_img = jnp.ones((A,), bool)
    if ins.get("ImInfo") and straddle >= 0:
        # discard anchors straddling the image border by > straddle pixels
        im = ins["ImInfo"][0].reshape((-1,))  # [h, w, scale]
        h, w = im[0], im[1]
        inside_img = ((anchors[:, 0] >= -straddle)
                      & (anchors[:, 1] >= -straddle)
                      & (anchors[:, 2] < w + straddle)
                      & (anchors[:, 3] < h + straddle))
    fg_mask = ((best_iou >= pos_thr) | is_best_of_gt) & inside_img
    bg_mask = (best_iou < neg_thr) & ~fg_mask & inside_img

    k1, k2 = jax.random.split(ctx.rng(attrs))
    fg_idx, fg_valid = _topk_mask_indices(k1, fg_mask, fg_max)
    bg_idx, bg_valid = _topk_mask_indices(k2, bg_mask, batch - fg_max)

    score_idx = jnp.concatenate([fg_idx, bg_idx])
    score_valid = jnp.concatenate([fg_valid, bg_valid])
    labels = jnp.concatenate([
        jnp.where(fg_valid, 1, -1), jnp.where(bg_valid, 0, -1)])

    matched = gt[best_gt[fg_idx]]            # [fg_max, 4]
    src = anchors[fg_idx]
    # encode regression targets the standard RCNN way
    sw, sh = src[:, 2] - src[:, 0], src[:, 3] - src[:, 1]
    sx, sy = src[:, 0] + sw * 0.5, src[:, 1] + sh * 0.5
    gw, gh = matched[:, 2] - matched[:, 0], matched[:, 3] - matched[:, 1]
    gx, gy = matched[:, 0] + gw * 0.5, matched[:, 1] + gh * 0.5
    tgt = jnp.stack([(gx - sx) / jnp.maximum(sw, 1e-6),
                     (gy - sy) / jnp.maximum(sh, 1e-6),
                     jnp.log(jnp.maximum(gw, 1e-6) / jnp.maximum(sw, 1e-6)),
                     jnp.log(jnp.maximum(gh, 1e-6) / jnp.maximum(sh, 1e-6))],
                    axis=1)
    inside_w = jnp.where(fg_valid[:, None], 1.0, 0.0) * jnp.ones((1, 4))
    return {"LocationIndex": [fg_idx],
            "ScoreIndex": [score_idx],
            "TargetLabel": [labels[:, None].astype(jnp.int32)],
            "TargetBBox": [tgt],
            "BBoxInsideWeight": [inside_w],
            "ScoreValid": [score_valid]}


@register("generate_proposal_labels", differentiable=False, stateful=True)
def _generate_proposal_labels(ctx, ins, attrs):
    rois = ins["RpnRois"][0].reshape((-1, 4))
    gt_boxes = ins["GtBoxes"][0].reshape((-1, 4))
    gt_classes = ins["GtClasses"][0].reshape((-1,)).astype(jnp.int32)
    batch = attrs.get("batch_size_per_im", 512)
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thr = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    reg_w = jnp.asarray(
        attrs.get("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    class_nums = attrs.get("class_nums", 81)
    fg_max = int(batch * fg_frac)

    # gt boxes join the candidate pool, as in the reference (crowd gt is
    # excluded from both the pool and the matching targets)
    cand = jnp.concatenate([rois, gt_boxes], axis=0)
    iou = _iou_matrix(cand, gt_boxes)
    if ins.get("IsCrowd"):
        crowd = ins["IsCrowd"][0].reshape((-1,)).astype(bool)
        iou = jnp.where(crowd[None, :], 0.0, iou)
        # the appended gt candidates that are crowd can never be selected
        n_rois = rois.shape[0]
        cand_is_crowd = jnp.concatenate(
            [jnp.zeros((n_rois,), bool), crowd])
    else:
        cand_is_crowd = jnp.zeros((cand.shape[0],), bool)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    fg_mask = (best_iou >= fg_thr) & ~cand_is_crowd
    bg_mask = (best_iou < bg_hi) & (best_iou >= bg_lo) & ~cand_is_crowd

    k1, k2 = jax.random.split(ctx.rng(attrs))
    fg_idx, fg_valid = _topk_mask_indices(k1, fg_mask, fg_max)
    bg_idx, bg_valid = _topk_mask_indices(k2, bg_mask, batch - fg_max)
    sel = jnp.concatenate([fg_idx, bg_idx])
    valid = jnp.concatenate([fg_valid, bg_valid])

    sel_rois = cand[sel]
    labels = jnp.where(
        jnp.concatenate([fg_valid, jnp.zeros_like(bg_valid)]),
        gt_classes[best_gt[sel]], 0)
    matched = gt_boxes[best_gt[sel]]
    sw, sh = (sel_rois[:, 2] - sel_rois[:, 0],
              sel_rois[:, 3] - sel_rois[:, 1])
    sx, sy = sel_rois[:, 0] + sw * 0.5, sel_rois[:, 1] + sh * 0.5
    gw, gh = matched[:, 2] - matched[:, 0], matched[:, 3] - matched[:, 1]
    gx, gy = matched[:, 0] + gw * 0.5, matched[:, 1] + gh * 0.5
    tgt = jnp.stack([(gx - sx) / jnp.maximum(sw, 1e-6),
                     (gy - sy) / jnp.maximum(sh, 1e-6),
                     jnp.log(jnp.maximum(gw, 1e-6) / jnp.maximum(sw, 1e-6)),
                     jnp.log(jnp.maximum(gh, 1e-6) / jnp.maximum(sh, 1e-6))],
                    axis=1) / reg_w[None, :]
    is_fg = jnp.concatenate([fg_valid, jnp.zeros_like(bg_valid)])
    w_in = jnp.where(is_fg[:, None], 1.0, 0.0) * jnp.ones((1, 4))
    # per-class target layout [P, 4*class_nums]: only the label's 4-slot
    # window holds the regression target (reference bbox_targets expansion)
    P = sel.shape[0]
    cls_idx = jnp.clip(labels, 0, class_nums - 1)
    onehot = jax.nn.one_hot(cls_idx, class_nums,
                            dtype=tgt.dtype)          # [P, C]
    tgt_pc = (onehot[:, :, None] * (tgt * w_in)[:, None, :]).reshape(
        (P, 4 * class_nums))
    w_in_pc = (onehot[:, :, None] * w_in[:, None, :]).reshape(
        (P, 4 * class_nums))
    w_out_pc = (onehot[:, :, None]
                * jnp.where(valid, 1.0, 0.0)[:, None, None]
                * jnp.ones((1, 1, 4))).reshape((P, 4 * class_nums))
    return {"Rois": [sel_rois],
            "LabelsInt32": [labels[:, None]],
            "BboxTargets": [tgt_pc],
            "BboxInsideWeights": [w_in_pc],
            "BboxOutsideWeights": [w_out_pc]}


@register("generate_mask_labels", differentiable=False)
def _generate_mask_labels(ctx, ins, attrs):
    """Mask targets from dense gt masks. TPU-native contract: GtSegms is a
    dense bitmap [G, Hm, Wm] per gt box (polygon rasterization happens in
    the host pipeline); each fg roi crops+resizes its matched gt mask to
    resolution^2 (generate_mask_labels_op.cc)."""
    rois = ins["Rois"][0].reshape((-1, 4))
    gt_masks = ins["GtSegms"][0]          # [G, Hm, Wm] {0,1}
    labels = ins["LabelsInt32"][0].reshape((-1,)).astype(jnp.int32)
    res = attrs.get("resolution", 14)
    G, Hm, Wm = gt_masks.shape
    if ins.get("GtBoxes"):
        gt_boxes = ins["GtBoxes"][0].reshape((-1, 4))
    else:
        # derive each gt's box from its mask extent
        ys = jnp.any(gt_masks > 0, axis=2)   # [G, Hm]
        xs = jnp.any(gt_masks > 0, axis=1)   # [G, Wm]
        yi = jnp.arange(Hm)[None, :]
        xi = jnp.arange(Wm)[None, :]
        y1 = jnp.min(jnp.where(ys, yi, Hm), axis=1).astype(jnp.float32)
        y2 = jnp.max(jnp.where(ys, yi + 1, 0), axis=1).astype(jnp.float32)
        x1 = jnp.min(jnp.where(xs, xi, Wm), axis=1).astype(jnp.float32)
        x2 = jnp.max(jnp.where(xs, xi + 1, 0), axis=1).astype(jnp.float32)
        gt_boxes = jnp.stack([x1, y1, x2, y2], axis=1)

    iou = _iou_matrix(rois, gt_boxes)
    best_gt = jnp.argmax(iou, axis=1)

    # masks live in image pixel space ([Hm, Wm] = image grid); crop the
    # matched gt mask over the roi rectangle and resize to res×res by
    # nearest sampling (the reference rasterizes polygons to the same grid)
    def one(roi, g):
        mask = gt_masks[g]
        t = (jnp.arange(res) + 0.5) / res
        ys = roi[1] + t * (roi[3] - roi[1])
        xs = roi[0] + t * (roi[2] - roi[0])
        patch = mask[jnp.clip(ys.astype(jnp.int32), 0, Hm - 1)][
            :, jnp.clip(xs.astype(jnp.int32), 0, Wm - 1)]
        return patch

    masks = jax.vmap(one)(rois, best_gt)
    masks = masks * (labels > 0)[:, None, None]
    return {"MaskRois": [rois], "RoiHasMaskInt32": [(labels > 0)[:, None]
                                                    .astype(jnp.int32)],
            "MaskInt32": [masks.astype(jnp.int32)]}


@register("collect_fpn_proposals", differentiable=False)
def _collect_fpn_proposals(ctx, ins, attrs):
    rois_list = ins["MultiLevelRois"]     # list of [Ni, 4]
    scores_list = ins["MultiLevelScores"]  # list of [Ni, 1]
    post_nms_topn = attrs.get("post_nms_topN", 100)
    rois = jnp.concatenate([r.reshape((-1, 4)) for r in rois_list], axis=0)
    scores = jnp.concatenate([s.reshape((-1,)) for s in scores_list], axis=0)
    k = min(post_nms_topn, rois.shape[0])
    top_s, top_i = jax.lax.top_k(scores, k)
    return {"FpnRois": [rois[top_i]], "RoisNum": [jnp.array([k], jnp.int32)]}


@register("distribute_fpn_proposals", differentiable=False)
def _distribute_fpn_proposals(ctx, ins, attrs):
    rois = ins["FpnRois"][0].reshape((-1, 4))
    min_level = attrs.get("min_level", 2)
    max_level = attrs.get("max_level", 5)
    refer_level = attrs.get("refer_level", 4)
    refer_scale = attrs.get("refer_scale", 224)
    N = rois.shape[0]
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)

    outs = []
    for L in range(min_level, max_level + 1):
        m = (lvl == L).astype(rois.dtype)[:, None]
        outs.append(rois * m)  # static shape: non-members zeroed
    # restore index for the zero-masked layout above: concat(MultiFpnRois)
    # keeps every roi at row (level - min_level) * N + original_position
    restore = ((lvl - min_level) * N
               + jnp.arange(N, dtype=jnp.int32)).astype(jnp.int32)
    return {"MultiFpnRois": outs, "RestoreIndex": [restore[:, None]],
            "LevelIndex": [lvl[:, None]]}


@register("box_decoder_and_assign", differentiable=False)
def _box_decoder_and_assign(ctx, ins, attrs):
    prior = ins["PriorBox"][0].reshape((-1, 4))       # [N, 4]
    prior_var = ins["PriorBoxVar"][0].reshape((-1, 4))
    deltas = ins["TargetBox"][0]                      # [N, C*4]
    scores = ins["BoxScore"][0]                       # [N, C]
    box_clip = attrs.get("box_clip", 4.135)
    N = prior.shape[0]
    C = scores.shape[1]
    d = deltas.reshape((N, C, 4))

    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    dx = d[..., 0] * prior_var[:, None, 0]
    dy = d[..., 1] * prior_var[:, None, 1]
    dw = jnp.clip(d[..., 2] * prior_var[:, None, 2], -box_clip, box_clip)
    dh = jnp.clip(d[..., 3] * prior_var[:, None, 3], -box_clip, box_clip)
    cx = px[:, None] + dx * pw[:, None]
    cy = py[:, None] + dy * ph[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                         cx + w * 0.5 - 1, cy + h * 0.5 - 1], axis=-1)
    best = jnp.argmax(scores, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].astype(jnp.int32)
        * jnp.ones((1, 1, 4), jnp.int32), axis=1)[:, 0]
    return {"DecodeBox": [decoded.reshape((N, C * 4))],
            "OutputAssignBox": [assigned]}


@register("psroi_pool")
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive RoI average pooling (psroi_pool_op.cc): channel
    group (i, j) feeds output bin (i, j)."""
    x = ins["X"][0]                      # [N, C*Ph*Pw, H, W]
    rois = ins["ROIs"][0].reshape((-1, 4))
    out_c = attrs.get("output_channels")
    Ph = attrs.get("pooled_height", 7)
    Pw = attrs.get("pooled_width", Ph)
    scale = attrs.get("spatial_scale", 1.0)
    batch_ids = (ins["BatchId"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    N, Ctot, H, W = x.shape
    S = 2  # sub-samples per bin edge

    def one(roi, bid):
        x1, y1 = roi[0] * scale, roi[1] * scale
        x2, y2 = roi[2] * scale, roi[3] * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        ty = (jnp.arange(Ph * S) + 0.5) / (Ph * S)
        tx = (jnp.arange(Pw * S) + 0.5) / (Pw * S)
        gy = jnp.clip((y1 + ty * rh).astype(jnp.int32), 0, H - 1)
        gx = jnp.clip((x1 + tx * rw).astype(jnp.int32), 0, W - 1)
        patch = x[bid][:, gy][:, :, gx]              # [C*Ph*Pw, PhS, PwS]
        pooled = patch.reshape(Ctot, Ph, S, Pw, S).mean(axis=(2, 4))
        pooled = pooled.reshape(out_c, Ph, Pw, Ph, Pw)
        # dims (c, group_i, group_j, bin_i, bin_j): bin (i,j) reads its own
        # channel group (i,j)
        ii = jnp.arange(Ph)[:, None]
        jj = jnp.arange(Pw)[None, :]
        return pooled[:, ii, jj, ii, jj]

    out = jax.vmap(one)(rois, batch_ids)
    return {"Out": [out]}


@register("roi_perspective_transform")
def _roi_perspective_transform(ctx, ins, attrs):
    """Warp quadrilateral rois ([x1..y4] 8 coords) to a fixed H×W patch by
    bilinear sampling along the quad's bilinear surface
    (roi_perspective_transform_op.cc)."""
    x = ins["X"][0]                       # [N, C, H, W]
    rois = ins["ROIs"][0].reshape((-1, 8))
    oh = attrs.get("transformed_height", 8)
    ow = attrs.get("transformed_width", 8)
    scale = attrs.get("spatial_scale", 1.0)
    batch_ids = (ins["BatchId"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    N, C, H, W = x.shape

    def one(roi, bid):
        # corners in clockwise order (x1,y1)=(top-left) ... (x4,y4)=bottom-left
        tl = roi[0:2] * scale
        tr = roi[2:4] * scale
        br = roi[4:6] * scale
        bl = roi[6:8] * scale
        u = (jnp.arange(ow) + 0.5) / ow
        v = (jnp.arange(oh) + 0.5) / oh
        top = tl[None, :] + u[:, None] * (tr - tl)[None, :]   # [ow, 2]
        bot = bl[None, :] + u[:, None] * (br - bl)[None, :]
        pts = top[None, :, :] + v[:, None, None] * (bot - top)[None, :, :]
        px, py = pts[..., 0], pts[..., 1]                     # [oh, ow]
        x0 = jnp.clip(jnp.floor(px).astype(jnp.int32), 0, W - 1)
        y0 = jnp.clip(jnp.floor(py).astype(jnp.int32), 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        fx = jnp.clip(px - x0, 0.0, 1.0)
        fy = jnp.clip(py - y0, 0.0, 1.0)
        img = x[bid]                                          # [C, H, W]
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1_]
        v10 = img[:, y1_, x0]
        v11 = img[:, y1_, x1_]
        return (v00 * (1 - fx) * (1 - fy) + v01 * fx * (1 - fy)
                + v10 * (1 - fx) * fy + v11 * fx * fy)

    out = jax.vmap(one)(rois, batch_ids)
    mask = jnp.ones((rois.shape[0], 1, oh, ow), jnp.int32)
    return {"Out": [out], "Mask": [mask],
            "TransformMatrix": [jnp.zeros((rois.shape[0], 9), x.dtype)]}


@register("ssd_loss", nondiff_inputs=("GTBox", "GTLabel", "PriorBox",
                                      "PriorBoxVar"))
def _ssd_loss(ctx, ins, attrs):
    """SSD multibox loss (ssd_loss in layers/detection.py of the
    reference): per-prediction matching of priors to ground truth,
    smooth-L1 on encoded location offsets of the positives, softmax CE on
    classes with hard-negative mining at neg_pos_ratio, normalized by the
    positive count. Padded gt rows carry label < 0.

    Loc [B, M, 4], Conf [B, M, C], GTBox [B, G, 4] (xyxy), GTLabel
    [B, G] or [B, G, 1], PriorBox [M, 4], PriorBoxVar [M, 4].
    Out: [B, M] per-prior weighted loss whose sum is the total loss.
    """
    loc = ins["Loc"][0].astype(jnp.float32)
    conf = ins["Conf"][0].astype(jnp.float32)
    gt_box = ins["GTBox"][0].astype(jnp.float32)
    gt_label = ins["GTLabel"][0].reshape(gt_box.shape[0], -1)
    prior = ins["PriorBox"][0].astype(jnp.float32)
    pvar = (ins["PriorBoxVar"][0].astype(jnp.float32)
            if ins.get("PriorBoxVar") else None)
    background = attrs.get("background_label", 0)
    overlap_threshold = attrs.get("overlap_threshold", 0.5)
    neg_overlap = attrs.get("neg_overlap", 0.5)
    neg_pos_ratio = attrs.get("neg_pos_ratio", 3.0)
    loc_w = attrs.get("loc_loss_weight", 1.0)
    conf_w = attrs.get("conf_loss_weight", 1.0)
    match_type = attrs.get("match_type", "per_prediction")
    normalize = attrs.get("normalize", True)

    B, M, _ = loc.shape
    valid_gt = gt_label >= 0                                    # [B, G]
    G = gt_box.shape[1]

    iou = jax.vmap(lambda g: _iou_matrix(g, prior))(gt_box)     # [B, G, M]
    iou = jnp.where(valid_gt[..., None], iou, -1.0)
    best_iou = iou.max(axis=1)                                  # [B, M]

    # Stage 1 — greedy bipartite matching (bipartite_match_op.cc): every
    # valid gt gets its argmax prior even below overlap_threshold, priors
    # consumed one per gt in global-max order.
    def match_one(d):                                           # [G, M]
        def body(carry, _):
            dd, midx = carry
            flat = jnp.argmax(dd)
            i, j = flat // M, flat % M
            do = dd[i, j] > 0.0  # skip invalid (-1) and zero-IoU gts
            midx = jnp.where(do, midx.at[j].set(i), midx)
            dd = jnp.where(do, dd.at[i, :].set(-1e10).at[:, j].set(-1e10),
                           dd)
            return (dd, midx), None

        init = (d, -jnp.ones((M,), jnp.int32))
        (_, midx), _ = jax.lax.scan(body, init, None, length=min(G, M))
        return midx

    bip_g = jax.vmap(match_one)(iou)                            # [B, M]
    pos = bip_g >= 0

    # Stage 2 — per-prediction augmentation: unmatched priors whose best
    # overlap clears the threshold also become positives.
    best_g = iou.argmax(axis=1)                                 # [B, M]
    if match_type == "per_prediction":
        pos = pos | (best_iou >= overlap_threshold)
    best_g = jnp.where(bip_g >= 0, bip_g, best_g)

    tgt_label = jnp.take_along_axis(
        jnp.where(valid_gt, gt_label, background), best_g, axis=1)
    tgt_label = jnp.where(pos, tgt_label, background).astype(jnp.int32)

    # SSD box encoding of the matched gt against each prior
    matched = jnp.take_along_axis(gt_box, best_g[..., None], axis=1)
    pw = jnp.maximum(prior[:, 2] - prior[:, 0], 1e-6)
    ph = jnp.maximum(prior[:, 3] - prior[:, 1], 1e-6)
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2
    gw = jnp.maximum(matched[..., 2] - matched[..., 0], 1e-6)
    gh = jnp.maximum(matched[..., 3] - matched[..., 1], 1e-6)
    gcx = (matched[..., 0] + matched[..., 2]) / 2
    gcy = (matched[..., 1] + matched[..., 3]) / 2
    enc = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                     jnp.log(gw / pw), jnp.log(gh / ph)], axis=-1)
    if pvar is not None:
        enc = enc / jnp.maximum(pvar, 1e-6)

    diff = loc - enc
    ad = jnp.abs(diff)
    smooth_l1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(-1)
    loc_loss = smooth_l1 * pos.astype(jnp.float32)              # [B, M]

    logp = jax.nn.log_softmax(conf, axis=-1)
    ce = -jnp.take_along_axis(logp, tgt_label[..., None],
                              axis=-1)[..., 0]                  # [B, M]

    # hard negative mining: per image keep the neg_pos_ratio * npos
    # highest-CE negatives among priors whose overlap is below neg_overlap
    # (mine_hard_examples max_negative semantics)
    is_neg = (~pos) & (best_iou < neg_overlap)
    npos = pos.sum(axis=1, keepdims=True)
    nneg = jnp.minimum((npos * neg_pos_ratio).astype(jnp.int32),
                       is_neg.sum(axis=1, keepdims=True))
    neg_ce = jnp.where(is_neg, ce, -jnp.inf)
    order = jnp.argsort(-neg_ce, axis=1)
    rank = jnp.argsort(order, axis=1)
    selected_neg = is_neg & (rank < nneg)

    conf_loss = ce * (pos | selected_neg).astype(jnp.float32)
    total = loc_w * loc_loss + conf_w * conf_loss               # [B, M]
    if normalize:
        total = total / jnp.maximum(npos.astype(jnp.float32), 1.0)
    return {"Out": [total]}
