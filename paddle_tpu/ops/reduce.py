"""Reductions (parity: operators/reduce_ops/ — reduce_{sum,mean,max,min,prod,
all,any}_op.cc; plus mean_op.cc and argmin/argmax/top_k).
"""

import jax
import jax.numpy as jnp

from .registry import register, simple_op


def _reduce(name, fn, differentiable=True):
    def impl(ctx, ins, attrs):
        x = ins["X"][0]
        dim = attrs.get("dim", [0])
        keep_dim = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False):
            axis = None
        else:
            axis = tuple(d % x.ndim for d in (dim if isinstance(dim, (list, tuple)) else [dim]))
        out = fn(x, axis=axis, keepdims=keep_dim)
        if axis is None and not keep_dim:
            out = out.reshape((1,))
        return {"Out": [out]}

    register(name, differentiable=differentiable)(impl)


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", jnp.all, differentiable=False)
_reduce("reduce_any", jnp.any, differentiable=False)


@simple_op("mean")
def _mean(ctx, x, **_):
    # Fluid mean_op: mean over ALL elements -> shape [1]
    return jnp.mean(x).reshape((1,))


@register("argmax", differentiable=False)
def _argmax(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmax(x, axis=axis).astype(jnp.int64)]}


@register("argmin", differentiable=False)
def _argmin(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    return {"Out": [jnp.argmin(x, axis=axis).astype(jnp.int64)]}


@register("argsort", differentiable=False)
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    descending = attrs.get("descending", False)
    key = -x if descending else x
    idx = jnp.argsort(key, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}


@register("top_k", differentiable=False)
def _top_k(ctx, ins, attrs):
    x = ins["X"][0]
    k = int(attrs["k"])
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register("isfinite", differentiable=False)
def _isfinite(ctx, ins, attrs):
    xs = ins["X"]
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x.astype(jnp.float32))))
    return {"Out": [ok.reshape((1,))]}


@register("has_inf", differentiable=False)
def _has_inf(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.any(jnp.isinf(x.astype(jnp.float32))).reshape((1,))]}


@register("has_nan", differentiable=False)
def _has_nan(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.any(jnp.isnan(x.astype(jnp.float32))).reshape((1,))]}
