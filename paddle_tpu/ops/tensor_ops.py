"""Tensor manipulation ops (parity: SURVEY Appendix A "Tensor manipulation"
group — reshape/concat/split/transpose/gather/scatter/one_hot/slice/pad/
expand/stack/squeeze/... from operators/*.cc).
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, simple_op, np_dtype


@register("reshape2")
def _reshape2(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # Fluid reshape semantics: 0 means copy input dim, -1 inferred
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    out = x.reshape(shape)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": [x.reshape(shape)]}


@register("squeeze2")
def _squeeze2(ctx, ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if axes:
        out = x
        for ax in sorted((a % x.ndim for a in axes), reverse=True):
            out = jnp.squeeze(out, axis=ax)
    else:
        out = jnp.squeeze(x)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register("squeeze")
def _squeeze(ctx, ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if axes:
        out = x
        for ax in sorted((a % x.ndim for a in axes), reverse=True):
            out = jnp.squeeze(out, axis=ax)
    else:
        out = jnp.squeeze(x)
    return {"Out": [out]}


@register("unsqueeze2")
def _unsqueeze2(ctx, ins, attrs):
    x = ins["X"][0]
    out = x
    for ax in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, axis=ax)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    out = x
    for ax in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, axis=ax)
    return {"Out": [out]}


@register("flatten2")
def _flatten2(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    out = x.reshape((lead, -1))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register("flatten")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape((lead, -1))]}


@register("transpose2")
def _transpose2(ctx, ins, attrs):
    x = ins["X"][0]
    out = jnp.transpose(x, attrs["axis"])
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


@register("concat")
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    outs = [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis=axis)]
    return {"Y": outs}


@register("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        d = x.shape[ax]
        st = max(st + d, 0) if st < 0 else min(st, d)
        en = max(en + d, 0) if en < 0 else min(en, d)
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


@register("strided_slice")
def _strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for ax, st, en, stride in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                                  attrs["strides"]):
        idx[ax] = slice(st, en, stride)
    return {"Out": [x[tuple(idx)]]}


@register("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]
    pad_width = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pad_width, constant_values=attrs.get("pad_value", 0.0))]}


@register("pad2d")
def _pad2d(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    pw = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if attrs.get("data_format", "NCHW") == "NHWC":
        pw = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        out = jnp.pad(x, pw, constant_values=attrs.get("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pw, mode="reflect")
    else:
        out = jnp.pad(x, pw, mode="edge")
    return {"Out": [out]}


@register("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pw = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pw, constant_values=attrs.get("pad_value", 0.0))]}


@register("gather", nondiff_inputs=("Index",))
def _gather(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [jnp.take(x, idx.reshape((-1,)), axis=0)]}


@register("gather_nd", nondiff_inputs=("Index",))
def _gather_nd(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register("scatter", nondiff_inputs=("Ids",))
def _scatter(ctx, ins, attrs):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.reshape((-1,))
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(upd)]}
    return {"Out": [x.at[ids].add(upd)]}


@register("one_hot", differentiable=False)
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    depth = int(attrs["depth"])
    flat = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    return {"Out": [jax.nn.one_hot(flat, depth, dtype=jnp.float32)]}


@register("multiplex", nondiff_inputs=("Ids",))
def _multiplex(ctx, ins, attrs):
    ids = ins["Ids"][0].reshape((-1,))
    stacked = jnp.stack(ins["X"], axis=0)  # [n, batch, ...]
    rows = jnp.arange(stacked.shape[1])
    return {"Out": [stacked[ids, rows]]}


@register("reverse")
def _reverse(ctx, ins, attrs):
    x = ins["X"][0]
    out = x
    for ax in attrs["axis"]:
        out = jnp.flip(out, axis=ax)
    return {"Out": [out]}


@register("where", differentiable=False)
def _where(ctx, ins, attrs):
    cond = ins["Condition"][0]
    return {"Out": [jnp.argwhere(cond).astype(jnp.int64)]}


@register("where_op_select")
def _where_select(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register("is_empty", differentiable=False)
def _is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.asarray(x.size == 0).reshape((1,))]}


@register("label_smooth")
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        return {"Out": [(1 - eps) * x + eps * prior]}
    return {"Out": [(1 - eps) * x + eps / x.shape[-1]]}


@register("shard_index", differentiable=False)
def _shard_index(ctx, ins, attrs):
    x = ins["X"][0]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return {"Out": [jnp.where(in_shard, x % shard_size, ignore_value)]}


@register("sampling_id", differentiable=False, stateful=True)
def _sampling_id(ctx, ins, attrs):
    x = ins["X"][0]
    key = ctx.rng(attrs)
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register("uniform_random_batch_size_like", differentiable=False, stateful=True)
def _uniform_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[attrs.get("input_dim_idx", 0)]
    key = ctx.rng(attrs)
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jax.random.uniform(key, shape, jnp.float32,
                                       attrs.get("min", -1.0),
                                       attrs.get("max", 1.0)).astype(dt)]}


@register("gaussian_random_batch_size_like", differentiable=False, stateful=True)
def _gaussian_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[attrs.get("input_dim_idx", 0)]
    key = ctx.rng(attrs)
    dt = np_dtype(attrs.get("dtype", "float32"))
    out = jax.random.normal(key, shape) * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": [out.astype(dt)]}
