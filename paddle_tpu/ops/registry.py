"""Op registry + kernel dispatch (parity: paddle/fluid/framework/op_registry.h
REGISTER_OPERATOR :197 and OperatorWithKernel dispatch operator.cc:881-1160).

TPU-native: an "op kernel" is a pure JAX-traceable function
    impl(ctx, ins, attrs) -> outs
where ins/outs are dict[slot -> list[jax.Array]] mirroring Fluid's named
input/output slots. There is no (place, dtype, layout) kernel key — XLA
compiles one kernel for whatever mesh/dtype the program is lowered with, and
gradients are derived from the SAME impl via per-op `jax.vjp` at lowering
time (see paddle_tpu/backward.py), replacing Fluid's hand-registered
GradOpDescMakers (grad_op_desc_maker.h).

`ctx` is a LoweringContext giving ops deterministic per-op PRNG keys (seeded
by program seed + op id + step counter), the training/eval switch, and mesh
info for collective ops.
"""

import jax
import jax.numpy as jnp
import numpy as np

_REGISTRY = {}


class OpDef:
    def __init__(
        self,
        name,
        impl,
        differentiable=True,
        nondiff_inputs=(),
        stateful=False,
        infer_meta=None,
    ):
        self.name = name
        self.impl = impl
        self.differentiable = differentiable
        # input slots that never receive gradients (e.g. integer id inputs)
        self.nondiff_inputs = frozenset(nondiff_inputs)
        # stateful ops use ctx.rng() or update persistable state
        self.stateful = stateful
        # optional static-analysis metadata (an analysis.meta.OpMeta):
        # required input/output slots + attrs and a shape/dtype
        # propagation rule — the InferShape/InferVarType parity surface
        # the Program verifier checks ops against (docs/STATIC_ANALYSIS.md)
        self.infer_meta = infer_meta

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(name, differentiable=True, nondiff_inputs=(), stateful=False,
             infer_meta=None):
    """Decorator: register `impl(ctx, ins, attrs) -> outs` for op `name`."""

    def deco(fn):
        if name in _REGISTRY:
            raise ValueError("op %r already registered" % name)
        _REGISTRY[name] = OpDef(
            name, fn, differentiable, nondiff_inputs, stateful, infer_meta
        )
        return fn

    return deco


def set_infer_meta(name, meta):
    """Attach (or replace) the static-analysis metadata of a registered
    op — how `paddle_tpu.analysis.meta` contributes entries for ops whose
    kernels predate the verifier."""
    get(name).infer_meta = meta
    return meta


def simple_op(name, in_slots=("X",), out_slot="Out", differentiable=True,
              nondiff_inputs=(), stateful=False):
    """Register an op whose slots each carry exactly one tensor:
    fn(ctx, *tensors, **attrs) -> single tensor bound to `out_slot`.
    Multi-output ops must use register() and return a slot dict."""

    def deco(fn):
        def impl(ctx, ins, attrs):
            args = []
            for s in in_slots:
                vs = ins.get(s, [])
                args.append(vs[0] if vs else None)
            out = fn(ctx, *args, **attrs)
            if isinstance(out, tuple):
                raise TypeError(
                    "simple_op %r returned a tuple; multi-output ops must "
                    "use register() and return a slot dict" % name)
            return {out_slot: [out]}

        register(name, differentiable, nondiff_inputs, stateful)(impl)
        return fn

    return deco


def elementwise_unary(name, fn, differentiable=True):
    """Register a unary elementwise op X -> Out (activation family,
    parity: operators/activation_op.cc REGISTER_ACTIVATION_OP)."""

    def impl(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0], attrs)]}

    register(name, differentiable=differentiable)(impl)


def get(name):
    od = _REGISTRY.get(name)
    if od is None:
        raise KeyError(
            "no TPU kernel registered for op %r (registered: %d ops)"
            % (name, len(_REGISTRY))
        )
    return od


def has(name):
    return name in _REGISTRY


def all_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# helpers shared by op impls
# ---------------------------------------------------------------------------


def x_of(ins, slot="X"):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


def np_dtype(name):
    if name == "bfloat16":
        return jnp.bfloat16
    return np.dtype(name)


def broadcast_to_axis(y, x_ndim, axis):
    """Fluid elementwise broadcasting: align y's dims to x starting at `axis`
    (operators/elementwise/elementwise_op_function.h semantics). axis=-1
    means trailing alignment (numpy default)."""
    if axis is None or axis == -1 or y.ndim == 0 or y.ndim == x_ndim:
        return y
    # pad y's shape with 1s: axis leading, rest trailing
    shape = (1,) * axis + tuple(y.shape) + (1,) * (x_ndim - axis - y.ndim)
    return y.reshape(shape)
