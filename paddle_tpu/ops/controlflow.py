"""Control-flow ops (parity: operators/controlflow/ — WhileOp
while_op.cc:43, ConditionalBlockOp conditional_block_op.cc:75,
recurrent_op.cc; plus increment/print utility ops).

TPU-native design (SURVEY §7 "hard parts"): Fluid interprets sub-blocks
over mutable step scopes; here the sub-block is *symbolically re-executed*
inside `lax.while_loop` / `lax.cond` / `lax.scan` with explicit carried
state. Each control-flow op lists every outer variable its sub-block touches
as a real input (slot "X", names in attr `x_names`), so
 (a) the executor's persistable-state scan sees through the loop, and
 (b) the generic vjp grad machinery (core/lowering.py) differentiates
     through `cond`/`recurrent` with no hand-written grad kernels.
`while` is forward-only (lax.while_loop has no reverse-mode rule); Fluid
models needing a differentiable loop express it as `recurrent` (StaticRNN/
DynamicRNN), same as the reference's preferred path.
"""

import typing

import jax
import jax.numpy as jnp

from ..core.lowering import execute_block
from .registry import register, simple_op


@simple_op("increment")
def _increment(ctx, x, **attrs):
    return x + jnp.asarray(attrs.get("step", 1.0), x.dtype)


@register("print", differentiable=False)
def _print(ctx, ins, attrs):
    x = ins["In"][0]
    msg = attrs.get("message", "") or ""
    jax.debug.print(msg + " {x}", x=x)
    return {"Out": [x]}


@register("select_rowwise")
def _select_rowwise(ctx, ins, attrs):
    """Row-wise merge for IfElse (split/merge_lod_tensor parity without
    data-dependent shapes): out[b] = cond[b] ? x[b] : y[b]."""
    c = ins["Cond"][0]
    x, y = ins["X"][0], ins["Y"][0]
    c = jnp.reshape(c.astype(bool), (-1,) + (1,) * (x.ndim - 1))
    return {"Out": [jnp.where(c, x, y)]}


class TensorArrayBuf(typing.NamedTuple):
    """In-graph LoDTensorArray: a fixed-capacity stacked buffer
    [capacity, *elem] plus a live-length scalar. As a NamedTuple it is a
    pytree, so it rides lax.while_loop/scan carries — this is what lets
    the reference's While-loop beam decoder (the level-2-LoD workload,
    book test decoder_decode) run INSIDE one jitted region with a traced
    write index, instead of host-side between segments."""

    buf: typing.Any
    n: typing.Any


@register("array_write", differentiable=False)
def _array_write(ctx, ins, attrs):
    """LoDTensorArray write (tensor_array_read_write.cc). Two modes:
    host-side python list (concrete index — between jitted segments, the
    original representation), or TensorArrayBuf (inside a traced While:
    dynamic_update at a traced index into the pre-stacked buffer; the
    `while` lowering converts carried lists to buffers on loop entry)."""
    arr = ins.get("ArrayIn", [None])[0]
    i = ins["I"][0].reshape(())
    x = ins["X"][0]
    if isinstance(arr, TensorArrayBuf):
        i32 = i.astype(jnp.int32)
        buf = jax.lax.dynamic_update_index_in_dim(
            arr.buf, x.astype(arr.buf.dtype), i32, axis=0)
        n = jnp.maximum(arr.n, i32 + 1)
        return {"Out": [TensorArrayBuf(buf, n)]}
    if isinstance(i, jax.core.Tracer):
        raise RuntimeError(
            "array_write at a traced index outside a While carry: give the "
            "enclosing While a max_trip_count so the lowering can size the "
            "array buffer, or write between jitted segments")
    arr = list(arr or [])
    i = int(i)
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    return {"Out": [arr]}


@register("array_read", differentiable=False)
def _array_read(ctx, ins, attrs):
    arr = ins["X"][0]
    i = ins["I"][0].reshape(())
    if isinstance(arr, TensorArrayBuf):
        return {"Out": [jax.lax.dynamic_index_in_dim(
            arr.buf, i.astype(jnp.int32), axis=0, keepdims=False)]}
    return {"Out": [arr[int(i)]]}


@register("array_length", differentiable=False)
def _array_length(ctx, ins, attrs):
    arr = ins["X"][0]
    if isinstance(arr, TensorArrayBuf):
        return {"Out": [arr.n.reshape((1,)).astype(jnp.int32)]}
    return {"Out": [jnp.asarray([len(arr)], jnp.int32)]}


@register("tensor_array_to_tensor", differentiable=False)
def _tensor_array_to_tensor(ctx, ins, attrs):
    """Concat a LoDTensorArray along `axis`
    (tensor_array_to_tensor_op.cc). OutIndex records each element's size
    along the axis, the dense stand-in for the output LoD. For a
    TensorArrayBuf (array carried through a While) the FULL static
    capacity is emitted — the live length is dynamic (arr.n); slots past
    it hold zeros. Slice by OutIndex/arr.n host-side if the loop can end
    early."""
    arr = ins["X"][0]
    axis = attrs.get("axis", 1)
    use_stack = attrs.get("use_stack", False)
    if isinstance(arr, TensorArrayBuf):
        elems = [arr.buf[k] for k in range(arr.buf.shape[0])]
        cap = arr.buf.shape[0]
        # surface the capacity-vs-live-length divergence at run time (the
        # executor warns host-side once per site) instead of only in docs;
        # skip inside control-flow sub-traces where arr.n is an inner
        # tracer that may not leak into the outer step's reports
        if not ctx._nan_suppress:
            ctx.warn_reports.append((
                "tensor_array_to_tensor on a While-carried array emitted "
                "its full static capacity (%d elements) but the loop "
                "exited with fewer live entries — the tail is zeros; "
                "slice by OutIndex / array_length host-side "
                "(docs/MIGRATING.md)" % cap,
                arr.n < cap))
    else:
        elems = list(arr)
    if use_stack:
        out = jnp.stack(elems, axis=axis)
        sizes = jnp.ones((len(elems),), jnp.int32)
    else:
        out = jnp.concatenate(elems, axis=axis)
        sizes = jnp.asarray([a.shape[axis] for a in elems], jnp.int32)
    return {"Out": [out], "OutIndex": [sizes]}


def _env_of(ins, attrs):
    return dict(zip(attrs["x_names"], ins.get("X", [])))


@register("while", nondiff_inputs=("Condition",))
def _while(ctx, ins, attrs):
    """while_op.cc:43 — iterate sub_block until Condition goes false.
    Carried state = attr `carry_names` (sub-block writes that are
    parent-visible, incl. the condition).

    Two lowerings (SURVEY §7 hard-part "backward of While"):
    - `max_trip_count` set: lax.scan over that static length, each step
      masked by the live condition (lax.cond with an identity false
      branch) — reverse-differentiable, so while_grad (the reference's
      while_op.cc:43 grad maker) comes for free from the generic vjp.
    - unset: lax.while_loop — fully dynamic trip count, forward-only
      (append_backward raises a loud error rather than silently skipping)."""
    block = attrs["sub_block"]
    carry_names = list(attrs["carry_names"])
    env = _env_of(ins, attrs)
    env[attrs["cond_name"]] = ins["Condition"][0]
    cond_idx = carry_names.index(attrs["cond_name"])
    max_trip = attrs.get("max_trip_count")

    # tensor arrays (host lists) touched by the loop become fixed-capacity
    # stacked buffers so in-loop array_read/array_write lower to dynamic
    # index/update at the traced counter (the reference beam-decoder
    # pattern, tensor_array_read_write.cc inside while_op.cc). Capacity =
    # current length + max_trip_count * (writes to this array per trip);
    # read-only arrays need no headroom.
    def _writes_per_trip(blk, name):
        count = 0
        for op in blk.ops:
            if op.type == "array_write" and any(
                    v.name == name for v in op.outputs.get("Out", [])):
                count += 1
            for key in ("sub_block", "true_block", "false_block"):
                sub = op.attrs.get(key) if op.attrs else None
                if sub is not None and getattr(sub, "ops", None) is not None:
                    count += _writes_per_trip(sub, name)
        return count

    def _writer_x_var(blk, name):
        """The Variable written into array `name` by an in-loop
        array_write — its static shape seeds the buffer element proto when
        the array enters the loop empty (layers.create_array)."""
        for op in blk.ops:
            if op.type == "array_write" and any(
                    v.name == name for v in op.outputs.get("Out", [])):
                xs = op.inputs.get("X", [])
                if xs:
                    return xs[0]
            for key in ("sub_block", "true_block", "false_block"):
                sub = op.attrs.get(key) if op.attrs else None
                if sub is not None and getattr(sub, "ops", None) is not None:
                    found = _writer_x_var(sub, name)
                    if found is not None:
                        return found
        return None

    for name in list(env):
        val = env.get(name)
        if isinstance(val, list) and all(
                hasattr(e, "shape") for e in val if e is not None):
            writes = _writes_per_trip(block, name)
            if not val and not writes:
                continue  # empty and untouched: not a tensor array in use
            if writes and not max_trip:
                raise RuntimeError(
                    "While writes tensor array %r but has no "
                    "max_trip_count: the in-graph array buffer needs a "
                    "static capacity. Build the loop as "
                    "layers.While(cond, max_trip_count=N)" % name)
            elems = [e for e in val if e is not None]
            cap = len(val) + int(max_trip or 0) * writes
            if elems:
                proto = jnp.zeros_like(elems[0])
            else:
                # array created empty (layers.create_array) and first
                # written inside the loop: no seed element, so infer the
                # element proto from the writer's static var shape
                from ..framework import dtype_to_np

                xvar = _writer_x_var(block, name)
                shape = getattr(xvar, "shape", None)
                if xvar is None or shape is None or any(
                        d is None or d < 0 for d in shape):
                    raise RuntimeError(
                        "tensor array %r enters the While empty and its "
                        "in-loop writes have no static shape to size the "
                        "buffer element from — write one seed element "
                        "before the loop (array_write at index 0), or "
                        "give the written value a fully static shape"
                        % name)
                proto = jnp.zeros(tuple(shape), dtype_to_np(xvar.dtype))
            padded = [e if e is not None else proto for e in val]
            padded += [proto] * (cap - len(padded))
            env[name] = TensorArrayBuf(
                jnp.stack(padded, axis=0),
                jnp.asarray(len(val), jnp.int32))

    def body_fn(carry):
        local = dict(env)
        local.update(zip(carry_names, carry))
        with ctx.inner_trace():
            execute_block(block, local, ctx)
        return tuple(local[n] for n in carry_names)

    init = tuple(env[n] for n in carry_names)
    if max_trip:
        def scan_step(carry, _):
            pred = jnp.reshape(carry[cond_idx], ()).astype(bool)
            new = jax.lax.cond(pred, body_fn, lambda c: c, carry)
            return new, None

        final, _ = jax.lax.scan(scan_step, init, None,
                                length=int(max_trip))
        if not ctx._nan_suppress:
            # condition still live after N masked steps = the loop was
            # TRUNCATED (the dynamic while_loop would have kept going);
            # surface it instead of silently returning early carries
            ctx.warn_reports.append((
                "While loop truncated: condition still true after "
                "max_trip_count=%d steps" % int(max_trip),
                jnp.reshape(final[cond_idx], ()).astype(bool)))
    else:
        def cond_fn(carry):
            return jnp.reshape(carry[cond_idx], ()).astype(bool)

        final = jax.lax.while_loop(cond_fn, body_fn, init)
    out_names = attrs["out_names"]
    final_env = dict(zip(carry_names, final))
    return {"Out": [final_env[n] for n in out_names]}


@register("recompute")
def _recompute(ctx, ins, attrs):
    """Rematerialized segment (the TPU-native remat knob; the reference's
    later RecomputeOptimizer plays this role on GPU). Forward executes the
    sub_block once; because the segment function is wrapped in
    `jax.checkpoint`, the generic vjp grad op (core/lowering.py
    _execute_grad_op) saves only the segment INPUTS as residuals and
    re-executes the sub_block — behind an XLA optimization barrier, so CSE
    cannot merge it back with the forward — during the backward pass.
    Activations internal to the segment never stay live between forward and
    backward, trading FLOPs for HBM exactly like jax.checkpoint on a
    hand-written model. Deterministic per-op PRNG (ctx.rng folds on the op
    seed, not trace position) guarantees dropout masks agree between the
    forward run and the backward recompute."""
    block = attrs["sub_block"]
    x_names = list(attrs["x_names"])
    out_names = list(attrs["out_names"])

    @jax.checkpoint
    def seg(*vals):
        local = dict(zip(x_names, vals))
        with ctx.inner_trace():
            execute_block(block, local, ctx)
        return tuple(local[n] for n in out_names)

    outs = seg(*ins.get("X", []))
    return {"Out": list(outs)}


@register("cond")
def _cond(ctx, ins, attrs):
    """Functional two-branch conditional (modern layers.cond; IfElse/Switch
    lower onto this). A branch that doesn't write an output var falls back
    to the var's incoming value (conditional_block_op.cc:75 skip
    semantics)."""
    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    env = _env_of(ins, attrs)
    out_names = attrs["out_names"]

    def run(block):
        local = dict(env)
        if block is not None:
            with ctx.inner_trace():
                execute_block(block, local, ctx)
        return tuple(local[n] for n in out_names)

    outs = jax.lax.cond(pred,
                        lambda: run(attrs["true_block"]),
                        lambda: run(attrs.get("false_block")))
    return {"Out": list(outs)}


@register("recurrent")
def _recurrent(ctx, ins, attrs):
    """recurrent_op.cc — scan sub_block over the leading (time) axis.

    slots: StepInputs (time-major [T, ...]), Boot (initial memories),
    X (closure); attrs: step_input_names/memory_names (inner [pre, post]
    pairs)/step_output_names/x_names/sub_block; optional SeqLen input masks
    memory updates past each sequence's length (DynamicRNN parity without
    LoD batch shrinking — SURVEY §5.7)."""
    block = attrs["sub_block"]
    env = _env_of(ins, attrs)
    step_in_names = attrs["step_input_names"]
    mem_pairs = attrs["memory_names"]  # [(pre_name, post_name), ...]
    step_out_names = attrs["step_output_names"]
    reverse = bool(attrs.get("is_reverse", False))

    xs = tuple(ins.get("StepInputs", []))
    init = tuple(ins.get("Boot", []))
    seq_len = ins.get("SeqLen", [None])[0]

    def step(carry, xs_and_t):
        t, xs_t = xs_and_t
        local = dict(env)
        local.update(zip(step_in_names, xs_t))
        local.update(zip([p for p, _ in mem_pairs], carry))
        with ctx.inner_trace():
            execute_block(block, local, ctx)
        new = [local[q] for _, q in mem_pairs]
        if seq_len is not None:
            # batch rows whose sequence ended keep their old memory
            alive = t < seq_len.reshape((-1,))

            def sel(n, c):
                return jnp.where(
                    jnp.reshape(alive, (-1,) + (1,) * (n.ndim - 1)), n, c)

            new = [sel(n, c) for n, c in zip(new, carry)]
            ys = tuple(
                jnp.where(jnp.reshape(alive, (-1,) + (1,) * (y.ndim - 1)),
                          y, jnp.zeros_like(y))
                for y in (local[n] for n in step_out_names))
        else:
            ys = tuple(local[n] for n in step_out_names)
        return tuple(new), ys

    if attrs.get("remat"):
        # rematerialized scan body (StaticRNN(remat=True)): the backward
        # through lax.scan recomputes each step from its carry instead of
        # storing the body's internals — the native flagship's
        # layers-under-scan memory profile, available to API users
        step = jax.checkpoint(step)
    T = xs[0].shape[0] if xs else attrs["max_len"]
    ts = jnp.arange(T)
    final_carry, ys = jax.lax.scan(step, init, (ts, xs), reverse=reverse)
    return {"StepOutputs": list(ys), "FinalMemories": list(final_carry)}
