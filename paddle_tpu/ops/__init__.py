"""TPU op corpus. Importing this package registers all op kernels
(parity: the REGISTER_OPERATOR corpus, SURVEY §2.2 / Appendix A)."""

from . import registry  # noqa: F401
from . import math  # noqa: F401
from . import elementwise  # noqa: F401
from . import activations  # noqa: F401
from . import reduce  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import conv  # noqa: F401
from . import loss_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import controlflow  # noqa: F401
from . import misc_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import compat_ops  # noqa: F401
from . import fused_tail_ops  # noqa: F401
