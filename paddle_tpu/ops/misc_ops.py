"""Misc op corpus: CRF, CTC, sampled losses, beam search, hashing, tree/row
conv, chunk metrics (parity: operators/linear_chain_crf_op.cc,
crf_decoding_op.cc, ctc_align_op.cc, edit_distance_op.cc, warpctc_op.cc,
nce_op.cc, hierarchical_sigmoid_op.cc, crop_op.cc, hash_op.cc, fsp_op.cc,
row_conv_op.cc, tree_conv_op.cc, beam_search_op.cc, beam_search_decode_op.cc,
chunk_eval_op.cc, cvm_op.cc, merge_selected_rows_op.cc,
get_tensor_from_selected_rows_op.cc, py_func_op.cc — SURVEY Appendix A).

TPU-native conventions: ragged LoD inputs become padded-dense [B, T, ...]
with an optional integer Length input; dynamic-programming recurrences
(CRF forward, Viterbi, CTC, edit distance) are lax.scan loops over the
time axis so XLA compiles them as single fused loops.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

_NEG = -1e30


def _lengths(ins, B, T, slot="Length"):
    if ins.get(slot):
        return ins[slot][0].reshape((-1,)).astype(jnp.int32)
    return jnp.full((B,), T, jnp.int32)


# ---------------------------------------------------------------------------
# linear-chain CRF (linear_chain_crf_op.cc / crf_decoding_op.cc)
# ---------------------------------------------------------------------------
# Transition layout matches the reference: row 0 = start weights, row 1 =
# stop weights, rows 2..C+1 = transition[i][j] score of i -> j.


def _crf_unpack(transition):
    start, stop, trans = transition[0], transition[1], transition[2:]
    return start, stop, trans


@register("linear_chain_crf", nondiff_inputs=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    em = ins["Emission"][0]          # [B, T, C] unnormalized emission scores
    transition = ins["Transition"][0]  # [C+2, C]
    label = ins["Label"][0].reshape(em.shape[:2]).astype(jnp.int32)  # [B, T]
    B, T, C = em.shape
    lens = _lengths(ins, B, T)
    start, stop, trans = _crf_unpack(transition)
    em = em.astype(jnp.float32)

    t_idx = jnp.arange(T)
    valid = (t_idx[None, :] < lens[:, None])  # [B, T]

    # --- log partition via forward algorithm (alpha recursion) ---
    alpha0 = start[None, :] + em[:, 0]  # [B, C]

    def fwd(alpha, xs):
        e_t, valid_t = xs  # [B, C], [B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None, :, :], axis=1)
        nxt = nxt + e_t
        alpha = jnp.where(valid_t[:, None], nxt, alpha)
        return alpha, alpha

    alphaT, alphas = jax.lax.scan(
        fwd, alpha0, (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    logZ = jax.nn.logsumexp(alphaT + stop[None, :], axis=1)  # [B]

    # --- score of the gold path ---
    emit_score = jnp.sum(
        jnp.take_along_axis(em, label[:, :, None], axis=2)[..., 0]
        * valid.astype(jnp.float32), axis=1)
    prev, nxt = label[:, :-1], label[:, 1:]
    trans_score = jnp.sum(
        trans[prev, nxt] * valid[:, 1:].astype(jnp.float32), axis=1)
    last = jnp.take_along_axis(
        label, jnp.maximum(lens - 1, 0)[:, None], axis=1)[:, 0]
    path = emit_score + trans_score + start[label[:, 0]] + stop[last]

    ll = (path - logZ)[:, None]  # log-likelihood [B, 1]
    alpha_full = jnp.concatenate([alpha0[:, None], jnp.swapaxes(alphas, 0, 1)],
                                 axis=1)
    return {"Alpha": [alpha_full], "EmissionExps": [jnp.exp(em)],
            "TransitionExps": [jnp.exp(transition.astype(jnp.float32))],
            "LogLikelihood": [ll]}


@register("crf_decoding", differentiable=False,
          nondiff_inputs=("Emission", "Transition", "Label", "Length"))
def _crf_decoding(ctx, ins, attrs):
    em = ins["Emission"][0].astype(jnp.float32)  # [B, T, C]
    transition = ins["Transition"][0].astype(jnp.float32)
    B, T, C = em.shape
    lens = _lengths(ins, B, T)
    start, stop, trans = _crf_unpack(transition)
    valid = (jnp.arange(T)[None, :] < lens[:, None])

    # Viterbi forward keeping backpointers
    delta0 = start[None, :] + em[:, 0]

    def vit(delta, xs):
        e_t, valid_t = xs
        cand = delta[:, :, None] + trans[None, :, :]     # [B, C_prev, C]
        best = jnp.max(cand, axis=1) + e_t
        bp = jnp.argmax(cand, axis=1).astype(jnp.int32)  # [B, C]
        new = jnp.where(valid_t[:, None], best, delta)
        bp = jnp.where(valid_t[:, None], bp, jnp.arange(C)[None, :])
        return new, bp

    deltaT, bps = jax.lax.scan(
        vit, delta0,
        (jnp.swapaxes(em, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    lastmax = jnp.argmax(deltaT + stop[None, :], axis=1).astype(jnp.int32)

    # backward pass: walk backpointers from each sequence's last position
    def back(state, bp_t):
        cur, t = state  # cur [B], t scalar index into bps (reversed walk)
        prev = jnp.take_along_axis(bp_t, cur[:, None], axis=1)[:, 0]
        # only move the pointer for rows where t < len-1 (inside the seq)
        cur = jnp.where(t < lens - 1, prev, cur)
        return (cur, t - 1), cur

    (_, _), rev_path = jax.lax.scan(
        back, (lastmax, jnp.full((), T - 2)), bps, reverse=True)
    path = jnp.concatenate(
        [jnp.swapaxes(rev_path, 0, 1), lastmax[:, None]], axis=1)  # [B, T]
    path = jnp.where(valid, path, 0)

    if ins.get("Label"):
        lab = ins["Label"][0].reshape((B, T)).astype(jnp.int32)
        # parity: with Label given, emit 1 where prediction is correct
        out = (path == lab).astype(jnp.int64) * valid.astype(jnp.int64)
        return {"ViterbiPath": [out[..., None]]}
    return {"ViterbiPath": [path[..., None].astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# CTC: greedy decode, edit distance, warpctc loss
# ---------------------------------------------------------------------------


@register("ctc_align", differentiable=False, nondiff_inputs=("Input",))
def _ctc_align(ctx, ins, attrs):
    """Greedy CTC decode: merge repeats then drop blanks. Output is padded
    with -1 (the dense stand-in for the reference's LoD output)."""
    ids = ins["Input"][0].astype(jnp.int32)  # [B, T] argmax'd ids
    blank = attrs.get("blank", 0)
    B, T = ids.shape
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), ids[:, :-1]], axis=1)
    keep = (ids != prev) & (ids != blank)
    if ins.get("Length"):
        lens = _lengths(ins, B, T)
        keep = keep & (jnp.arange(T)[None, :] < lens[:, None])
    # stable left-compaction of kept ids
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((B, T), -1, jnp.int32)
    bidx = jnp.repeat(jnp.arange(B)[:, None], T, axis=1)
    out = out.at[bidx, jnp.where(keep, pos, T - 1)].set(
        jnp.where(keep, ids, -1), mode="drop")
    out_lens = jnp.sum(keep.astype(jnp.int32), axis=1)
    return {"Output": [out], "OutputLength": [out_lens[:, None]]}


@register("edit_distance", differentiable=False,
          nondiff_inputs=("Hyps", "Refs", "HypsLength", "RefsLength"))
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance, batched. DP over the ref axis as a lax.scan;
    pad token rows are neutralized via the Length inputs."""
    hyp = ins["Hyps"][0].astype(jnp.int32)
    ref = ins["Refs"][0].astype(jnp.int32)
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    B, Th = hyp.shape
    Tr = ref.shape[1]
    hlens = _lengths(ins, B, Th, "HypsLength")
    rlens = _lengths(ins, B, Tr, "RefsLength")

    row0 = jnp.broadcast_to(jnp.arange(Th + 1, dtype=jnp.float32), (B, Th + 1))

    def step(row, xs):
        r_tok, i = xs  # ref token [B], row index (1-based)
        inside = (i <= rlens).astype(jnp.float32)  # [B]
        sub = (hyp != r_tok[:, None]).astype(jnp.float32)  # [B, Th]
        # new[0] = i; new[j] = min(row[j]+1, new[j-1]+1, row[j-1]+sub)
        # the left-to-right dependency is itself a scan over Th
        def inner(left, xs2):
            up, diag, s = xs2  # [B] each
            val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0), diag + s)
            return val, val

        _, tail = jax.lax.scan(
            inner, jnp.full((B,), i, jnp.float32),
            (row[:, 1:].T, row[:, :-1].T, sub.T))
        new = jnp.concatenate([jnp.full((B, 1), i, jnp.float32), tail.T], axis=1)
        row = jnp.where(inside[:, None] > 0, new, row)
        return row, None

    row, _ = jax.lax.scan(
        step, row0,
        (ref.T, jnp.arange(1, Tr + 1, dtype=jnp.float32)))
    dist = jnp.take_along_axis(row, hlens[:, None], axis=1)[:, 0]
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(rlens.astype(jnp.float32), 1.0)
    seq_num = jnp.array([B], jnp.int32)
    return {"Out": [dist[:, None]], "SequenceNum": [seq_num]}


@register("warpctc", nondiff_inputs=("Label", "LogitsLength", "LabelLength"))
def _warpctc(ctx, ins, attrs):
    """CTC loss via the log-semiring alpha recursion (warpctc_op.cc parity,
    computed natively instead of calling the warp-ctc library)."""
    logits = ins["Logits"][0].astype(jnp.float32)  # [B, T, C] (batch-first)
    label = ins["Label"][0].astype(jnp.int32)      # [B, S]
    if label.ndim == 3:
        label = label[..., 0]
    blank = attrs.get("blank", 0)
    if attrs.get("norm_by_times", False):
        pass  # normalization applied at the end
    B, T, C = logits.shape
    S = label.shape[1]
    llen = _lengths(ins, B, T, "LogitsLength")
    slen = _lengths(ins, B, S, "LabelLength")

    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended sequence: blank, l1, blank, l2, ..., blank  (length 2S+1)
    ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    ext_valid = jnp.arange(2 * S + 1)[None, :] < (2 * slen + 1)[:, None]

    # allow skip (alpha[s-2]) where ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), blank, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)
    can_skip = can_skip.at[:, :2].set(False)

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)  # [B, 2S+1]

    a0 = jnp.full((B, 2 * S + 1), _NEG)
    a0 = a0.at[:, 0].set(logp[:, 0, blank])
    a0 = a0.at[:, 1].set(jnp.take_along_axis(
        logp[:, 0], label[:, :1], axis=1)[:, 0])
    a0 = jnp.where(ext_valid, a0, _NEG)

    shift1 = jnp.full((B, 1), _NEG)

    def step(alpha, t):
        a1 = jnp.concatenate([shift1, alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([shift1, shift1, alpha[:, :-2]], axis=1)
        a2 = jnp.where(can_skip, a2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        e = jnp.take_along_axis(logp[:, t], ext, axis=1)
        new = jnp.where(ext_valid, merged + e, _NEG)
        alpha = jnp.where((t < llen)[:, None], new, alpha)
        return alpha, None

    alphaT, _ = jax.lax.scan(step, a0, jnp.arange(1, T))
    endpos = 2 * slen  # last blank
    last_blank = jnp.take_along_axis(alphaT, endpos[:, None], axis=1)[:, 0]
    last_label = jnp.take_along_axis(
        alphaT, jnp.maximum(endpos - 1, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(last_blank, last_label)
    loss = -ll
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(llen.astype(jnp.float32), 1.0)
    return {"Loss": [loss[:, None]],
            "WarpCTCGrad": [jnp.zeros_like(logits)]}


# ---------------------------------------------------------------------------
# sampled losses: NCE + hierarchical sigmoid
# ---------------------------------------------------------------------------


@register("nce", nondiff_inputs=("Label",), stateful=True)
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation with a uniform noise sampler
    (nce_op.cc; the reference defaults to its uniform sampler too)."""
    x = ins["Input"][0]                     # [B, D]
    w = ins["Weight"][0]                    # [N, D]
    label = ins["Label"][0].reshape((-1,)).astype(jnp.int32)  # [B]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_total = w.shape[0]
    num_neg = attrs.get("num_neg_samples", 10)
    B = x.shape[0]

    key = ctx.rng(attrs)
    noise = jax.random.randint(key, (B, num_neg), 0, num_total)
    ids = jnp.concatenate([label[:, None], noise], axis=1)  # [B, 1+K]

    w_s = w[ids]                                    # [B, 1+K, D]
    logits = jnp.einsum("bd,bkd->bk", x, w_s)
    if bias is not None:
        logits = logits + bias.reshape((-1,))[ids]
    # NCE binary labels: first col true, rest noise
    p_noise = 1.0 / num_total
    logits = logits - jnp.log(num_neg * p_noise)
    lab = jnp.zeros_like(logits).at[:, 0].set(1.0)
    per = (jnp.maximum(logits, 0) - logits * lab
           + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    cost = jnp.sum(per, axis=1, keepdims=True)
    return {"Cost": [cost], "SampleLogits": [logits],
            "SampleLabels": [ids]}


@register("hierarchical_sigmoid", nondiff_inputs=("Label",))
def _hsigmoid(ctx, ins, attrs):
    """Default complete-binary-tree hierarchical sigmoid
    (hierarchical_sigmoid_op.cc). Codes/paths for class c come from the
    bits of (c + num_classes) as in the reference's SimpleCode."""
    x = ins["X"][0]                        # [B, D]
    w = ins["W"][0]                        # [num_classes-1, D]
    label = ins["Label"][0].reshape((-1,)).astype(jnp.int32)
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = attrs["num_classes"]
    B = x.shape[0]
    max_code = int(np.ceil(np.log2(max(num_classes, 2))))

    # SimpleCode: code(c) = c + num_classes; node at depth d =
    # (code >> (L-d)) - 1 valid while (code >> (L-d)) > 1
    code = label + num_classes
    L = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
    d = jnp.arange(max_code)[None, :]                     # [1, M]
    shifted = code[:, None] >> jnp.maximum(L[:, None] - d, 0)
    valid = d < L[:, None]
    node = jnp.where(valid, shifted - 1, 0)               # [B, M]
    bit = jnp.where(valid, (code[:, None] >> jnp.maximum(
        L[:, None] - d - 1, 0)) & 1, 0)                   # next-branch bit

    w_n = w[node]                                         # [B, M, D]
    logits = jnp.einsum("bd,bmd->bm", x, w_n)
    if bias is not None:
        logits = logits + bias.reshape((-1,))[node]
    t = bit.astype(jnp.float32)
    per = (jnp.maximum(logits, 0) - logits * t
           + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    per = per * valid.astype(jnp.float32)
    out = jnp.sum(per, axis=1, keepdims=True)
    pre_out = jax.nn.sigmoid(logits)
    return {"Out": [out], "PreOut": [pre_out]}


# ---------------------------------------------------------------------------
# small structural ops
# ---------------------------------------------------------------------------


@register("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    if ins.get("Offsets"):
        off = ins["Offsets"][0]
        offsets = [off[i] for i in range(x.ndim)]
    else:
        offsets = attrs.get("offsets", [0] * x.ndim)
    shape = attrs.get("shape")
    if ins.get("Y") and shape is None:
        shape = ins["Y"][0].shape
    out = jax.lax.dynamic_slice(x, [jnp.asarray(o) for o in offsets],
                                shape)
    return {"Out": [out]}


@register("hash", differentiable=False, nondiff_inputs=("X",))
def _hash(ctx, ins, attrs):
    """Multiplicative int hashing into num_hash buckets of size mod_by
    (hash_op.cc uses xxhash over the id bytes; any stable hash satisfies
    the contract of mapping id-tuples to [0, mod_by))."""
    x = ins["X"][0].astype(jnp.uint32)     # [B, L] or [B, L, 1]
    squeeze = x.ndim == 3
    if squeeze:
        x = x[..., 0]
    num_hash = attrs.get("num_hash", 1)
    mod_by = attrs.get("mod_by", 1)
    seeds = jnp.arange(1, num_hash + 1, dtype=jnp.uint32) * np.uint32(0x9E3779B1)
    h = x[..., None] * seeds + (x[..., None] >> 16)
    h = h ^ (h >> 13)
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    out = (h % jnp.uint32(mod_by)).astype(jnp.int64)  # [B, L, num_hash]
    return {"Out": [out]}


@register("fsp")
def _fsp(ctx, ins, attrs):
    """Flow-of-solution-procedure matrix for distillation (fsp_op.cc):
    Out[b, i, j] = mean_hw X[b,i,h,w] * Y[b,j,h,w]."""
    x, y = ins["X"][0], ins["Y"][0]
    hw = x.shape[2] * x.shape[3]
    out = jnp.einsum("bihw,bjhw->bij", x, y) / hw
    return {"Out": [out]}


@register("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (row_conv_op.cc): out[t] =
    sum_{i<k} W[i] * x[t+i], batch-first padded-dense [B, T, D]."""
    x = ins["X"][0]
    w = ins["Filter"][0]  # [k, D]
    k = w.shape[0]
    B, T, D = x.shape
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for i in range(k):  # k is a small static constant; unrolled matmul-free
        out = out + xp[:, i:i + T, :] * w[i][None, None, :]
    return {"Out": [out]}


@register("tree_conv")
def _tree_conv(ctx, ins, attrs):
    """Tree-based convolution (tree_conv_op.cc, TBCNN). NodesVector
    [B, N, D], EdgeSet [B, E, 2] (parent->child int pairs), Filter
    [D, 3, out, num_filters]. The three filter slices play the TBCNN
    top/left/right roles; children aggregate into parents by mean."""
    nodes = ins["NodesVector"][0]
    edges = ins["EdgeSet"][0].astype(jnp.int32)
    filt = ins["Filter"][0]       # [D, 3, out, F]
    B, N, D = nodes.shape
    E = edges.shape[1]
    parent, child = edges[..., 0], edges[..., 1]  # [B, E]
    ok = (parent >= 0) & (child >= 0) & (parent != child)

    onehot = jax.nn.one_hot(jnp.where(ok, parent, N), N + 1,
                            dtype=nodes.dtype)[..., :N]     # [B, E, N]
    child_vec = jnp.take_along_axis(
        nodes, jnp.where(ok, child, 0)[..., None], axis=1)  # [B, E, D]
    child_vec = child_vec * ok[..., None].astype(nodes.dtype)
    summed = jnp.einsum("ben,bed->bnd", onehot, child_vec)
    cnt = jnp.maximum(jnp.einsum("ben->bn", onehot), 1.0)[..., None]
    child_mean = summed / cnt

    # left/right split: order of a child among its siblings (approximated by
    # child id parity — static-shape friendly sibling ordering)
    left_mask = (child % 2 == 0) & ok
    right_mask = (child % 2 == 1) & ok
    lsum = jnp.einsum("ben,bed->bnd", onehot * left_mask[..., None], child_vec)
    rsum = jnp.einsum("ben,bed->bnd", onehot * right_mask[..., None], child_vec)

    out = (jnp.einsum("bnd,dof->bnof", nodes, filt[:, 0])
           + jnp.einsum("bnd,dof->bnof", lsum / cnt, filt[:, 1])
           + jnp.einsum("bnd,dof->bnof", rsum / cnt, filt[:, 2]))
    del child_mean
    return {"Out": [jnp.tanh(out)]}


@register("lod_reset")
def _lod_reset(ctx, ins, attrs):
    """Padded-dense parity: data passes through; the new per-row lengths (the
    reference's target LoD) ride along as an extra output."""
    x = ins["X"][0]
    if ins.get("Y"):
        lens = ins["Y"][0]
    else:
        tl = attrs.get("target_lod", [])
        lens = jnp.diff(jnp.asarray(tl, jnp.int32)) if len(tl) else \
            jnp.full((x.shape[0],), x.shape[1] if x.ndim > 1 else 1, jnp.int32)
    return {"Out": [x], "Length": [lens]}


@register("cvm", nondiff_inputs=("CVM",))
def _cvm(ctx, ins, attrs):
    """Continuous-value-model op (cvm_op.cc): X's first two features are
    show/click counters; use_cvm keeps them log-transformed, otherwise they
    are stripped."""
    x = ins["X"][0]
    use_cvm = attrs.get("use_cvm", True)
    if use_cvm:
        show = jnp.log(x[:, :1] + 1.0)
        click = jnp.log(x[:, 1:2] + 1.0) - jnp.log(x[:, :1] + 1.0)
        out = jnp.concatenate([show, click, x[:, 2:]], axis=1)
    else:
        out = x[:, 2:]
    return {"Y": [out]}


@register("merge_selected_rows")
def _merge_selected_rows(ctx, ins, attrs):
    # dense-grad world: rows are already merged by XLA scatter-add
    return {"Out": [ins["X"][0]]}


@register("get_tensor_from_selected_rows")
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


# ---------------------------------------------------------------------------
# beam search (dense [batch, beam] semantics replacing the reference's LoD)
# ---------------------------------------------------------------------------


@register("beam_search", differentiable=False,
          nondiff_inputs=("pre_ids", "pre_scores", "ids", "scores"))
def _beam_search(ctx, ins, attrs):
    """One beam-search step (beam_search_op.cc). Dense layout: pre_ids
    [batch, beam], pre_scores [batch, beam], ids/scores [batch, beam, K]
    per-candidate continuations. Emits top beam_size of beam*K candidates
    per source sentence plus the parent beam index for backtracking."""
    pre_ids = ins["pre_ids"][0].astype(jnp.int32)
    pre_scores = ins["pre_scores"][0].astype(jnp.float32)
    ids = ins["ids"][0].astype(jnp.int32)
    scores = ins["scores"][0].astype(jnp.float32)
    beam_size = attrs.get("beam_size", ids.shape[1])
    end_id = attrs.get("end_id", 0)
    Bz, W, K = scores.shape

    finished = pre_ids == end_id
    # finished beams only propagate themselves with unchanged score
    cand = pre_scores[:, :, None] + jnp.log(jnp.maximum(scores, 1e-20))
    cand = jnp.where(finished[:, :, None],
                     jnp.where(jnp.arange(K)[None, None, :] == 0,
                               pre_scores[:, :, None], _NEG),
                     cand)
    cand_ids = jnp.where(finished[:, :, None], end_id, ids)

    flat = cand.reshape((Bz, W * K))
    top_s, top_i = jax.lax.top_k(flat, beam_size)
    parent = (top_i // K).astype(jnp.int32)
    sel = jnp.take_along_axis(cand_ids.reshape((Bz, W * K)), top_i, axis=1)
    return {"selected_ids": [sel], "selected_scores": [top_s],
            "parent_idx": [parent]}


@register("beam_search_decode", differentiable=False,
          nondiff_inputs=("Ids", "Scores", "Parents"))
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack stacked per-step ids/parents [T, batch, beam] into full
    sequences [batch, beam, T] (beam_search_decode_op.cc)."""
    ids = ins["Ids"][0].astype(jnp.int32)        # [T, B, W]
    scores = ins["Scores"][0].astype(jnp.float32)
    parents = ins["Parents"][0].astype(jnp.int32)
    T, B, W = ids.shape

    def back(beam_ptr, xs):
        id_t, par_t = xs  # [B, W] each (walked in reverse time)
        tok = jnp.take_along_axis(id_t, beam_ptr, axis=1)
        beam_ptr = jnp.take_along_axis(par_t, beam_ptr, axis=1)
        return beam_ptr, tok

    ptr0 = jnp.broadcast_to(jnp.arange(W)[None, :], (B, W))
    _, toks = jax.lax.scan(back, ptr0, (ids, parents), reverse=True)
    seqs = jnp.transpose(toks, (1, 2, 0))  # [B, W, T]
    final_scores = jnp.transpose(scores[-1], (0, 1))
    return {"SentenceIds": [seqs], "SentenceScores": [final_scores]}


# ---------------------------------------------------------------------------
# chunk evaluation (NER-style chunk F1, chunk_eval_op.cc)
# ---------------------------------------------------------------------------


_SCHEME_NUM_TAG_TYPES = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}


def _chunk_bounds(tags, num_types, lens, scheme, excluded):
    """Chunk begin/end masks for the reference's four tag schemes
    (chunk_eval_op.cc): tag = chunk_type * num_tag_types + tag_type with
    tag_type layouts plain:{}, IOB:{B,I}, IOE:{I,E}, IOBES:{B,I,E,S}.
    Tags with type >= num_types (or in excluded_chunk_types) are outside."""
    B_, T = tags.shape
    ntt = _SCHEME_NUM_TAG_TYPES[scheme]
    typ = tags // ntt
    pos = tags % ntt
    inside = ((tags >= 0) & (typ < num_types)
              & (jnp.arange(T)[None, :] < lens[:, None]))
    for ex in excluded:
        inside = inside & (typ != ex)

    def shift_prev(a, fill):
        return jnp.concatenate(
            [jnp.full((B_, 1), fill, a.dtype), a[:, :-1]], axis=1)

    def shift_next(a, fill):
        return jnp.concatenate(
            [a[:, 1:], jnp.full((B_, 1), fill, a.dtype)], axis=1)

    prev_typ = shift_prev(typ, -1)
    next_typ = shift_next(typ, -1)
    prev_inside = shift_prev(inside, False)
    next_inside = shift_next(inside, False)
    new_run = ~prev_inside | (typ != prev_typ)      # type/coverage break
    run_ends = ~next_inside | (typ != next_typ)

    if scheme == "plain":
        begins = inside
        ends = inside
    elif scheme == "IOB":
        is_b = pos == 0
        begins = inside & (is_b | new_run)
        next_is_b = shift_next(is_b & inside, False)
        ends = inside & (next_is_b | run_ends)
    elif scheme == "IOE":
        is_e = pos == 1
        prev_is_e = shift_prev(is_e & inside, False)
        begins = inside & (prev_is_e | new_run)
        ends = inside & (is_e | run_ends)
    else:  # IOBES
        is_b, is_e, is_s = pos == 0, pos == 2, pos == 3
        prev_closed = shift_prev((is_e | is_s) & inside, False)
        next_opens = shift_next((is_b | is_s) & inside, False)
        begins = inside & (is_b | is_s | prev_closed | new_run)
        ends = inside & (is_e | is_s | next_opens | run_ends)
    return begins, ends, typ, inside


@register("chunk_eval", differentiable=False,
          nondiff_inputs=("Inference", "Label", "SeqLength"))
def _chunk_eval(ctx, ins, attrs):
    """A label chunk [s, e] counts as correct when inference tags equal label
    tags on [s, e], inference also begins a chunk at s, and also ends one at
    e — exactly the boundary+type match of the reference."""
    inf = ins["Inference"][0].astype(jnp.int32)
    lab = ins["Label"][0].astype(jnp.int32)
    if inf.ndim == 3:
        inf, lab = inf[..., 0], lab[..., 0]
    B, T = inf.shape
    lens = _lengths(ins, B, T, "SeqLength")
    num_types = attrs.get("num_chunk_types", 1)
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = tuple(attrs.get("excluded_chunk_types", []) or [])

    ib, ie, it, ii = _chunk_bounds(inf, num_types, lens, scheme, excluded)
    lb, le, lt, li = _chunk_bounds(lab, num_types, lens, scheme, excluded)
    num_inf = jnp.sum(ib.astype(jnp.int64))
    num_lab = jnp.sum(lb.astype(jnp.int64))

    # running flag: inside the current label chunk, tags have agreed since a
    # joint begin
    eq = (inf == lab)

    def prop(ok, xs):
        eq_t, lb_t, ib_t = xs
        ok = jnp.where(lb_t, eq_t & ib_t, ok & eq_t)
        return ok, ok

    _, run = jax.lax.scan(prop, jnp.zeros((B,), bool),
                          (jnp.swapaxes(eq, 0, 1), jnp.swapaxes(lb, 0, 1),
                           jnp.swapaxes(ib, 0, 1)))
    ok = jnp.swapaxes(run, 0, 1)
    correct = jnp.sum((le & ie & ok).astype(jnp.int64))

    prec = correct / jnp.maximum(num_inf, 1)
    rec = correct / jnp.maximum(num_lab, 1)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    z = lambda v: jnp.asarray([v])
    return {"Precision": [z(prec.astype(jnp.float32))],
            "Recall": [z(rec.astype(jnp.float32))],
            "F1-Score": [z(f1.astype(jnp.float32))],
            "NumInferChunks": [z(num_inf)],
            "NumLabelChunks": [z(num_lab)],
            "NumCorrectChunks": [z(correct)]}


# ---------------------------------------------------------------------------
# py_func: host-python escape hatch (py_func_op.cc)
# ---------------------------------------------------------------------------

_PYFUNC_TABLE = []


def register_py_func(fn):
    _PYFUNC_TABLE.append(fn)
    return len(_PYFUNC_TABLE) - 1


@register("py_func", differentiable=False)
def _py_func(ctx, ins, attrs):
    fn = _PYFUNC_TABLE[attrs["func_id"]]
    xs = ins.get("X", [])
    shapes = attrs["out_shapes"]
    dtypes = attrs["out_dtypes"]
    shape_dtypes = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                    for s, d in zip(shapes, dtypes)]

    def host_fn(*arrays):
        out = fn(*arrays)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(np.asarray(o, dtype=sd.dtype).reshape(sd.shape)
                     for o, sd in zip(out, shape_dtypes))

    outs = jax.pure_callback(host_fn, tuple(shape_dtypes), *xs)
    return {"Out": list(outs)}


# ---------------------------------------------------------------------------
# distributed lookup table (host-offloaded embedding; P6/P7 parity —
# operators/distributed/parameter_prefetch.cc + fleet_wrapper.h pull/push)
# ---------------------------------------------------------------------------


@register("lookup_table_host", nondiff_inputs=("Ids",))
def _lookup_table_host(ctx, ins, attrs):
    from ..parallel.host_embedding import host_embedding_lookup

    ids = ins["Ids"][0]
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    anchor = ins["Anchor"][0].reshape(())
    out = host_embedding_lookup(attrs["table_name"], ids, anchor)
    return {"Out": [out]}


@register("lookup_table_prefetched",
          nondiff_inputs=("Ids", "Rows", "Inv", "Hit", "Slot", "Cache"))
def _lookup_table_prefetched(ctx, ins, attrs):
    """Prefetch fast path of lookup_table_host (docs/RECOMMENDER.md):
    the embed_prefetch_rewrite pass rewires the lookup to read the
    [n, dim] unique-row buffer + inverse indices the
    HostEmbeddingPrefetcher staged a step ahead (and, with the hot-row
    cache armed, the Hit/Slot/Cache feeds) — no host callback in the
    forward. The backward still pushes through the table's optimizer,
    so post-push state is bitwise the synchronous op's. Only Anchor is
    differentiable: the staged buffers are constants for one step."""
    from ..parallel.host_embedding import prefetched_embedding_lookup

    ids = ins["Ids"][0]
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    anchor = ins["Anchor"][0].reshape(())
    rows = ins["Rows"][0]
    inv = ins["Inv"][0]
    hit = ins["Hit"][0] if ins.get("Hit") else None
    slot = ins["Slot"][0] if ins.get("Slot") else None
    cache = ins["Cache"][0] if ins.get("Cache") else None
    out = prefetched_embedding_lookup(attrs["table_name"], ids, anchor,
                                      rows, inv, hit, slot, cache)
    return {"Out": [out]}


@register("switch_moe", nondiff_inputs=())
def _switch_moe(ctx, ins, attrs):
    """Top-1 switch mixture-of-experts FFN (beyond-reference, SURVEY §5.7
    expert-parallel axis; same math as parallel/transformer._moe_block but
    as a single-program kernel — under the sharding planner the expert
    weights carry P("dp", ...) specs and GSPMD inserts the token
    all-to-all the shard_map version writes by hand).

    X [B, T, D], Router [D, E], W1 [E, D, F], W2 [E, F, D] -> Out
    [B, T, D], AuxLoss [] (switch load-balance loss, fp32)."""
    x = ins["X"][0]
    router = ins["Router"][0]
    w1, w2 = ins["W1"][0], ins["W2"][0]
    cap_factor = float(attrs.get("capacity_factor", 1.25))
    dtype = x.dtype
    B, T, D = x.shape
    E = router.shape[1]
    N = B * T
    xt = x.reshape(N, D)

    gates = jax.nn.softmax(jnp.einsum(
        "nd,de->ne", xt.astype(jnp.float32), router.astype(jnp.float32)))
    expert = jnp.argmax(gates, axis=-1)
    gate = jnp.take_along_axis(gates, expert[:, None], axis=-1)[:, 0]

    cap = int(cap_factor * N / E) + 1
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    pos1 = pos.max(axis=-1)
    keep = pos1 < cap
    idx_e = jnp.where(keep, expert, 0)
    idx_c = jnp.where(keep, pos1, 0)
    disp = jnp.zeros((E, cap, D), dtype).at[idx_e, idx_c].add(
        jnp.where(keep[:, None], xt, 0).astype(dtype))

    a = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", disp, w1.astype(dtype)))
    out = jnp.einsum("ecf,efd->ecd", a, w2.astype(dtype))

    y = out[idx_e, idx_c]
    y = jnp.where(keep[:, None], y, 0).astype(jnp.float32) * gate[:, None]
    y = (xt + y.astype(dtype)).reshape(B, T, D)

    # switch aux loss: E * Σ_e fraction_e * mean_gate_e
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_gate = jnp.mean(gates, axis=0)
    aux = E * jnp.sum(frac * mean_gate)
    return {"Out": [y], "AuxLoss": [aux]}
