"""Kernel dispatch registry — the ONE decision point between a tuned
Pallas kernel and its lax fallback (docs/KERNELS.md).

The reference framework's performance story is a hand-tuned CUDA kernel
per hot op behind op-level `use_cudnn`-style switches; the TPU-native
analogue here is a *registry*: each kernel declares its qualification
predicate (the shape/platform conditions under which its tiling is
profitable and correct) and its default platform policy, and every
dispatch site asks :func:`choose` instead of carrying an ad-hoc shape
check (the `use_pallas` gate `compat_ops.py` used to hard-code — which
silently dropped the tuned path for cross-attention shapes and never
told anyone why).

Dispatch contract (trace time — decisions are static per compiled step):

  ``PTPU_KERNELS`` unset   each kernel's own default policy decides:
                           `flash_attention` runs everywhere (interpret
                           mode off-TPU, its historical behavior); the
                           serving/quant kernels (`paged_decode`,
                           `spec_window`, `int8_matmul`) engage on TPU
                           only, so non-TPU platforms reproduce pre-
                           kernel numerics bitwise.
  ``PTPU_KERNELS=1``       every registered kernel forced on (interpret
                           mode off-TPU) — the CI/test spelling.
  ``PTPU_KERNELS=0``       every dispatch takes its lax fallback,
                           bitwise.
  ``PTPU_KERNELS_DISABLE`` comma-separated kernel names pinned to their
                           fallback regardless of the mode.

A dispatch that qualifies increments ``kernels/dispatches`` and
``kernels/kernel:<name>``; one that falls back (mode off, platform
policy, disabled, or shape disqualified) increments
``kernels/fallbacks``. A *shape* disqualification additionally warns
once per (kernel, reason) — the DeferredWarns discipline: the first
trace that loses the tuned path says why, steady state stays silent.

Flipping the mode must never reuse a step compiled under the other
policy: :func:`cache_key` rides the compile-cache pipeline key and the
serving step caches.
"""

import warnings

from .. import flags as _flags
from ..observability import metrics as _metrics

__all__ = ["KernelSpec", "register_kernel", "get_kernel",
           "registered_kernels", "choose", "dispatch", "enabled_for",
           "kernels_mode", "cache_key"]


class KernelSpec:
    """One registered kernel: the tuned Pallas implementation, its lax
    fallback, the shape-qualification predicate, and the default
    platform policy used when ``PTPU_KERNELS`` is unset.

    ``qualify(...)`` receives the same arguments the implementations
    take (or the cheap shape proxies a site passes to :func:`choose`)
    and returns ``(ok, reason)`` — `reason` is the human-readable
    disqualification (warned once per kernel+reason) or None.
    ``default_on()`` returns whether the kernel engages under the unset
    (auto) mode on the current platform."""

    __slots__ = ("name", "pallas", "fallback", "_qualify", "_default_on",
                 "doc")

    def __init__(self, name, pallas, fallback, qualify, default_on, doc):
        self.name = name
        self.pallas = pallas
        self.fallback = fallback
        self._qualify = qualify
        self._default_on = default_on
        self.doc = doc

    def qualify(self, *args, **kw):
        if self._qualify is None:
            return True, None
        return self._qualify(*args, **kw)

    def default_on(self):
        if self._default_on is None:
            return True
        return bool(self._default_on())


_REGISTRY = {}
# (kernel name, reason) pairs already warned about — qualification
# failures report once per distinct cause, not once per trace
_WARNED = set()


def register_kernel(name, pallas, fallback, qualify=None, default_on=None,
                    doc=""):
    """Register (or replace) one kernel spec. Returns the spec."""
    spec = KernelSpec(str(name), pallas, fallback, qualify, default_on,
                      doc)
    _REGISTRY[spec.name] = spec
    return spec


def get_kernel(name):
    spec = _REGISTRY.get(name)
    if spec is None:
        # the kernel library registers on import; dispatch sites that
        # reach the registry first (serving, compile passes) trigger it
        from . import pallas_kernels  # noqa: F401  (registers kernels)

        spec = _REGISTRY.get(name)
    if spec is None:
        raise KeyError(
            "unknown kernel %r — registered: %s"
            % (name, sorted(_REGISTRY)))
    return spec


def registered_kernels():
    """{name: KernelSpec} snapshot (docs/KERNELS.md's source of truth)."""
    return dict(_REGISTRY)


def kernels_mode():
    """'force' | 'off' | 'auto' from PTPU_KERNELS (tri-state bool)."""
    val = _flags.env("PTPU_KERNELS")
    if val is True:
        return "force"
    if val is False:
        return "off"
    return "auto"


def _disabled():
    raw = _flags.env("PTPU_KERNELS_DISABLE")
    if not raw:
        return frozenset()
    return frozenset(s.strip() for s in raw.split(",") if s.strip())


def cache_key():
    """Compile-cache key component covering the dispatch policy: steps
    compiled under one kernel mode must not serve another. The default
    state stringifies to 'auto' (callers omit it then, keeping pre-
    kernel cache keys bitwise)."""
    mode = kernels_mode()
    dis = _disabled()
    return mode if not dis else mode + ":-" + ",".join(sorted(dis))


def enabled_for(name):
    """Mode+platform decision WITHOUT shape qualification — for compile
    passes that must decide what to *emit* before trace-time shapes
    exist (quant_rewrite's fused-matmul emission). No telemetry: the
    trace-time :func:`choose` on the emitted op is the counted event."""
    spec = get_kernel(name)
    mode = kernels_mode()
    if mode == "off" or name in _disabled():
        return False
    if mode == "force":
        return True
    return spec.default_on()


def choose(name, *args, **kwargs):
    """The dispatch decision for one kernel launch site (trace time):
    True -> call the Pallas kernel, False -> the lax fallback. The
    arguments feed the spec's qualification predicate. Counts
    ``kernels/{dispatches,fallbacks}`` (+ the per-kernel counter) and
    warns once per (kernel, reason) when a *shape* disqualifies."""
    spec = get_kernel(name)
    if not enabled_for(name):
        _metrics.counter("kernels/fallbacks").inc()
        return False
    ok, reason = spec.qualify(*args, **kwargs)
    if not ok:
        _metrics.counter("kernels/fallbacks").inc()
        key = (name, reason)
        if key not in _WARNED:
            _WARNED.add(key)
            warnings.warn(
                "kernel %r disqualified (%s): taking the lax fallback "
                "for this shape (docs/KERNELS.md)" % (name, reason),
                RuntimeWarning)
        return False
    _metrics.counter("kernels/dispatches").inc()
    _metrics.counter("kernels/kernel:" + name).inc()
    return True


def dispatch(name, *args, **kwargs):
    """choose() + call: runs the Pallas kernel when the site qualifies
    (passing the SAME arguments to the qualification predicate), the
    lax fallback otherwise. Sites whose qualification wants cheap shape
    proxies instead of full operands call :func:`choose` themselves and
    invoke the chosen implementation directly."""
    spec = get_kernel(name)
    if choose(name, *args, **kwargs):
        return spec.pallas(*args, **kwargs)
    return spec.fallback(*args, **kwargs)
