"""Broadcasted elementwise ops (parity: operators/elementwise/, 31 files —
elementwise_{add,sub,mul,div,min,max,mod,floordiv,pow}_op.cc with Fluid's
`axis` broadcasting convention).

These all fuse into neighbors under XLA, so each is a plain jnp expression.
"""

import jax.numpy as jnp

from .registry import register, broadcast_to_axis


def _binary(name, fn, differentiable=True):
    def impl(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        y = broadcast_to_axis(y, x.ndim, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}

    register(name, differentiable=differentiable)(impl)


_binary("elementwise_add", lambda x, y: x + y)
_binary("elementwise_sub", lambda x, y: x - y)
_binary("elementwise_mul", lambda x, y: x * y)
_binary("elementwise_div", lambda x, y: x / y)
_binary("elementwise_max", jnp.maximum)
_binary("elementwise_min", jnp.minimum)
_binary("elementwise_pow", lambda x, y: x**y)
_binary("elementwise_mod", lambda x, y: jnp.mod(x, y), differentiable=False)
_binary("elementwise_floordiv", lambda x, y: jnp.floor_divide(x, y),
        differentiable=False)


def _compare(name, fn):
    def impl(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        y = broadcast_to_axis(y, x.ndim, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}

    register(name, differentiable=False)(impl)


_compare("equal", lambda x, y: x == y)
_compare("not_equal", lambda x, y: x != y)
_compare("less_than", lambda x, y: x < y)
_compare("less_equal", lambda x, y: x <= y)
_compare("greater_than", lambda x, y: x > y)
_compare("greater_equal", lambda x, y: x >= y)


def _logical(name, fn, unary=False):
    def impl(ctx, ins, attrs):
        x = ins["X"][0]
        if unary:
            return {"Out": [fn(x)]}
        return {"Out": [fn(x, ins["Y"][0])]}

    register(name, differentiable=False)(impl)


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, unary=True)
