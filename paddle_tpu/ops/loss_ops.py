"""Loss ops (parity: SURVEY Appendix A "Losses" — operators/{cross_entropy_op,
softmax_with_cross_entropy_op,sigmoid_cross_entropy_with_logits_op,huber_loss,
hinge_loss,log_loss,rank_loss,margin_rank_loss,smooth_l1_loss,kldiv_loss,
bpr_loss,npair_loss,...}.cc).
"""

import functools

import jax
import jax.numpy as jnp

from ..core.jax_compat import optimization_barrier
from .registry import register


def _take_label_prob(x, label):
    """Pick prob of the label class: x [N, C], label [N, 1] int or [N, C] soft."""
    if jnp.issubdtype(label.dtype, jnp.integer):
        lab = label.reshape((-1,))
        return jnp.take_along_axis(x, lab[:, None], axis=1)
    return None


@register("cross_entropy", nondiff_inputs=("Label",))
def _cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    eps = 1e-12
    if soft:
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        p = _take_label_prob(x, label)
        loss = -jnp.log(jnp.maximum(p, eps))
        lab = label.reshape((-1, 1))
        loss = jnp.where(lab == ignore_index, 0.0, loss)
    return {"Y": [loss]}


@register("cross_entropy2", nondiff_inputs=("Label",))
def _cross_entropy2(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    p = _take_label_prob(x, label)
    loss = -jnp.log(jnp.maximum(p, 1e-12))
    return {"Y": [loss], "MatchX": [p], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _hard_label_ce(logits, lab, ignore_index):
    """Mean-free per-position CE with a memory-lean vjp: residuals are the
    LOGITS themselves (bf16 under AMP), not the fp32 log-softmax — for an
    LM head that is the difference between pinning 8G and 4G in HBM.
    Backward recomputes softmax from logits (elementwise + one reduction:
    the cheap kind of remat, matching what XLA's own rematerializer picks
    for the native-path head)."""
    loss, _ = _hard_label_ce_fwd(logits, lab, ignore_index)
    return loss


def _hard_label_ce_fwd(logits, lab, ignore_index):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                 axis=-1)
    loss = jnp.where(lab[..., None] == ignore_index, 0.0, -picked)
    return loss, (logits, lab)


def _hard_label_ce_bwd(ignore_index, res, g):
    logits, lab = res
    # barrier: without it XLA CSEs this upcast with the forward's and
    # keeps the full fp32 logits alive from forward to backward — the
    # exact buffer this custom vjp exists to avoid
    logits = optimization_barrier(logits)
    xf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(xf, axis=-1, keepdims=True)
    # dlogits in the LOGITS dtype end to end: softmax values are in [0, 1]
    # where bf16 carries ~3 digits, and keeping the whole chain low
    # precision lets XLA emit one fused elementwise pass (bf16 in, bf16
    # out) instead of materializing a full-vocab fp32 intermediate
    sm = jnp.exp(xf - lse).astype(logits.dtype)
    onehot = jax.nn.one_hot(lab, logits.shape[-1], dtype=logits.dtype)
    gv = jnp.where(lab[..., None] != ignore_index, g, 0.0)
    dlogits = (sm - onehot) * gv.astype(logits.dtype)
    return dlogits, None


_hard_label_ce.defvjp(_hard_label_ce_fwd, _hard_label_ce_bwd)


@register("softmax_with_cross_entropy", nondiff_inputs=("Label",))
def _softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    soft = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    axis = attrs.get("axis", -1)
    need_softmax = attrs.get("__need_softmax__", True)
    if not soft and axis in (-1, logits.ndim - 1):
        lab = label
        if lab.shape and lab.shape[-1] == 1:
            lab = lab.reshape(lab.shape[:-1])
        loss = _hard_label_ce(logits, lab, ignore_index)
        # Loss stays fp32 even for bf16 logits (black-list AMP
        # semantics): downstream sums over ~1e5 per-token losses would
        # lose ~3 digits in bf16
        if not need_softmax:
            # skipping the discarded side output saves a full fp32
            # [.., vocab] HBM round-trip per step on LM heads
            return {"Loss": [loss]}
        softmax = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return {"Softmax": [softmax.astype(logits.dtype)], "Loss": [loss]}
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
        if not need_softmax:
            return {"Loss": [loss]}
    else:
        lab = label
        ax = axis % logits.ndim
        # hard label carries its singleton class dim at `axis` (reference
        # layout, softmax_with_cross_entropy_op.cc) — move it last to align
        # with the moveaxis'd logp before take_along_axis
        if lab.ndim == logits.ndim and lab.shape[ax] == 1:
            lab = jnp.squeeze(jnp.moveaxis(lab, ax, -1), -1)
        picked = jnp.take_along_axis(
            jnp.moveaxis(logp, ax, -1),
            lab[..., None].astype(jnp.int32), axis=-1)
        loss = jnp.where(lab[..., None] == ignore_index, 0.0, -picked)
        loss = jnp.moveaxis(loss, -1, ax)
        if not need_softmax:
            return {"Loss": [loss]}
    softmax = jnp.exp(logp)
    return {"Softmax": [softmax.astype(logits.dtype)], "Loss": [loss]}


@register("sigmoid_cross_entropy_with_logits", nondiff_inputs=("Label",))
def _sigmoid_ce(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    ignore_index = attrs.get("ignore_index", -100)
    loss = jnp.maximum(x, 0.0) - x * label + jax.nn.softplus(-jnp.abs(x))
    loss = jnp.where(label == ignore_index, 0.0, loss)
    if attrs.get("normalize", False):
        n_valid = jnp.maximum(jnp.sum((label != ignore_index).astype(x.dtype)), 1.0)
        loss = loss * (loss.size / n_valid)
    return {"Out": [loss]}


@register("bpr_loss", nondiff_inputs=("Label",))
def _bpr_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    n, c = x.shape
    pos = jnp.take_along_axis(x, label.reshape((-1, 1)).astype(jnp.int32), axis=1)
    diff = x - pos
    loss = jnp.mean(jax.nn.softplus(diff), axis=1, keepdims=True) * (c / (c - 1.0))
    return {"Y": [loss]}


@register("hinge_loss", nondiff_inputs=("Labels",))
def _hinge_loss(ctx, ins, attrs):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0)]}


@register("huber_loss", nondiff_inputs=("Y",))
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ab = jnp.abs(r)
    loss = jnp.where(ab <= delta, 0.5 * r * r, delta * (ab - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register("log_loss", nondiff_inputs=("Labels",))
def _log_loss(ctx, ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)
    return {"Loss": [loss]}


@register("rank_loss", nondiff_inputs=("Label",))
def _rank_loss(ctx, ins, attrs):
    label = ins["Label"][0]
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jax.nn.softplus(d) - label * d]}


@register("margin_rank_loss", nondiff_inputs=("Label",))
def _margin_rank_loss(ctx, ins, attrs):
    label = ins["Label"][0]
    x1, x2 = ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register("smooth_l1_loss", nondiff_inputs=("Y",))
def _smooth_l1_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    if ins.get("InsideWeight"):
        d = d * ins["InsideWeight"][0]
    ab = jnp.abs(d)
    val = jnp.where(ab < 1.0 / s2, 0.5 * s2 * d * d, ab - 0.5 / s2)
    if ins.get("OutsideWeight"):
        val = val * ins["OutsideWeight"][0]
    loss = jnp.sum(val, axis=tuple(range(1, val.ndim))).reshape((-1, 1))
    return {"Out": [loss], "Diff": [d]}


@register("kldiv_loss", nondiff_inputs=("Target",))
def _kldiv_loss(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    reduction = attrs.get("reduction", "mean")
    loss = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - x), 0.0)
    if reduction == "mean":
        out = jnp.mean(loss).reshape((1,))
    elif reduction == "sum":
        out = jnp.sum(loss).reshape((1,))
    elif reduction == "batchmean":
        out = (jnp.sum(loss) / x.shape[0]).reshape((1,))
    else:
        out = loss
    return {"Loss": [out]}


@register("mse_loss", nondiff_inputs=())
def _mse_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [(x - y) ** 2]}


@register("npair_loss", nondiff_inputs=("Labels",))
def _npair_loss(ctx, ins, attrs):
    anchor, positive = ins["Anchor"][0], ins["Positive"][0]
    labels = ins["Labels"][0].reshape((-1,))
    l2_reg = attrs.get("l2_reg", 0.002)
    sim = anchor @ positive.T
    eq = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.sum(tgt * logp, axis=1).mean()
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, 1))
                    + jnp.mean(jnp.sum(positive * positive, 1))) * 0.25
    return {"Out": [(ce + reg).reshape((1,))]}


@register("teacher_student_sigmoid_loss", nondiff_inputs=("Label",))
def _ts_sigmoid_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft_max_up = attrs.get("soft_max_up_bound", 15.0)
    soft_max_lo = attrs.get("soft_max_lower_bound", -15.0)
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    teacher = jnp.where(label > 0.0, label, 0.0)
    student = (label > -1.0).astype(x.dtype)
    loss = jax.nn.softplus(z) - z * student + jax.nn.softplus(z) - z * teacher
    return {"Y": [loss]}


@register("dice_loss_helper")
def _dice_loss_helper(ctx, ins, attrs):
    # dice loss is composed in layers; helper kept for completeness
    x, label = ins["X"][0], ins["Label"][0]
    eps = attrs.get("epsilon", 1e-5)
    inter = jnp.sum(x * label, axis=tuple(range(1, x.ndim)))
    union = jnp.sum(x + label, axis=tuple(range(1, x.ndim)))
    return {"Out": [1.0 - (2.0 * inter + eps) / (union + eps)]}
