"""Core math / tensor-creation ops.

Parity targets (SURVEY §2.2 / Appendix A "Core math" group):
operators/{matmul_op,mul_op,scale_op,sum_op,cast_op,fill_constant_op,
uniform_random_op,gaussian_random_op,truncated_gaussian_random_op,clip_op,
cumsum_op,sign_op,...}.cc — re-expressed as jax lowerings (MXU-friendly:
matmuls stay single large dots so XLA tiles them onto the systolic array).
"""

import jax
import jax.numpy as jnp
import numpy as np

from . import registry
from .registry import register, simple_op, np_dtype


# -- creation ----------------------------------------------------------------


@register("fill_constant", differentiable=False)
def _fill_constant(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = np_dtype(attrs.get("dtype", "float32"))
    # numpy, not jnp: stays a trace-time CONSTANT under jit (omnistaging
    # would stage jnp.full into the graph), so downstream consumers that
    # need concrete values — TensorArray indices, shape args — still work;
    # XLA folds it identically either way
    return {"Out": [np.full(shape, attrs.get("value", 0.0), dtype=dt)]}


@register("fill_constant_batch_size_like", differentiable=False)
def _fill_constant_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = [int(s) for s in attrs["shape"]]
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dt)]}


@register("fill_zeros_like", differentiable=False)
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register("fill_any_like", differentiable=False)
def _fill_any_like(ctx, ins, attrs):
    x = ins["X"][0]
    val = attrs.get("value", 0.0)
    if attrs.get("__loss_seed__"):
        # BuildStrategy.GradientScaleStrategy hook: the backward seed
        # d loss/d loss scales by num-devices under `One` (reference
        # ScaleLossGradOpHandle semantics, details/scale_loss_grad_op_handle.cc)
        val = val * getattr(ctx, "grad_seed_scale", 1.0)
    return {"Out": [jnp.full_like(x, val)]}


@register("uniform_random", differentiable=False, stateful=True)
def _uniform_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = np_dtype(attrs.get("dtype", "float32"))
    key = ctx.rng(attrs)
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    return {"Out": [jax.random.uniform(key, shape, jnp.float32, lo, hi).astype(dt)]}


@register("gaussian_random", differentiable=False, stateful=True)
def _gaussian_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = np_dtype(attrs.get("dtype", "float32"))
    key = ctx.rng(attrs)
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": [(jax.random.normal(key, shape) * std + mean).astype(dt)]}


@register("truncated_gaussian_random", differentiable=False, stateful=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = np_dtype(attrs.get("dtype", "float32"))
    key = ctx.rng(attrs)
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    x = jax.random.truncated_normal(key, -2.0, 2.0, shape) * std + mean
    return {"Out": [x.astype(dt)]}


@register("assign")
def _assign(ctx, ins, attrs):
    if ins.get("X"):
        return {"Out": [ins["X"][0]]}
    v = np.asarray(attrs["value"], dtype=attrs.get("dtype", "float32"))
    return {"Out": [jnp.asarray(v)]}


@register("assign_value", differentiable=False)
def _assign_value(ctx, ins, attrs):
    # returned as a host numpy array (the fill_constant convention):
    # jnp.asarray under an active trace stages a device_put and the
    # value becomes a Tracer, breaking consumers that need a trace-time
    # concrete value (tensor-array indices, static bounds)
    dt = np_dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["values"], dtype=dt).reshape(attrs["shape"])
    return {"Out": [vals]}


@simple_op("shape", differentiable=False)
def _shape(ctx, x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register("range", differentiable=False)
def _range(ctx, ins, attrs):
    start = ins["Start"][0].reshape(())
    end = ins["End"][0].reshape(())
    step = ins["Step"][0].reshape(())
    # XLA needs static sizes: range bounds must be build-time constants, so
    # the layer stores them as attrs too when known.
    n = attrs["__static_len__"]
    out = start + step * jnp.arange(n, dtype=start.dtype)
    return {"Out": [out]}


@register("linspace", differentiable=False)
def _linspace(ctx, ins, attrs):
    start = ins["Start"][0].reshape(())
    stop = ins["Stop"][0].reshape(())
    num = int(attrs["__static_num__"])
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.linspace(start, stop, num).astype(dt)]}


# -- linear algebra ----------------------------------------------------------


def _int8_dot(x, y):
    """quant_rewrite-marked matmul/mul: int8 operands, int32 MXU
    accumulation (`preferred_element_type` — overflow-free over any K,
    and the layout XLA lowers onto the int8 systolic path). The
    per-channel dequantize back to fp32 is a separate
    `dequantize_linear` op (paddle_tpu/quant.py)."""
    return jax.lax.dot_general(
        x, y, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def _quant_int8(x, y, attrs):
    return (attrs.get("__quant_int8__")
            and jnp.issubdtype(x.dtype, jnp.integer)
            and jnp.issubdtype(y.dtype, jnp.integer))


def _amp_dot(x, y, attrs):
    """AMP white-list matmul: bf16 operands, fp32 MXU accumulation, bf16
    output (reference AMP semantics — white-list ops produce the low
    precision dtype, fp16_utils.py rewrite_program). The bf16 output
    matters twice: activations cost half the HBM, and the BACKWARD matmuls
    see bf16 cotangents — an fp32 cotangent operand would knock the grad
    dots off the MXU fast path (fp32 dots decompose into multiple bf16
    passes). Plain `@` otherwise."""
    if attrs.get("__amp_bf16__") and jnp.float32 in (x.dtype, y.dtype) \
            and x.dtype in (jnp.float32, jnp.bfloat16) \
            and y.dtype in (jnp.float32, jnp.bfloat16):
        # fp32 (or mixed) operands: cast down and emit a PLAIN bf16 dot —
        # the MXU accumulates bf16 dots in fp32 internally either way,
        # while preferred_element_type=f32 + convert would materialize a
        # full fp32 output buffer just to round it down again
        return jnp.matmul(x.astype(jnp.bfloat16), y.astype(jnp.bfloat16))
    return x @ y


@register("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if tx:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ty:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    if _quant_int8(x, y, attrs):
        return {"Out": [_int8_dot(x, y)]}
    out = _amp_dot(x, y, attrs)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register("fused_int8_matmul", differentiable=False)
def _fused_int8_matmul(ctx, ins, attrs):
    """quant_rewrite's fused full-int8 dense layer (one op instead of
    the quantize -> int8 matmul -> dequantize_linear chain): X [M, K]
    fp32 activation, Y [K, N] int8 weight, Scale [N] combined
    per-output-channel dequantize vector, attr `act_scale` the
    activation quantize scale. Dispatches the Pallas kernel through the
    registry (in-kernel activation quantize + int32 MXU accumulation +
    in-kernel dequant); the lax fallback is bitwise the unfused op
    chain, so flipping PTPU_KERNELS never moves inference numerics."""
    from .kernel_registry import dispatch as _dispatch_kernel

    x, y = ins["X"][0], ins["Y"][0]
    dq = ins["Scale"][0]
    act_scale = float(attrs["act_scale"])
    xn = attrs.get("x_num_col_dims")
    if xn is None:
        # plain 2-D matmul
        out = _dispatch_kernel("int8_matmul", x, y, dq, act_scale)
        return {"Out": [out]}
    # mul semantics: flatten exactly the way the mul op does, dot,
    # reshape back (quantize commutes with reshape — bitwise the chain)
    yn = int(attrs.get("y_num_col_dims", 1))
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), int(np.prod(xs[xn:]))))
    y2 = y.reshape((int(np.prod(ys[:yn])), int(np.prod(ys[yn:]))))
    out = _dispatch_kernel("int8_matmul", x2, y2, dq, act_scale)
    return {"Out": [out.reshape(xs[:xn] + ys[yn:])]}


@register("mul")
def _mul(ctx, ins, attrs):
    """Fluid `mul`: flatten x to 2-D at x_num_col_dims, y at y_num_col_dims,
    then matmul (operators/mul_op.cc). The FC workhorse — one big MXU dot."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), int(np.prod(xs[xn:]))))
    y2 = y.reshape((int(np.prod(ys[:yn])), int(np.prod(ys[yn:]))))
    out = _int8_dot(x2, y2) if _quant_int8(x2, y2, attrs) \
        else _amp_dot(x2, y2, attrs)
    return {"Out": [out.reshape(xs[:xn] + ys[yn:])]}


@simple_op("scale")
def _scale(ctx, x, scale=1.0, bias=0.0, bias_after_scale=True, **_):
    if bias_after_scale:
        return x * scale + jnp.asarray(bias, x.dtype)
    return (x + jnp.asarray(bias, x.dtype)) * scale


@register("sum")
def _sum(ctx, ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@simple_op("cast")
def _cast(ctx, x, out_dtype="float32", **_):
    return x.astype(np_dtype(out_dtype))


@simple_op("sign")
def _sign(ctx, x, **_):
    return jnp.sign(x)


@simple_op("clip")
def _clip(ctx, x, min=None, max=None, **_):
    return jnp.clip(x, min, max)


@register("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [(x * scale.astype(x.dtype))]}


@simple_op("cumsum")
def _cumsum(ctx, x, axis=-1, exclusive=False, reverse=False, **_):
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@simple_op("l1_norm")
def _l1_norm(ctx, x, **_):
    return jnp.sum(jnp.abs(x)).reshape((1,))


@simple_op("squared_l2_norm")
def _squared_l2_norm(ctx, x, **_):
    return jnp.sum(x * x).reshape((1,))


@register("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y.reshape((-1,) + x.shape[1:]) if y.shape[0] == 1 else x - y
    return {"Out": [jnp.sum(d * d, axis=tuple(range(1, d.ndim)), keepdims=False).reshape((-1, 1))], "sub_result": [d]}


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@simple_op("diag", differentiable=False)
def _diag(ctx, x, **_):
    return jnp.diag(x)


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    # w: [size, dx, dy]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register("dot")
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}
