"""Optimizers (parity: python/paddle/fluid/optimizer.py — base :49,
minimize :472 = backward :351 + apply_gradients :409; 15 classes §L5).

Each optimizer appends per-param update ops that the executor fuses into the
single jitted train step; accumulators are persistable vars initialized by
the startup program. On a data-parallel mesh the gradient allreduce comes
from sharding propagation (compiler.py), not from ops here.
"""

import numpy as np

from . import framework, unique_name
from .backward import append_backward
from .framework import Variable, default_main_program, default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "Lamb", "LarsMomentum", "DGCMomentum",
    "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "DecayedAdagradOptimizer", "AdadeltaOptimizer",
    "RMSPropOptimizer", "FtrlOptimizer", "LambOptimizer",
    "LarsMomentumOptimizer", "DGCMomentumOptimizer", "ModelAverage",
    "ExponentialMovingAverage", "GradientMergeOptimizer",
]


class Optimizer:
    """Base (parity: optimizer.py:49)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = {}  # {acc_name: {param_name: var}}
        self.helper = None

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self, prog=None):
        prog = prog or default_main_program()
        lr = self._learning_rate_map.get(prog)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[prog] = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        gb = prog.global_block()
        lr_var = gb.create_var(
            name=lr_name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True,
        )
        sb = default_startup_program().global_block()
        sv = sb.create_var(name=lr_name, shape=(1,), dtype="float32",
                           persistable=True)
        Constant(float(self._learning_rate))(sv, sb)
        self._learning_rate_map[prog] = lr_var

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate(param.block.program)
        mult = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return base
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="scale", inputs={"X": [base]}, outputs={"Out": [out]},
            attrs={"scale": float(mult)},
        )
        out.shape = (1,)
        return out

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        acc_name = unique_name.generate("%s_%s" % (param.name, name))
        shape = shape if shape is not None else param.shape
        dtype = dtype or "float32"
        gb = default_main_program().global_block()
        acc = gb.create_var(name=acc_name, shape=tuple(shape), dtype=dtype,
                            persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        sv = sb.create_var(name=acc_name, shape=tuple(shape), dtype=dtype,
                           persistable=True)
        Constant(float(fill_value))(sv, sb)
        self._accumulators.setdefault(name, {})[param.name] = acc
        return acc

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks ---------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- API -----------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        # ops must land in the program that owns the params — which may not
        # be the current default program (e.g. minimize() after the guard)
        if params_grads:
            prog = params_grads[0][0].block.program
        else:
            prog = default_main_program()
        block = prog.global_block()
        self.helper = LayerHelper(self.__class__.__name__)
        with framework.program_guard(prog):
            self._create_global_learning_rate(prog)

            from .clip import append_gradient_clip_ops
            from .regularizer import append_regularization_ops

            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = append_regularization_ops(params_grads,
                                                     self.regularization)

            self._create_accumulators(block, [p for p, _ in params_grads])
            start = len(block.ops)
            for pg in params_grads:
                self._append_optimize_op(block, pg)
            self._finish_update(block, params_grads)
            return list(block.ops[start:])

    def apply_optimize(self, loss, startup_program, params_grads):
        """Second half of minimize() (parity: optimizer.py apply_optimize —
        the hook subclasses/extensions override to wrap apply_gradients)."""
        return self.apply_gradients(params_grads)

    def get_opti_var_name_list(self):
        """Names of optimizer-created vars: accumulators + the global LR
        (parity: optimizer.py get_opti_var_name_list)."""
        names = []
        for acc_map in self._accumulators.values():
            names.extend(v.name for v in acc_map.values())
        names.extend(v.name for v in self._learning_rate_map.values())
        return names

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .dygraph import base as dy_base

        if dy_base.enabled():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_optimize(loss, startup_program,
                                           params_grads)
        return optimize_ops, params_grads

    # -- dygraph (eager) path ------------------------------------------------
    # Parity: the reference optimizer applies updates directly to VarBase
    # params after loss.backward() populates their gradients
    # (optimizer.py minimize under in_dygraph_mode). Accumulators live on
    # the optimizer instance, keyed by the parameter object. Updates run
    # in jnp (device-resident, no host round-trip) at fp32, cast back to
    # the parameter's own dtype. Gradient clipping (set_gradient_clip) is
    # static-graph-only in the reference too; weight decay IS applied.

    def _eager_lr(self):
        lr = self._learning_rate
        if isinstance(lr, (int, float)):
            return float(lr)
        if callable(lr):
            return float(lr())
        raise NotImplementedError(
            "%s: dygraph mode needs a numeric learning rate (got %r)"
            % (self.__class__.__name__, lr))

    def _eager_state_for(self, p):
        # keyed by the VarBase object (holds a reference — same lifetime
        # as the reference's per-param accumulator vars; id() alone could
        # be reused after gc)
        if not hasattr(self, "_eager_state"):
            self._eager_state = {}
        return self._eager_state.setdefault(p, {})

    def _eager_update(self, p, g, lr):
        raise NotImplementedError(
            "%s has no dygraph update rule" % self.__class__.__name__)

    @staticmethod
    def _eager_param_f32(p):
        import jax.numpy as jnp

        return jnp.asarray(p.value).astype(jnp.float32)

    @staticmethod
    def _eager_assign(p, new_f32):
        import jax.numpy as jnp

        p.value = new_f32.astype(jnp.asarray(p.value).dtype)

    def _eager_parameters(self):
        """Parameters seen on the tracer tape, discovered incrementally
        (the tape is append-only; rescanning it whole every step would be
        O(steps^2))."""
        from .dygraph import base as dy_base

        t = dy_base._current_tracer()
        import weakref

        if not hasattr(self, "_eager_params"):
            self._eager_params = []
            self._eager_seen = set()
            self._tape_ref = None
            self._tape_pos = 0
        # weakref to the tape, not id(): a GC'd tape's address can be
        # reused by a fresh Tape (silently skipping its entries), and a
        # strong ref would pin a whole step's activations after the
        # tracer drops the tape
        if self._tape_ref is None or self._tape_ref() is not t.tape:
            self._tape_ref = weakref.ref(t.tape)
            self._tape_pos = 0
        entries = t.tape.entries
        for _op, ins, _attrs, vouts, _ctx in entries[self._tape_pos:]:
            for vs in list(ins.values()) + list(vouts.values()):
                for v in vs:
                    if (isinstance(v, dy_base.VarBase) and v.persistable
                            and not v.stop_gradient
                            and id(v) not in self._eager_seen):
                        self._eager_seen.add(id(v))
                        self._eager_params.append(v)
        self._tape_pos = len(entries)
        return self._eager_params

    def _dygraph_minimize(self, loss, parameter_list=None):
        """Apply updates to every tracked parameter with a gradient (the
        user has already called loss.backward())."""
        import jax.numpy as jnp

        if parameter_list is None:
            parameter_list = self._eager_parameters()
        lr = self._eager_lr()
        reg = self.regularization
        params_grads = []
        for p in parameter_list:
            if getattr(p, "_grad", None) is None:
                continue
            g = jnp.asarray(p._grad).astype(jnp.float32)
            if reg is not None:
                from .regularizer import (L1DecayRegularizer,
                                          L2DecayRegularizer)

                pv = jnp.asarray(p.value).astype(jnp.float32)
                if isinstance(reg, L2DecayRegularizer):
                    g = g + reg._coeff * pv
                elif isinstance(reg, L1DecayRegularizer):
                    g = g + reg._coeff * jnp.sign(pv)
            self._eager_update(p, g, lr)
            params_grads.append((p, p._grad))
        return [], params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p]},
        )

    def _eager_update(self, p, g, lr):
        self._eager_assign(p, self._eager_param_f32(p) - lr * g)


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )

    def _eager_update(self, p, g, lr):
        st = self._eager_state_for(p)
        v = st.get("velocity")
        v = g if v is None else self._momentum * v + g
        st["velocity"] = v
        step = (g + self._momentum * v) if self._use_nesterov else v
        self._eager_assign(p, self._eager_param_f32(p) - lr * step)


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op(
            type="lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """API parity for DGC (P9). Dense momentum update here; the sparse top-k
    compressed allreduce engages in data-parallel compilation (parallel/dgc)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        super().__init__(learning_rate, momentum, use_nesterov,
                         regularization, name)
        self.type = "dgc_momentum"
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = sparsity


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon},
        )

    def _eager_update(self, p, g, lr):
        import jax.numpy as jnp

        st = self._eager_state_for(p)
        m = st.get("moment", jnp.full_like(g, self._initial)) + g * g
        st["moment"] = m
        self._eager_assign(
            p, self._eager_param_f32(p)
            - lr * g / (jnp.sqrt(m) + self._epsilon))


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        block.append_op(
            type="adam",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _eager_update(self, p, g, lr):
        import jax.numpy as jnp

        st = self._eager_state_for(p)
        m = st.get("m", jnp.zeros_like(g))
        v = st.get("v", jnp.zeros_like(g))
        b1p = st.get("b1p", 1.0) * self._beta1
        b2p = st.get("b2p", 1.0) * self._beta2
        m = self._beta1 * m + (1.0 - self._beta1) * g
        v = self._beta2 * v + (1.0 - self._beta2) * g * g
        st.update(m=m, v=v, b1p=b1p, b2p=b2p)
        lr_t = lr * float(np.sqrt(1.0 - b2p) / (1.0 - b1p))
        self._eager_assign(
            p, self._eager_param_f32(p)
            - lr_t * m / (jnp.sqrt(v) + self._epsilon))


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            type="adamax",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("moment", p)],
                     "InfNormOut": [self._get_accumulator("inf_norm", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _finish_update(self, block, parameters_and_grads):
        for p, _ in parameters_and_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                type="scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
                attrs={"scale": self._beta1},
            )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("_avg_squared_grad", p)
        asu = self._get_accumulator("_avg_squared_update", p)
        block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            type="rmsprop",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("momentum", p)],
                    "MeanSquare": [self._get_accumulator("mean_square", p)],
                    "MeanGrad": [self._get_accumulator("mean_grad", p)],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("momentum", p)],
                     "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                     "MeanGradOut": [self._get_accumulator("mean_grad", p)]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )

    def _eager_update(self, p, g, lr):
        import jax.numpy as jnp

        st = self._eager_state_for(p)
        ms = st.get("mean_square", jnp.zeros_like(g))
        mg = st.get("mean_grad", jnp.zeros_like(g))
        mom = st.get("moment", jnp.zeros_like(g))
        ms = self._rho * ms + (1.0 - self._rho) * g * g
        if self._centered:
            mg = self._rho * mg + (1.0 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * mom + lr * g / denom
        st.update(mean_square=ms, mean_grad=mg, moment=mom)
        self._eager_assign(p, self._eager_param_f32(p) - mom)


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            type="ftrl",
            inputs={"Param": [p], "Grad": [g],
                    "SquaredAccumulator": [self._get_accumulator("squared", p)],
                    "LinearAccumulator": [self._get_accumulator("linear", p)],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p],
                     "SquaredAccumOut": [self._get_accumulator("squared", p)],
                     "LinearAccumOut": [self._get_accumulator("linear", p)]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         regularization, name)
        self.type = "lamb"
        self._weight_decay = lamb_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            type="lamb",
            inputs={"Param": [p], "Grad": [g],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay},
        )


class ModelAverage(Optimizer):
    """Parameter averaging over a sliding window (parity: optimizer.py:2002).
    apply()/restore() swap averaged params in and out of the scope."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        prog = default_main_program()
        for p in prog.global_block().all_parameters():
            if p.trainable:
                self.params_grads.append((p, None))
        self.helper = LayerHelper("model_average")
        self._create_accumulators(prog.global_block(),
                                  [p for p, _ in self.params_grads])
        for pg in self.params_grads:
            self._append_optimize_op(prog.global_block(), pg)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("sum_1", p)
            self._add_accumulator("sum_2", p)
            self._add_accumulator("sum_3", p)
            self._add_accumulator("num_accumulates", p, dtype="int64",
                                  fill_value=0, shape=[1])
            self._add_accumulator("old_num_accumulates", p, dtype="int64",
                                  fill_value=0, shape=[1])
            self._add_accumulator("num_updates", p, dtype="int64",
                                  fill_value=0, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, _ = param_and_grad
        block.append_op(
            type="average_accumulates",
            inputs={
                "param": [p],
                "in_sum_1": [self._get_accumulator("sum_1", p)],
                "in_sum_2": [self._get_accumulator("sum_2", p)],
                "in_sum_3": [self._get_accumulator("sum_3", p)],
                "in_num_accumulates": [self._get_accumulator("num_accumulates", p)],
                "in_old_num_accumulates": [self._get_accumulator("old_num_accumulates", p)],
                "in_num_updates": [self._get_accumulator("num_updates", p)],
            },
            outputs={
                "out_sum_1": [self._get_accumulator("sum_1", p)],
                "out_sum_2": [self._get_accumulator("sum_2", p)],
                "out_sum_3": [self._get_accumulator("sum_3", p)],
                "out_num_accumulates": [self._get_accumulator("num_accumulates", p)],
                "out_old_num_accumulates": [self._get_accumulator("old_num_accumulates", p)],
                "out_num_updates": [self._get_accumulator("num_updates", p)],
            },
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window},
        )

    def _param_backup_name(self, p):
        return p.name + "@MODEL_AVG_BACKUP"

    def apply(self, executor, need_restore=True):
        """Swap averaged values into the params in the current scope."""
        from .core.scope import global_scope

        scope = global_scope()
        for p, _ in self.params_grads:
            s1 = np.asarray(scope.get(self._get_accumulator("sum_1", p).name))
            s2 = np.asarray(scope.get(self._get_accumulator("sum_2", p).name))
            s3 = np.asarray(scope.get(self._get_accumulator("sum_3", p).name))
            na = int(np.asarray(scope.get(self._get_accumulator("num_accumulates", p).name)).reshape(()))
            ona = int(np.asarray(scope.get(self._get_accumulator("old_num_accumulates", p).name)).reshape(()))
            total = max(na + ona, 1)
            if need_restore:
                scope.set(self._param_backup_name(p),
                          np.asarray(scope.get(p.name)))
            scope.set(p.name, ((s1 + s2 + s3) / total).astype(
                np.asarray(scope.get(p.name)).dtype))

    def restore(self, executor):
        from .core.scope import global_scope

        scope = global_scope()
        for p, _ in self.params_grads:
            backup = scope.get(self._param_backup_name(p))
            if backup is not None:
                scope.set(p.name, backup)


class ExponentialMovingAverage:
    """EMA of params (parity: optimizer.py:2161). update() is appended to the
    train program; apply()/restore() swap shadow params at eval time."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._shadows = {}
        prog = default_main_program()
        block = prog.global_block()
        helper = LayerHelper("ema")
        self._helper = helper
        for p in block.all_parameters():
            if p.trainable:
                shadow_name = p.name + ".ema"
                shadow = block.create_var(name=shadow_name, shape=p.shape,
                                          dtype=p.dtype, persistable=True,
                                          stop_gradient=True)
                sb = default_startup_program().global_block()
                sv = sb.create_var(name=shadow_name, shape=p.shape,
                                   dtype=p.dtype, persistable=True)
                Constant(0.0)(sv, sb)
                self._shadows[p.name] = shadow

    def update(self):
        prog = default_main_program()
        block = prog.global_block()
        for pname, shadow in self._shadows.items():
            p = block.var(pname)
            tmp = self._helper.create_variable_for_type_inference(p.dtype)
            block.append_op(
                type="scale", inputs={"X": [shadow]}, outputs={"Out": [tmp]},
                attrs={"scale": self._decay})
            tmp2 = self._helper.create_variable_for_type_inference(p.dtype)
            block.append_op(
                type="scale", inputs={"X": [p]}, outputs={"Out": [tmp2]},
                attrs={"scale": 1.0 - self._decay})
            block.append_op(
                type="elementwise_add", inputs={"X": [tmp], "Y": [tmp2]},
                outputs={"Out": [shadow]})

    def apply(self, executor=None, need_restore=True):
        from .core.scope import global_scope

        scope = global_scope()
        self._backups = {}
        for pname, shadow in self._shadows.items():
            if need_restore:
                self._backups[pname] = np.asarray(scope.get(pname))
            sval = scope.get(shadow.name)
            if sval is not None:
                scope.set(pname, np.asarray(sval))
        return _EMAGuard(self)

    def restore(self, executor=None):
        from .core.scope import global_scope

        scope = global_scope()
        for pname, val in getattr(self, "_backups", {}).items():
            scope.set(pname, val)


class _EMAGuard:
    def __init__(self, ema):
        self._ema = ema

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self._ema.restore()


class GradientMergeOptimizer:
    """Gradient accumulation over k steps (parity: SURVEY §2.3 P10
    multi-batch-merge — ir/multi_batch_merge_pass.cc replicated fwd/bwd K
    times per iteration; here: grads accumulate into persistable buffers and
    the wrapped optimizer's update runs under a `cond` every k-th step)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .layers import control_flow, learning_rate_scheduler, nn, tensor

        params_grads = self.inner.backward(loss, startup_program,
                                           parameter_list, no_grad_set)
        if self.k_steps <= 1:
            return self.inner.apply_gradients(params_grads), params_grads

        prog = params_grads[0][0].block.program
        block = prog.global_block()
        with framework.program_guard(prog):
            self.inner.helper = LayerHelper("gradient_merge")
            self.inner._create_global_learning_rate(prog)
            self.inner._create_accumulators(block,
                                            [p for p, _ in params_grads])
            merged = []
            for p, g in params_grads:
                acc = block.create_var(
                    name=unique_name.generate(p.name + "_grad_merge"),
                    shape=p.shape, dtype="float32", persistable=True,
                    stop_gradient=True)
                sb = default_startup_program().global_block()
                sv = sb.create_var(name=acc.name, shape=p.shape,
                                   dtype="float32", persistable=True)
                Constant(0.0)(sv, sb)
                block.append_op(type="elementwise_add",
                                inputs={"X": [acc], "Y": [g]},
                                outputs={"Out": [acc]}, attrs={"axis": -1})
                merged.append((p, acc))

            counter = learning_rate_scheduler.autoincreased_step_counter(
                counter_name="@gradient_merge_step@")
            kvar = tensor.fill_constant([1], "int64", self.k_steps)
            zero = tensor.fill_constant([1], "int64", 0)
            rem = nn.elementwise_mod(counter, kvar)
            pred = nn.equal(rem, zero)

            with control_flow._sub_block() as update_blk:
                for p, acc in merged:
                    g_eff = nn.scale(
                        acc, scale=1.0 / self.k_steps) if self.avg else acc
                    self.inner._append_optimize_op(update_blk, (p, g_eff))
                    # reset the accumulator after applying
                    zg = tensor.fill_constant(p.shape, "float32", 0.0)
                    update_blk.append_op(type="assign",
                                         inputs={"X": [zg]},
                                         outputs={"Out": [acc]})
                if merged:
                    self.inner._finish_update(update_blk, merged)
            control_flow._append_cond_op(
                block, pred, update_blk, None,
                [p.name for p, _ in merged] + [a.name for _, a in merged])
        return [], params_grads


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
DGCMomentum = DGCMomentumOptimizer
