"""Executor (parity: python/paddle/fluid/executor.py:292 `Executor`, :550
`run`, :671 `_run` with program cache; C++ framework/executor.cc).

TPU-native execution model: `run()` lowers the whole program (forward + grad
+ optimizer ops) into ONE pure function
    step(state, feeds, step_counter) -> (fetches, new_state)
jit-compiled by XLA with the state pytree donated, so parameter updates are
in-place buffer aliases in HBM and the host loop does nothing but feed and
fetch. Compiled executables are cached on (program fingerprint, feed
signature, fetch names) — the analogue of Fluid's `_get_strong_program_cache_key`
(executor.py:250), but a cache hit here skips XLA retracing entirely.

The hot loop is asynchronous end-to-end (docs/ASYNC_EXECUTION.md):
`return_numpy=False` (or a non-boundary `fetch_every_n` step) returns the
fetches as unmaterialized device futures, a bounded in-flight window
(`async_steps`, default $PTPU_ASYNC_STEPS or 12) backpressures dispatch,
feed batches can be staged host->device in the background
(`Executor.prefetch` / `train_from_dataset`'s built-in lookahead), and
$PTPU_CACHE_DIR persists compiled executables across processes.
"""

import time

import numpy as np

import jax

from . import framework
from .flags import env as flags_env
from . import observability as _observability
from .observability import metrics as _metrics
from .observability import tracing as _tracing
from .async_engine import (DeferredWarns, FeedPrefetcher, InflightWindow,
                           LazyFetchList, note_compiled_program,
                           prefetch_iter, setup_persistent_cache)
from .async_engine import _nbytes  # shared feed/fetch byte accounting
from .async_engine import as_numpy  # noqa: F401  (re-export: sync point)
from .core.lowering import (LoweringContext, execute_block,
                            pack_nan_reports, pack_warn_reports,
                            raise_if_nonfinite)
from .core.place import CPUPlace, TPUPlace, default_place
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .framework import Program, dtype_to_np

__all__ = ["Executor", "global_scope", "scope_guard", "as_numpy"]


def _feed_signature(feed):
    # duck-typed dtype: np.asarray on a device-resident jax.Array would
    # round-trip the whole buffer over the host link EVERY run() call
    def _dt(v):
        dt = getattr(v, "dtype", None)
        return str(dt) if dt is not None else str(np.asarray(v).dtype)

    return tuple(
        sorted((k, tuple(np.shape(v)), _dt(v)) for k, v in feed.items())
    )


_INT64_DTYPES = (np.dtype(np.int64), np.dtype(np.uint64))


def check_feed_int64(name, value):
    """JAX canonicalizes int64 device inputs to int32; an id above 2^31
    would truncate SILENTLY. Fail loudly instead — raw feature hashes
    belong on the host side (DataFeedDesc slot hash_mod /
    HostEmbeddingTable(hash_ids=True)).

    Checked on the ORIGINAL feed value, BEFORE the host/device branch:
    a device-resident jax.Array keeps an int64 dtype only under
    jax_enable_x64, and exactly then this guard still sees it (with x64
    off the truncation already happened inside the user's device_put,
    which no run()-time check can undo). Only int64/uint64 feeds pay the
    range reduction; every other dtype is one dtype compare."""
    dt = getattr(value, "dtype", None)
    if dt is None or np.dtype(dt) not in _INT64_DTYPES:
        return
    if not getattr(value, "size", 0):
        return
    # host-side reduction even for device arrays: a jnp.max on an int64
    # operand under x64-off canonicalizes the REDUCTION to int32 and
    # reports the truncated value — the very bug being guarded against.
    # The transfer only taxes the rare (and discouraged) int64 feed path.
    arr = np.asarray(value)
    mx, mn = int(arr.max()), int(arr.min())
    if mx > np.iinfo(np.int32).max or mn < np.iinfo(np.int32).min:
        raise ValueError(
            "feed %r holds int64 ids above int32 range; JAX would "
            "silently truncate them on device. Hash them on the "
            "host first (DataFeedDesc.set_hash_mod, or "
            "HostEmbeddingTable(hash_ids=True) for direct "
            "pull/push)" % name)


# byte-scale buckets for module-size histograms (1KiB .. 1GiB)
_BYTE_BUCKETS = tuple(float(1 << s) for s in range(10, 31, 2))


class _CompiledStep:
    """One lowered+jitted step for a (program, feed signature, fetches)."""

    def __init__(self, program, feed_names, fetch_names, scope, mesh_ctx=None):
        from . import ir_passes
        from .compiler import classify_persistable_state

        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        block = program.global_block()

        # pserver-mode RPC ops (transpiled trainer program) run host-side
        # after the jitted step: send needs the step's grad values fetched
        self._rpc_ops = [op for op in block.ops if op.type in
                         ("send", "recv", "send_barrier", "fetch_barrier")]
        self._rpc_client = None
        self._rpc_endpoints = []
        for op in self._rpc_ops:
            for ep in [op.attrs.get("endpoint")] + list(
                    op.attrs.get("endpoints", [])):
                if ep and ep not in self._rpc_endpoints:
                    self._rpc_endpoints.append(ep)
        rpc_fetches = []
        for op in self._rpc_ops:
            if op.type == "send":
                for v in op.inputs.get("X", []):
                    if v.name not in rpc_fetches \
                            and v.name not in self.fetch_names:
                        rpc_fetches.append(v.name)
        self._all_fetch_names = self.fetch_names + rpc_fetches

        # persistable read/write classification (shared with the
        # data-parallel step): mut is donated — param/accumulator updates
        # alias in-place in HBM; const is read-only (e.g. learning rate)
        inplace = (ir_passes.InplaceInfo(scope=scope)
                   if ir_passes.pipeline_enabled() else None)
        self._inplace = inplace
        self.mut_names, self.const_names, self.state_out = \
            classify_persistable_state(block, self._all_fetch_names,
                                       inplace=inplace)
        seed = program.random_seed or 0
        self._seed = seed

        from .flags import flag

        self._check_nan_inf = bool(flag("check_nan_inf"))
        self._nan_labels = []
        self._warn_labels = []
        self._warned = set()
        self._deferred_warns = DeferredWarns()

        def step(mut_state, const_state, feeds, step_counter):
            base_key = jax.random.fold_in(
                jax.random.PRNGKey(self._seed), step_counter
            )
            ctx = LoweringContext(base_key=base_key,
                                  check_nan_inf=self._check_nan_inf)
            env = {}
            env.update(const_state)
            env.update(mut_state)
            env.update(feeds)
            execute_block(block, env, ctx)
            fetches = [env[n] for n in self._all_fetch_names]
            new_state = {n: env[n] for n in self.state_out if n in env}
            # FLAGS_check_nan_inf parity: one fused bool per op output;
            # labels are trace-static, flags come back as a packed array
            self._nan_labels, finite = pack_nan_reports(ctx)
            self._warn_labels, warns = pack_warn_reports(ctx)
            return fetches, new_state, finite, warns

        # under the debug flag, keep state undonated so a nan raise can
        # leave the scope at its pre-step values (catch-and-continue safe)
        donate = () if self._check_nan_inf else (0,)
        self._jitted = jax.jit(step, donate_argnums=donate)
        # AOT-compiled executable, built on FIRST run when telemetry is on
        # so compile time and module size are measured separately from
        # execute time (the plain jit dispatch hides both in call #1).
        # Once a step has executed via the jit path its executable is
        # already cached — AOT-compiling then would duplicate the whole
        # XLA compile just to measure it, so _ran_jit pins the jit path.
        self._aot = None
        self._ran_jit = False

    def _read_state(self, scope, names):
        from . import ir_passes

        state = {}
        for name in names:
            val = scope.get(name)
            if val is None:
                # compile-time artifacts (baked folded constants,
                # donation-promoted dead inputs) self-heal into whatever
                # scope this cached step runs against
                val = ir_passes.state_fallback(self.program,
                                               self._inplace, name)
                if val is not None:
                    scope.set(name, val)
            if val is None:
                raise RuntimeError(
                    "persistable var %r is not initialized — run the startup "
                    "program first (exe.run(fluid.default_startup_program()))"
                    % name
                )
            state[name] = val
        return state

    def run(self, scope, feed):
        mut = self._read_state(scope, self.mut_names)
        const = self._read_state(scope, self.const_names)
        feeds = {}
        block = self.program.global_block()
        for name in self.feed_names:
            v = block._find_var_recursive(name)
            arr = feed[name]
            # range-check the ORIGINAL value: after the device branch a
            # jax.Array has already been canonicalized, after the astype
            # a numpy int64 has already been narrowed
            check_feed_int64(name, arr)
            # device-resident arrays (PyReader double-buffer, user
            # device_put) pass through untouched — np.asarray here would
            # round-trip them over the host link every step
            if not isinstance(arr, jax.Array):
                arr = np.asarray(arr)
            if v is not None and v.shape is not None:
                want = dtype_to_np(v.dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            feeds[name] = arr
        step_counter = np.uint32(scope.get("__step_counter__", 0) or 0)
        fn = self._aot
        if fn is None:
            # tracing alone also takes the AOT path: without it the first
            # "execute" span would swallow the whole trace+compile and
            # point a Perfetto reader at the device for host-side cost
            if ((_metrics.enabled() or _tracing.enabled())
                    and not self._ran_jit):
                fn = self._compile_instrumented(mut, const, feeds,
                                                step_counter)
            else:
                fn = self._jitted
                self._ran_jit = True
        with _tracing.span("execute"):
            fetches, new_state, finite, warns = fn(
                mut, const, feeds, step_counter)
        # deferred: the all-false common case must not sync the device
        # every step — flags accumulate and materialize every few steps
        # (and at Executor.sync/close)
        self._deferred_warns.add(self._warn_labels, warns, self._warned)
        if self._check_nan_inf and finite.size:
            # state was NOT donated under the debug flag: raising here leaves
            # the scope at its pre-step values, so the poisoned update is
            # discarded and training can resume after catching
            raise_if_nonfinite(self._nan_labels, finite)
        for name, val in new_state.items():
            scope.set(name, val)
        scope.set("__step_counter__", int(step_counter) + 1)
        if self._rpc_ops:
            self._run_rpc_plan(scope, dict(zip(self._all_fetch_names,
                                               fetches)))
        return fetches[: len(self.fetch_names)]

    def _compile_instrumented(self, mut, const, feeds, step_counter):
        """Trace+lower+compile ahead of time (jax AOT), recording the
        compile-vs-execute split and the StableHLO module size. The
        compiled executable replaces the jit dispatch for this step's
        remaining runs, so the telemetry shows compile cost exactly once
        per cache entry instead of folded into the first step."""
        with _tracing.span("compile", step=self.fetch_names[:4]):
            t0 = time.perf_counter()
            lowered = self._jitted.lower(mut, const, feeds, step_counter)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        _metrics.histogram("compile_cache/trace_time").observe(t1 - t0)
        _metrics.histogram("compile_cache/compile_time").observe(t2 - t1)
        if _metrics.enabled():
            # per-step FLOPs/bytes from XLA's own cost model — the MFU
            # receipts bench.py reports (docs/OBSERVABILITY.md)
            from .observability import cost as _cost

            _cost.publish(compiled)
        if _metrics.enabled():  # serialization is real work, not a no-op
            try:
                # bytecode serialization, NOT as_text(): the pretty text
                # of a large step runs to tens of MB just to be len()'d
                import io

                buf = io.BytesIO()
                lowered.compiler_ir("stablehlo").operation.write_bytecode(
                    buf)
                _metrics.histogram("compile_cache/stablehlo_module_bytes",
                                   buckets=_BYTE_BUCKETS).observe(
                    buf.tell())
            except Exception:
                pass
        self._aot = compiled
        return compiled

    def _run_rpc_plan(self, scope, fetched):
        """Host-side pserver round (grpc_client.h parity): send grads,
        barrier on the server's optimizer pass, pull fresh params into the
        scope for the next step."""
        from .distributed_runtime import ParameterServerClient

        if self._rpc_client is None:
            tid = next((op.attrs.get("trainer_id", 0)
                        for op in self._rpc_ops), 0)
            self._rpc_client = ParameterServerClient(trainer_id=tid or 0)
        c = self._rpc_client
        for op in self._rpc_ops:
            a = op.attrs
            if op.type == "send":
                for v in op.inputs.get("X", []):
                    c.send_var(a["endpoint"], v.name,
                               np.asarray(fetched[v.name]))
            elif op.type == "send_barrier":
                for ep in a.get("endpoints", []):
                    c.send_barrier(ep)
            elif op.type == "recv":
                for v in op.outputs.get("Out", []):
                    scope.set(v.name, c.get_var(a["endpoint"], v.name))
            elif op.type == "fetch_barrier":
                for ep in a.get("endpoints", []):
                    c.fetch_barrier(ep)


class Executor:
    """Drop-in parity with fluid.Executor (executor.py:292).

    `async_steps` bounds how many dispatched-but-unsynced steps the
    async return paths (`return_numpy=False`, `fetch_every_n`) keep in
    flight before backpressuring on the oldest (default: $PTPU_ASYNC_STEPS
    or 12 — the measured axon-tunnel sweet spot, deep enough to amortize
    the drain RTT, shallow enough to stay clear of the
    many-outstanding-steps wedge)."""

    def __init__(self, place=None, async_steps=None):
        self.place = place if place is not None else default_place()
        self._cache = {}
        if async_steps is None:
            async_steps = flags_env("PTPU_ASYNC_STEPS")
        self._window = InflightWindow(async_steps)
        self._fetch_tick = 0
        self._prefetcher = None
        self._feed_sharding_fn = None
        # compiled steps owned by CompiledPrograms run through this
        # executor — sync() must reach their deferred warnings too
        self._warn_sources = []
        setup_persistent_cache()

    # -- async pipeline ----------------------------------------------------
    def sync(self):
        """Explicit sync point: block until every in-flight step has
        materialized and flush deferred runtime warnings."""
        self._window.drain()
        for compiled in list(self._cache.values()) + self._warn_sources:
            warns = getattr(compiled, "_deferred_warns", None)
            if warns is not None:
                warns.drain(compiled._warned)

    def _feed_sharding(self, name, value):
        """Target placement for a prefetched feed value: the compiled
        sharded step's decision once one exists (compiler.py
        feed_sharding), this executor's device until then."""
        fn = self._feed_sharding_fn
        if fn is not None:
            return fn(name, value)
        return self.place.jax_device()

    def prefetch(self, feed):
        """Stage `feed`'s host values to device on a background thread,
        overlapping the H2D transfer with the device's current step. A
        subsequent `run(feed=feed)` with the SAME value objects picks up
        the staged copies transparently; staged batches are consumed in
        prefetch order."""
        if self._prefetcher is None:
            self._prefetcher = FeedPrefetcher(
                sharding_fn=self._feed_sharding)
        self._prefetcher.put(feed)

    def _finish_run(self, fetches, return_numpy, fetch_every_n):
        """Shared async/sync return path (Executor.run and
        CompiledProgram._run): materialize at the sync points, otherwise
        admit the step to the in-flight window and hand back lazy fetch
        handles."""
        n = int(fetch_every_n or 0)
        if n > 1:
            self._fetch_tick += 1
            if self._fetch_tick % n:
                self._window.admit(fetches)
                return LazyFetchList(fetches)
        if return_numpy:
            out = [np.asarray(f) for f in fetches]
            # the newest step is now host-complete; device execution is
            # in-order, so every older in-flight step is too
            self._window.reset()
            return out
        self._window.admit(fetches)
        return LazyFetchList(fetches)

    def close(self):
        """Notify pservers this trainer is done (executor.py:453 parity —
        the server exits once every trainer completed), then drop caches,
        flushing deferred warnings and the in-flight window."""
        self.sync()
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        for compiled in self._cache.values():
            client = getattr(compiled, "_rpc_client", None)
            if client is not None:
                for ep in getattr(compiled, "_rpc_endpoints", ()):
                    client.complete(ep)
                client.close()
        self._cache.clear()

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
        fetch_every_n=None,
    ):
        """`fetch_every_n=N` keeps the loop asynchronous between sync
        points: only every Nth call materializes fetches (per
        `return_numpy`); the steps in between return LazyFetchList
        handles without touching the host link, bounded by the
        executor's in-flight window."""
        from .compiler import CompiledProgram

        if program is None:
            program = framework.default_main_program()
        if isinstance(program, CompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy,
                                fetch_every_n)
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        scope = scope if scope is not None else global_scope()

        # a transpiled pserver program: block serving (the reference's
        # ListenAndServOp::RunImpl never returns until shutdown)
        lsv = next((op for op in program.global_block().ops
                    if op.type == "listen_and_serv"), None)
        if lsv is not None:
            from .distributed_runtime import run_pserver

            run_pserver(program, scope, lsv.attrs["endpoint"])
            return []

        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        from . import ir_passes
        from .flags import flag

        key = (
            id(program),
            program.version,
            _feed_signature(feed),
            tuple(fetch_names),
            bool(flag("check_nan_inf")),
            # the compile-time pass pipeline is part of the step identity:
            # toggling PTPU_NO_PROGRAM_OPT (or the program flipping
            # between train/inference shape) must not hit a stale entry.
            # The scope is NOT in the key: scope-bound compile artifacts
            # (baked constants, promoted dead inputs) self-heal through
            # ir_passes.state_fallback at state-read time
            ir_passes.pipeline_key(None, program),
        )
        # substitute staged device copies only AFTER the cache key is
        # computed from the ORIGINAL feed: device_put canonicalizes some
        # dtypes, and a signature drift here would force a spurious
        # recompile of the identical program
        if self._prefetcher is not None:
            staged = self._prefetcher.take_if_match(feed)
            if staged is not None:
                feed = staged
        rec = _metrics.enabled()
        with _observability.step_scope():
            compiled = self._cache.get(key) if use_program_cache else None
            if compiled is None:
                # fault-injection hook (docs/RESILIENCE.md): the
                # `transient_compile` site raises a retryable error here
                # so the rollback-and-retry path is testable without a
                # real allocator failure
                from .resilience import maybe_inject_compile_fault

                maybe_inject_compile_fault()
                if rec:
                    _metrics.counter("compile_cache/miss").inc()
                # thread OUR fingerprint through the on-disk cache: the
                # manifest attributes the jit compile below to this
                # program+signature across process restarts
                from .async_engine import persistent_cache_dir

                # compile-time pass pipeline (docs/COMPILER_PASSES.md):
                # DCE/CSE/constant folding on a clone of the program;
                # PTPU_NO_PROGRAM_OPT=1 restores the unoptimized path
                run_program = program
                if ir_passes.pipeline_enabled():
                    with _tracing.span("optimize"):
                        run_program = ir_passes.optimize_for_execution(
                            program, fetch_names, scope)
                else:
                    # PTPU_NO_PROGRAM_OPT=1 skips the pipeline (and its
                    # per-pass verification) — PTPU_VERIFY_PASSES=1 must
                    # still check the program once per compile
                    from .analysis import maybe_verify

                    maybe_verify(program, tuple(fetch_names))
                if persistent_cache_dir():
                    note_compiled_program(run_program.fingerprint(),
                                          key[2], tuple(fetch_names),
                                          key[4])
                with _tracing.span("lower"):
                    compiled = _CompiledStep(run_program, feed.keys(),
                                             fetch_names, scope)
                if use_program_cache:
                    self._cache[key] = compiled
                else:
                    # sync()/close() can never reach an uncached step, so
                    # its warnings must not defer past this run
                    compiled._deferred_warns.drain_every = 1
            elif rec:
                _metrics.counter("compile_cache/hit").inc()

            with jax.default_device(self.place.jax_device()):
                fetches = compiled.run(scope, feed)
        if rec:
            _metrics.counter("executor/feed_bytes").inc(
                _nbytes(feed.values()))
            _metrics.counter("executor/fetch_bytes").inc(_nbytes(fetches))
        out = self._finish_run(fetches, return_numpy, fetch_every_n)
        if not isinstance(out, LazyFetchList):
            # a materializing run is already a sync point: flush pending
            # runtime warnings so the per-step-sync loop warns promptly
            compiled._deferred_warns.drain(compiled._warned)
        return out

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           cursor=None, epochs=None):
        """Drive a whole Dataset through the program (parity: executor.py:851
        → C++ MultiTrainer/HogwildWorker trainer.h:71/C15). The reference's
        thread-per-core Hogwild becomes a reader thread pool over file
        shards (thread= here or dataset.set_thread) parsing on the host
        while the single jitted step owns the device;
        FLAGS_cpu_deterministic serializes emission to filelist order.

        `cursor` (a `data_plane.DatasetCursor`) switches to the
        checkpoint-resumable stream (docs/DATA_PLANE.md): batches start
        at the cursor's position, and the cursor — mirrored into the
        run scope's ``__data_cursor__`` as each batch is consumed — is
        what a later restore resumes the byte-identical stream from.
        `epochs` is the ABSOLUTE epoch bound of that stream (the
        `resumable_batches` contract); default = one pass from the
        cursor's current epoch, so a restored epoch-k cursor trains the
        rest of epoch k rather than silently yielding nothing.
        No cursor = the exact legacy path."""
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        if thread:
            dataset.set_thread(thread)
        program = program or framework.default_main_program()
        fetch_list = list(fetch_list or [])
        fetch_names = [v.name if isinstance(v, framework.Variable) else str(v)
                       for v in fetch_list]
        step = 0
        last = None
        cursor_states = None
        if cursor is not None:
            from collections import deque

            from .core.scope import global_scope

            cursor_scope = scope if scope is not None else global_scope()
            if epochs is None:
                epochs = cursor.epoch + 1
            pair_stream = dataset._resumable_stream(cursor, epochs, None)
            cursor_states = deque()

            def _feeds():
                for feed, state in pair_stream:
                    cursor_states.append(state)
                    yield feed

            batches = _feeds()
        elif epochs is not None:
            raise ValueError("epochs= only applies to the cursor path; "
                             "re-run train_from_dataset per epoch on "
                             "the legacy stream")
        else:
            batches = (dataset._batches_prefetched()
                       if getattr(dataset, "_thread", 1) > 1
                       else dataset._batches())
        # sparse-embedding fast path (docs/RECOMMENDER.md): with
        # PTPU_EMBED_PREFETCH=1 and host-embedding lookups in the
        # program, batch t+1's ids are announced to a background gather
        # worker as the lookahead pulls them, and each step receives the
        # staged row buffer as ordinary feeds instead of paying the
        # in-step pure_callback pull. None = the exact legacy path.
        from .parallel.embedding_pipeline import maybe_pipeline

        embed_pipeline = maybe_pipeline(program)
        if embed_pipeline is not None:
            batches = embed_pipeline.announce_iter(batches)
        # H2D lookahead: while the device runs batch k, a background
        # thread device_puts batch k+1 (same contract as PyReader's
        # double buffer, here for the Dataset path)
        device_feeder = FeedPrefetcher(sharding_fn=self._feed_sharding)
        try:
            for feed in prefetch_iter(batches, device_feeder):
                if embed_pipeline is not None:
                    # coherence point: barrier on the prior steps'
                    # pushes, repair dirtied rows, merge staged arrays
                    feed = embed_pipeline.finalize_into(feed)
                if cursor_states is not None:
                    # consumption point: the lookahead above has already
                    # PULLED batch k+1, but the mirrored cursor may only
                    # advance as batch k is taken for its step — else a
                    # checkpoint would name a position one batch ahead
                    cursor.advance_to(*cursor_states.popleft())
                    cursor.write_to(cursor_scope)
                last = self.run(program, feed=feed, fetch_list=fetch_list,
                                scope=scope)
                step += 1
                if debug and fetch_names and step % print_period == 0:
                    info = fetch_info or fetch_names
                    print("step %d: %s" % (step, {
                        k: np.asarray(v).ravel()[:4]
                        for k, v in zip(info, last)}))
        finally:
            device_feeder.close()
            if embed_pipeline is not None:
                # detaches the program decoration too, so a later direct
                # exe.run compiles the legacy synchronous lookup again
                embed_pipeline.close()
        return last

    infer_from_dataset = train_from_dataset
