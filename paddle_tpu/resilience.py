"""Fault-tolerant training runtime (reference lineage: the Fluid stack's
production trainers survive bad batches, preempted workers and corrupt
state — SURVEY §5.3-5.4 checkpoint_notify flow, io.py save/load_persistables;
PAPERS.md elastic/resilient large-scale trainers).

Four cooperating pieces, all opt-in and all measured through the
observability registry (docs/RESILIENCE.md):

  guarded steps    — `ResilientTrainer` dispatches steps asynchronously
                     (`return_numpy=False`, the PR-2 in-flight window) and
                     validates the fetched losses in BATCHES at sync
                     points: one host materialization per `guard_every`
                     steps, zero added per-step device syncs. NaN/Inf and
                     loss-spike anomalies route through a configurable
                     policy (`warn | skip_batch | rollback | abort`,
                     env `PTPU_ANOMALY_POLICY`).
  rollback/retry   — bounded in-memory host snapshots of the scope state
                     at each validated boundary; on an anomaly (or a
                     transient XlaRuntimeError) the last-good snapshot is
                     restored, the good prefix of the window is replayed,
                     and the poisoned step is retried (policy `rollback`,
                     spending an exponential-backoff retry budget) or
                     dropped (policy `skip_batch` — forward progress, so
                     budget-free). A retried step replays at its
                     ORIGINAL `__step_counter__`, so its RNG folds and the
                     resumed trajectory are bitwise identical to the
                     fault-free run (tests/test_resilience.py pins this).
  crash-safe ckpt  — checkpoint.py writes atomically (tmp dir + rename)
                     with a per-leaf digest manifest; restore verifies
                     digests and falls back to the newest INTACT step.
                     `ResilientTrainer(checkpoint_dir=...)` saves on a
                     background thread from the already-host snapshot, so
                     the device never waits on the filesystem.
  preemption drain — SIGTERM/SIGINT set a flag (`PreemptionGuard`); the
                     trainer notices at the next step boundary, drains the
                     in-flight window, validates, writes an emergency
                     checkpoint and returns `TrainResult.preempted=True`.

Every recovery path is testable in CI via deterministic fault injection
(`PTPU_FAULT_INJECT="nan_at_step:12,ckpt_torn_write:1,..."` — see
`FaultInjector`); scripts/ci.sh's `chaos` stage trains fit-a-line under
injected faults and gates on `resilience/rollbacks` + final loss.
"""

import collections
import copy
import os
import signal
import threading
import time
import warnings

import numpy as np

from .flags import env as _env
from .observability import flight_recorder as _blackbox
from .observability import metrics as _metrics
from .observability import tracing as _tracing

__all__ = [
    "POLICY_WARN", "POLICY_SKIP_BATCH", "POLICY_ROLLBACK", "POLICY_ABORT",
    "POLICIES", "anomaly_policy", "AnomalyDetector", "AnomalousStepError",
    "RetryBudgetExceededError", "InjectedTransientError",
    "InjectedReplicaDeathError", "maybe_inject_serve_fault",
    "InjectedPeerDeathError", "maybe_inject_peer_death",
    "maybe_inject_shard_fault", "maybe_inject_swap_death",
    "maybe_inject_canary_anomaly",
    "is_transient_error", "FaultInjector", "global_injector",
    "set_global_injector", "PreemptionGuard", "ScopeSnapshot",
    "snapshot_scope", "restore_scope_snapshot", "TrainResult",
    "ResilientTrainer",
]


# ---------------------------------------------------------------------------
# anomaly policy
# ---------------------------------------------------------------------------

POLICY_WARN = "warn"
POLICY_SKIP_BATCH = "skip_batch"
POLICY_ROLLBACK = "rollback"
POLICY_ABORT = "abort"
POLICIES = (POLICY_WARN, POLICY_SKIP_BATCH, POLICY_ROLLBACK, POLICY_ABORT)


def anomaly_policy(value=None):
    """Resolve the anomaly policy: explicit arg > $PTPU_ANOMALY_POLICY >
    `rollback` (the trainer exists to recover, so recovery is the
    default)."""
    policy = value or _env("PTPU_ANOMALY_POLICY") or POLICY_ROLLBACK
    if policy not in POLICIES:
        raise ValueError("unknown anomaly policy %r (want one of %s)"
                         % (policy, "|".join(POLICIES)))
    return policy


class AnomalousStepError(RuntimeError):
    """Raised under policy `abort` (and by an exhausted retry budget) —
    carries the offending global step and the observed value."""

    def __init__(self, step, kind, value):
        super().__init__(
            "anomalous training step %d (%s): loss=%r" % (step, kind, value))
        self.step = step
        self.kind = kind
        self.value = value


class RetryBudgetExceededError(RuntimeError):
    """The run consumed its whole rollback/retry budget — the failure is
    not transient; surfacing it beats looping forever."""


class AnomalyDetector:
    """Cheap host-side NaN/Inf + loss-spike detector.

    `check(value)` returns None for a healthy loss, `"nonfinite"` for
    NaN/Inf, `"spike"` when the mean exceeds `spike_factor` x the running
    EMA (only after `warmup` healthy observations — a cold EMA would flag
    normal early-training noise). Healthy values fold into the EMA;
    anomalous ones never do, so one spike cannot drag the baseline up.
    Spike detection is off unless `spike_factor` (or $PTPU_SPIKE_FACTOR)
    is set — NaN/Inf detection is always on."""

    def __init__(self, spike_factor=None, spike_window=16, warmup=5):
        if spike_factor is None:
            spike_factor = _env("PTPU_SPIKE_FACTOR") or 0.0
        self.spike_factor = float(spike_factor or 0.0)
        self.warmup = int(warmup)
        self._alpha = 2.0 / (max(2, int(spike_window)) + 1.0)
        self._ema = 0.0
        self._n = 0

    def check(self, value):
        try:
            arr = np.asarray(value, dtype=np.float64)
        except (TypeError, ValueError):
            return None  # non-numeric fetch: nothing to guard
        if arr.size == 0:
            return None
        if not np.isfinite(arr).all():
            return "nonfinite"
        mean = float(arr.mean())
        if (self.spike_factor > 0.0 and self._n >= self.warmup
                and abs(mean) > self.spike_factor * max(abs(self._ema),
                                                        1e-12)):
            return "spike"
        self._ema = (mean if self._n == 0
                     else (1.0 - self._alpha) * self._ema
                     + self._alpha * mean)
        self._n += 1
        return None

    def state(self):
        """Opaque EMA state, captured alongside scope snapshots so a
        rollback rewinds the baseline too — replayed losses must not
        fold into the EMA twice."""
        return (self._ema, self._n)

    def restore(self, state):
        self._ema, self._n = state


# ---------------------------------------------------------------------------
# transient-error classification
# ---------------------------------------------------------------------------

# XLA/runtime failure modes worth retrying: allocator pressure, a flaky
# transport, a coordinator hiccup. Compile errors, shape errors and user
# exceptions never match — retrying those only hides bugs.
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                      "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED")


class InjectedTransientError(RuntimeError):
    """What `FaultInjector` raises for `transient_*` sites — message
    mimics a retryable XLA status so the classifier exercises the same
    path a real RESOURCE_EXHAUSTED would."""


_XLA_ERROR_TYPES = None


def _xla_error_types():
    global _XLA_ERROR_TYPES
    if _XLA_ERROR_TYPES is None:
        types = []
        try:
            from jax.errors import JaxRuntimeError
            types.append(JaxRuntimeError)
        except ImportError:
            pass
        try:
            import jaxlib.xla_extension as _xe
            types.append(_xe.XlaRuntimeError)
        except (ImportError, AttributeError):
            pass
        _XLA_ERROR_TYPES = tuple(types)
    return _XLA_ERROR_TYPES


def is_transient_error(exc):
    """True when `exc` is a runtime failure worth a rollback-and-retry:
    an XlaRuntimeError carrying a retryable status code, or an injected
    stand-in for one."""
    if isinstance(exc, InjectedTransientError):
        return True
    if isinstance(exc, _xla_error_types()):
        msg = str(exc)
        return any(marker in msg for marker in _TRANSIENT_MARKERS)
    return False


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------


class FaultInjector:
    """Deterministic fault hooks so every recovery path runs in CI.

    Spec syntax (also the $PTPU_FAULT_INJECT format): comma-separated
    `site:N` pairs. Step-keyed sites fire when the trainer reaches global
    step N; occurrence-keyed sites fire on the N-th time the hook site is
    reached (1-based). Every firing is ONE-SHOT — a retried step does not
    re-poison itself, which is exactly what makes rollback-and-retry
    converge. Match-and-consume is atomic (one lock around the armed-set
    lookup and discard): the serving sites below are hit concurrently
    from N engine worker threads, and two workers racing one armed step
    must produce exactly one firing.

      nan_at_step:N        poison the step-N feed with a NaN (trainer)
      sigterm_at_step:N    deliver SIGTERM to this process at step N
      transient_at_step:N  raise a retryable runtime error at step N
      transient_compile:K  K-th executor compile raises retryable error
      ckpt_torn_write:K    corrupt the K-th checkpoint after it lands
                           (a torn write the digest manifest must catch)

    Serving sites (docs/SERVING.md "Fleet & failover") key on the engine
    worker's own dispatched-step counter (0-based; the hook runs at the
    step boundary BEFORE dispatching step N, while scheduler state is
    still consistent). With several replicas the first worker to reach
    step N consumes the armed firing:

      serve_die_at_step:N       raise a fatal (non-transient) error in
                                the serving step loop — replica death
      serve_transient_at_step:N raise a retryable error in the serving
                                step loop (the worker retries in place)
      serve_stall_at_step:N     stop making step progress WITHOUT
                                raising, until the replica is aborted
                                or closed — the watchdog failure mode
                                exceptions cannot model

    Data-plane sites (docs/DATA_PLANE.md): shard sites key on the
    shard's index in the dataset filelist, the peer site on the
    exchanging worker's rank:

      data_corrupt_shard:N      shard N's chunks all fail CRC
                                verification (containment policy path)
      data_stall_shard:N        opening shard N stalls briefly without
                                failing (slow-reader path — the
                                prefetch window must absorb it)
      data_peer_die_at_exchange:K
                                the rank-K worker dies at the top of
                                `exchange_samples` — survivors must
                                confirm the loss and re-partition

    Online-update sites (docs/SERVING.md "Online updates"): the weight
    hot-swap plane's chaos matrix. ``canary_anomaly_at_version`` keys
    on the rollout's weight-version number; the other two are
    occurrence-keyed:

      ckpt_torn_export:K        corrupt the K-th published generation
                                artifact after it lands (a torn export
                                the artifact digest manifest must
                                catch — the rollout skips it)
      swap_die_mid_drain:K      kill the draining replica during the
                                K-th rollout drain (survivors must
                                re-admit its requests; the rollout
                                resumes past the corpse)
      canary_anomaly_at_version:N
                                the canary gate reports an anomaly for
                                weight version N — the structured-
                                rollback path runs deterministically
    """

    STEP_SITES = ("nan_at_step", "sigterm_at_step", "transient_at_step",
                  "serve_die_at_step", "serve_transient_at_step",
                  "serve_stall_at_step", "data_corrupt_shard",
                  "data_stall_shard", "data_peer_die_at_exchange",
                  "canary_anomaly_at_version")
    OCCURRENCE_SITES = ("transient_compile", "ckpt_torn_write",
                        "ckpt_torn_export", "swap_die_mid_drain")

    def __init__(self, spec=None):
        from .analysis.concurrency import make_lock

        # one-shot firings must be atomic across engine worker threads
        # (named site, tracked under PTPU_LOCK_CHECK=1)
        self._lock = make_lock("resilience.fault_injector")
        self._steps = {}        # site -> set of step numbers still armed
        self._targets = {}      # site -> set of occurrence indices armed
        self._occ = collections.Counter()
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            site, _, num = part.partition(":")
            site = site.strip().replace("-", "_")
            if site not in self.STEP_SITES + self.OCCURRENCE_SITES:
                raise ValueError(
                    "unknown fault-injection site %r (want one of %s)"
                    % (site, ", ".join(self.STEP_SITES
                                       + self.OCCURRENCE_SITES)))
            try:
                n = int(num)
            except ValueError:
                raise ValueError("fault spec %r wants site:N" % part)
            bucket = (self._steps if site in self.STEP_SITES
                      else self._targets)
            bucket.setdefault(site, set()).add(n)

    @classmethod
    def from_env(cls):
        return cls(_env("PTPU_FAULT_INJECT"))

    def active(self):
        return bool(self._steps or self._targets)

    def _fired(self, site):
        _metrics.counter("resilience/faults_injected").inc()
        _blackbox.record_event("fault_injected", site=site)
        warnings.warn("PTPU_FAULT_INJECT: firing %r" % site,
                      RuntimeWarning)

    def fire_at_step(self, site, step):
        """One-shot: True exactly once when `step` is armed for `site`.
        Match-and-consume runs under the injector lock; the telemetry
        side effects run after release (the metrics-registry locks are
        themselves tracked sites)."""
        with self._lock:
            armed = self._steps.get(site)
            hit = bool(armed and int(step) in armed)
            if hit:
                armed.discard(int(step))
        if hit:
            self._fired("%s:%d" % (site, step))
        return hit

    def fire_occurrence(self, site):
        """One-shot: True on the N-th call for each armed N (atomic, see
        `fire_at_step`)."""
        with self._lock:
            armed = self._targets.get(site)
            if not armed:
                return False
            self._occ[site] += 1
            occ = self._occ[site]
            hit = occ in armed
            if hit:
                armed.discard(occ)
        if hit:
            self._fired("%s#%d" % (site, occ))
        return hit


_GLOBAL_INJECTOR = None


def global_injector():
    """The process-wide injector, built lazily from $PTPU_FAULT_INJECT.
    The executor's compile hook and checkpoint.py's torn-write hook read
    this one; `ResilientTrainer` does too unless given its own."""
    global _GLOBAL_INJECTOR
    if _GLOBAL_INJECTOR is None:
        _GLOBAL_INJECTOR = FaultInjector.from_env()
    return _GLOBAL_INJECTOR


def set_global_injector(injector):
    """Swap the process-wide injector (tests); returns the previous one."""
    global _GLOBAL_INJECTOR
    prev = _GLOBAL_INJECTOR
    _GLOBAL_INJECTOR = injector
    return prev


def maybe_inject_compile_fault():
    """Executor hook (cache-miss path): raise a retryable error when the
    `transient_compile` site fires. Lives here so executor.py carries one
    call, not the policy."""
    inj = global_injector()
    if inj.active() and inj.fire_occurrence("transient_compile"):
        raise InjectedTransientError(
            "RESOURCE_EXHAUSTED: injected transient compile failure "
            "(PTPU_FAULT_INJECT transient_compile)")


class InjectedReplicaDeathError(RuntimeError):
    """What the `serve_die_at_step` site raises in a serving worker — a
    fatal, NON-transient failure, so the engine dies and the router's
    failover path (not an in-place retry) must recover."""


def maybe_inject_serve_fault(step):
    """Serving-engine step-boundary hook (docs/SERVING.md "Fleet &
    failover"): raises for the `serve_die_at_step` /
    `serve_transient_at_step` sites, returns ``"stall"`` when
    `serve_stall_at_step` fires (the engine owns the stall loop — it
    must stay abortable), else None. The engine calls this BEFORE any
    scheduler mutation, so a retried tick after a transient firing is
    clean."""
    inj = global_injector()
    if not inj.active():
        return None
    if inj.fire_at_step("serve_die_at_step", step):
        raise InjectedReplicaDeathError(
            "injected serving replica death at step %d "
            "(PTPU_FAULT_INJECT serve_die_at_step)" % int(step))
    if inj.fire_at_step("serve_transient_at_step", step):
        raise InjectedTransientError(
            "UNAVAILABLE: injected transient serving step failure at "
            "step %d (PTPU_FAULT_INJECT serve_transient_at_step)"
            % int(step))
    if inj.fire_at_step("serve_stall_at_step", step):
        return "stall"
    return None


def maybe_inject_swap_death():
    """OnlineUpdater drain hook (docs/SERVING.md "Online updates"):
    True when the `swap_die_mid_drain` site fires — the updater then
    kills the draining replica instead of swapping it, modelling a
    host lost mid-rollout (the router's watchdog must re-admit its
    in-flight requests on survivors and the rollout must resume past
    the corpse)."""
    inj = global_injector()
    return inj.active() and inj.fire_occurrence("swap_die_mid_drain")


def maybe_inject_canary_anomaly(version):
    """Canary-gate hook (docs/SERVING.md "Online updates"): True when
    the `canary_anomaly_at_version` site is armed for this weight
    version — the gate reports a (structured, injected) anomaly and
    the updater's rollback path runs deterministically in CI."""
    inj = global_injector()
    return inj.active() and inj.fire_at_step("canary_anomaly_at_version",
                                             version)


class InjectedPeerDeathError(RuntimeError):
    """What the `data_peer_die_at_exchange` site raises in the armed
    rank's `exchange_samples` — that worker drops out before binding
    its listener, so its peers observe exactly what a crashed machine
    looks like: refused connections and a missing sample frame."""


def maybe_inject_peer_death(rank):
    """`exchange_samples` entry hook (docs/DATA_PLANE.md): the armed
    rank dies before it binds its listener or sends a byte."""
    inj = global_injector()
    if inj.active() and inj.fire_at_step("data_peer_die_at_exchange",
                                         rank):
        raise InjectedPeerDeathError(
            "injected shuffle-peer death at rank %d (PTPU_FAULT_INJECT "
            "data_peer_die_at_exchange)" % int(rank))


def maybe_inject_shard_fault(shard_index):
    """Shard-reader open hook (docs/DATA_PLANE.md): ``"corrupt"`` when
    `data_corrupt_shard` fires for this shard index (every chunk then
    fails CRC verification, exercising the containment policy on intact
    bytes), ``"stall"`` when `data_stall_shard` fires (the reader naps
    briefly — the prefetch window's job to absorb), else None."""
    inj = global_injector()
    if not inj.active():
        return None
    if inj.fire_at_step("data_corrupt_shard", shard_index):
        return "corrupt"
    if inj.fire_at_step("data_stall_shard", shard_index):
        return "stall"
    return None


# ---------------------------------------------------------------------------
# preemption handling
# ---------------------------------------------------------------------------


class PreemptionGuard:
    """SIGTERM/SIGINT -> drain-don't-die. Entering installs handlers that
    only SET A FLAG (no work in signal context — the trainer drains at
    its next step boundary); exiting restores the previous handlers. A
    second signal while draining restores default disposition and
    re-raises, so a stuck drain can still be killed. Outside the main
    thread (signal.signal would throw) the guard degrades to an inert
    flag holder."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self.triggered = None  # signal number once preempted
        self._previous = {}
        self._installed = False

    def _handle(self, signum, frame):
        if self.triggered is not None:
            # escalate: second signal behaves as if we never intercepted
            self.uninstall()
            if signum == signal.SIGINT:
                raise KeyboardInterrupt
            signal.raise_signal(signum)
            return
        self.triggered = signum

    def install(self):
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal only works from the main thread
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


# ---------------------------------------------------------------------------
# scope snapshots (the rollback substrate)
# ---------------------------------------------------------------------------


def _host_copy(value):
    """A host-owned copy of one scope value. Device arrays MUST be copied
    off their buffers: the jitted step donates the state pytree, and a
    donated buffer is dead the moment the next step dispatches — a view
    (plain np.asarray) would silently read recycled memory."""
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    try:
        import jax

        if isinstance(value, jax.Array):
            return np.array(value)  # np.array copies; np.asarray may view
    except ImportError:
        pass
    if isinstance(value, np.ndarray):
        return value.copy()
    try:
        return copy.deepcopy(value)
    except Exception:
        return value  # uncopyable handle: keep the reference


class ScopeSnapshot:
    """Host copy of a scope's top-level state at a validated boundary.
    `aux` carries caller bookkeeping that must rewind with the scope
    (the trainer parks its anomaly-detector EMA state there)."""

    __slots__ = ("step", "state", "aux")

    def __init__(self, step, state, aux=None):
        self.step = int(step)
        self.state = state
        self.aux = aux

    @property
    def nbytes(self):
        return sum(int(getattr(v, "nbytes", 0) or 0)
                   for v in self.state.values())


def snapshot_scope(scope, step=None):
    """Copy every top-level scope value to host memory. Taken at sync
    points only (the copy IS a device sync), so the guarded loop never
    adds per-step syncs."""
    if step is None:
        step = int(scope.get("__step_counter__", 0) or 0)
    with _tracing.span("resilience/snapshot"):
        state = {name: _host_copy(value) for name, value in scope.items()}
    return ScopeSnapshot(step, state)


def restore_scope_snapshot(snapshot, scope):
    """Write a snapshot back into `scope`. Hands out fresh copies —
    arrays AND mutable containers (tensor-array lists etc.) — so
    post-rollback training can never dirty the snapshot across repeated
    rollbacks."""
    for name, value in snapshot.state.items():
        if isinstance(value, np.ndarray):
            value = value.copy()
        elif not isinstance(value, (type(None), bool, int, float, str,
                                    bytes)):
            try:
                value = copy.deepcopy(value)
            except Exception:
                pass  # uncopyable handle: hand out the reference
        scope.set(name, value)
    return snapshot.step


# ---------------------------------------------------------------------------
# the resilient training loop
# ---------------------------------------------------------------------------


class TrainResult:
    """What `ResilientTrainer.run` returns: the last materialized fetches
    plus the recovery ledger (mirrored into `resilience/*` counters when
    metrics are on, live here even when they are off)."""

    __slots__ = ("step", "last_fetches", "preempted", "anomalies",
                 "rollbacks", "retries", "skipped_steps", "losses",
                 "checkpoints_saved")

    def __init__(self):
        self.step = 0
        self.last_fetches = None
        self.preempted = False
        self.anomalies = 0
        self.rollbacks = 0
        self.retries = 0
        self.skipped_steps = 0
        self.checkpoints_saved = 0
        self.losses = []

    def __repr__(self):
        return ("TrainResult(step=%d, preempted=%s, anomalies=%d, "
                "rollbacks=%d, retries=%d, skipped=%d, ckpts=%d)"
                % (self.step, self.preempted, self.anomalies,
                   self.rollbacks, self.retries, self.skipped_steps,
                   self.checkpoints_saved))


class _Pending:
    """One dispatched-but-unvalidated step."""

    __slots__ = ("gstep", "key", "feed", "fetches")

    def __init__(self, gstep, key, feed, fetches):
        self.gstep = gstep
        # batch identity, assigned once when the batch is pulled from
        # the feed iterator — step labels renumber under skip_batch, so
        # per-batch retry accounting must not key on gstep
        self.key = key
        self.feed = feed
        self.fetches = fetches


class ResilientTrainer:
    """Guarded, rollback-capable wrapper around `Executor.run`.

    The loop dispatches steps asynchronously (`return_numpy=False`) and
    validates fetched losses every `guard_every` steps — the SAME sync
    cadence the PR-2 in-flight window already imposes, so the guard's
    only extra cost is the host-side isfinite/EMA check and a scope
    snapshot per validated boundary (measured by bench.py's
    `bench/step_time_guarded` vs `_unguarded` leg).

        trainer = ResilientTrainer(exe, program, fetch_list=[loss],
                                   checkpoint_dir="ckpt", ...)
        trainer.restore()           # resume from the newest intact ckpt
        result = trainer.run(feed_batches)

    Recovery semantics (docs/RESILIENCE.md): an anomalous or failed step
    rolls the scope back to the last validated snapshot and replays the
    window's good steps AT THEIR ORIGINAL step counters, so a successful
    retry is bitwise identical to a fault-free run."""

    def __init__(self, exe, program=None, fetch_list=None, scope=None,
                 policy=None, guard_every=8, guard_fetch_index=0,
                 snapshot_limit=1, checkpoint_dir=None, checkpoint_every=0,
                 max_to_keep=3, retry_budget=None, backoff_base=None,
                 backoff_max=30.0, max_step_retries=2, spike_factor=None,
                 spike_window=16, fault_injector=None,
                 handle_preemption=True):
        from . import framework
        from .core.scope import global_scope

        self.exe = exe
        self.program = (program if program is not None
                        else framework.default_main_program())
        self.fetch_list = list(fetch_list or [])
        if not self.fetch_list:
            raise ValueError("ResilientTrainer needs a fetch_list with the "
                             "loss to guard (guard_fetch_index names it)")
        self.scope = scope if scope is not None else global_scope()
        self.policy = anomaly_policy(policy)
        self.guard_every = max(1, int(guard_every))
        self.guard_fetch_index = int(guard_fetch_index)
        if retry_budget is None:
            retry_budget = _env("PTPU_RETRY_BUDGET")
        self.retry_budget = int(retry_budget)
        if backoff_base is None:
            backoff_base = _env("PTPU_RETRY_BACKOFF")
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.max_step_retries = int(max_step_retries)
        self.detector = AnomalyDetector(spike_factor=spike_factor,
                                        spike_window=spike_window)
        self.injector = (fault_injector if fault_injector is not None
                         else global_injector())
        self.handle_preemption = bool(handle_preemption)
        self._snapshots = collections.deque(maxlen=max(1,
                                                       int(snapshot_limit)))
        self.checkpoint_every = int(checkpoint_every)
        self._manager = None
        if checkpoint_dir:
            from .checkpoint import CheckpointManager

            self._manager = CheckpointManager(checkpoint_dir,
                                              max_to_keep=max_to_keep,
                                              async_save=True)
        self._retries_left = self.retry_budget
        self._batch_retries = collections.Counter()
        self._last_ckpt_step = None

    # -- checkpoint resume -------------------------------------------------
    def restore(self):
        """Load the newest INTACT checkpoint into the scope (corrupt or
        torn steps fall through to older ones — checkpoint.py verifies
        the digest manifest). Returns the restored global step, or None
        when the directory holds no usable checkpoint."""
        if self._manager is None:
            raise ValueError("ResilientTrainer has no checkpoint_dir")
        try:
            state = self._manager.restore()
        except FileNotFoundError:
            return None
        for name, value in state.items():
            self.scope.set(name, value)
        step = int(np.asarray(self.scope.get("__step_counter__", 0)
                              or 0).item())
        self.scope.set("__step_counter__", step)
        self._last_ckpt_step = step
        return step

    # -- internals ---------------------------------------------------------
    def _current_step(self):
        return int(np.asarray(self.scope.get("__step_counter__", 0)
                              or 0).item())

    def _maybe_corrupt(self, feed, gstep):
        """`nan_at_step` injection: poison the first float feed value of
        step `gstep` (a copy — never the caller's array)."""
        if not self.injector.fire_at_step("nan_at_step", gstep):
            return feed
        poisoned = dict(feed)
        for name, value in poisoned.items():
            arr = np.array(value)
            if arr.dtype.kind == "f" and arr.size:
                arr.reshape(-1)[0] = np.nan
                poisoned[name] = arr
                break
        return poisoned

    def _consume_retry(self, what):
        if self._retries_left <= 0:
            _blackbox.record_event("retry_budget_exhausted",
                                   budget=self.retry_budget,
                                   error=repr(what))
            _blackbox.dump("retry_budget_exceeded")
            raise RetryBudgetExceededError(
                "retry budget (%d) exhausted while handling %s"
                % (self.retry_budget, what))
        self._retries_left -= 1
        attempt = self.retry_budget - self._retries_left
        delay = min(self.backoff_max,
                    self.backoff_base * (2.0 ** (attempt - 1)))
        if delay > 0:
            time.sleep(delay)

    def _dispatch(self, feed, gstep, result):
        """One guarded exe.run. Transient runtime failures (real
        XlaRuntimeError RESOURCE_EXHAUSTED/... or injected) roll back to
        the last snapshot — donated state buffers may already be dead
        after a failed dispatch, so the scope MUST be rebuilt from host
        copies — and raise `_Replay` for the driver to redo the window."""
        if self.injector.fire_at_step("transient_at_step", gstep):
            raise InjectedTransientError(
                "UNAVAILABLE: injected transient step failure "
                "(PTPU_FAULT_INJECT transient_at_step)")
        return self.exe.run(self.program, feed=feed,
                            fetch_list=self.fetch_list, scope=self.scope,
                            return_numpy=False)

    def _rollback(self, result):
        """Restore the newest snapshot into the scope. The executor's
        in-flight window is already quiesced by the materialization that
        preceded every rollback decision.

        The data-plane cursor (``__data_cursor__``) is exempt: it
        tracks the PULL frontier of the record stream, and a rollback
        replays the window from the in-memory feed buffer — it never
        re-reads the stream — so the frontier must survive the restore.
        Rewinding it with the weights would leave the next boundary's
        checkpoint one window behind the state it describes, and a
        resume would double-train that window."""
        snap = self._snapshots[-1]
        with _tracing.span("resilience/rollback", step=snap.step):
            from .data_plane import DatasetCursor

            cursor_val = self.scope.get(DatasetCursor.SCOPE_KEY)
            restore_scope_snapshot(snap, self.scope)
            if cursor_val is not None:
                self.scope.set(DatasetCursor.SCOPE_KEY, cursor_val)
        if snap.aux is not None:
            # rewind the spike-EMA baseline too: the replay re-checks
            # the same healthy losses, which must not fold in twice
            self.detector.restore(snap.aux)
        result.rollbacks += 1
        _metrics.counter("resilience/rollbacks").inc()
        _blackbox.record_event("rollback", step=snap.step)
        return snap.step

    def _replay(self, records, result):
        """Re-dispatch a list of (gstep, key, feed) records after a
        rollback, re-entering the transient-retry path if the replay
        itself fails. Returns fresh pending entries."""
        pending = []
        for gstep, key, feed in records:
            while True:
                try:
                    fetches = self._dispatch(feed, gstep, result)
                    break
                except Exception as exc:  # noqa: BLE001 — classified below
                    if not is_transient_error(exc):
                        raise
                    result.retries += 1
                    _metrics.counter("resilience/retries").inc()
                    # roll back BEFORE spending the budget: if the budget
                    # is exhausted the raised error must leave the scope
                    # at last-good state, not holding dead donated buffers
                    self._rollback(result)
                    self._consume_retry(exc)
                    # restart the whole replay from the snapshot (the
                    # partially-replayed prefix was rolled back too);
                    # recursion depth is bounded by the retry budget
                    return self._replay(records, result)
            pending.append(_Pending(gstep, key, feed, fetches))
        return pending

    def _validate(self, pending, result):
        """Materialize the window's fetches (ONE sync point), scan the
        guarded loss for anomalies, apply the policy, and on a clean
        window advance the snapshot/checkpoint boundary. Returns the new
        pending list (empty unless a replay is itself dirty and the
        policy keeps retrying). An empty window is a no-op — the last
        boundary already snapshotted this exact state."""
        if not pending:
            return []
        while pending:
            gi = self.guard_fetch_index
            values = [np.asarray(p.fetches[gi]) for p in pending]
            bad_index = bad_kind = None
            for i, value in enumerate(values):
                kind = self.detector.check(value)
                if kind is not None:
                    bad_index, bad_kind = i, kind
                    break
            if bad_index is not None:
                bad = pending[bad_index]
                result.anomalies += 1
                _metrics.counter("resilience/anomalies").inc()
                _blackbox.record_event("anomaly", step=bad.gstep,
                                       kind=bad_kind,
                                       policy=self.policy)
                if self.policy == POLICY_ABORT:
                    raise AnomalousStepError(bad.gstep, bad_kind,
                                             values[bad_index])
                if self.policy == POLICY_WARN:
                    warnings.warn(
                        "anomalous step %d (%s): loss=%r — policy=warn, "
                        "continuing with poisoned state"
                        % (bad.gstep, bad_kind, values[bad_index]),
                        RuntimeWarning)
                    # warn accepts the whole window, so the scan must
                    # finish it: later healthy losses still fold into
                    # the EMA (anomalous ones never do). The window
                    # counts as ONE anomaly — per-step counting would
                    # spam hundreds of warnings once the state is
                    # poisoned, which is exactly what warn permits
                    for i in range(bad_index + 1, len(values)):
                        self.detector.check(values[i])
            if bad_index is None or self.policy == POLICY_WARN:
                # clean window (or warn-mode acceptance of a dirty one):
                # record it and advance the snapshot boundary
                for p, v in zip(pending, values):
                    result.losses.append(float(np.asarray(v).ravel()[0])
                                         if v.size else float("nan"))
                result.last_fetches = [np.asarray(f)
                                       for f in pending[-1].fetches]
                result.step = pending[-1].gstep + 1
                self._mark_boundary(result)
                return []
            bad = pending[bad_index]
            # skip_batch / rollback: rebuild from the last-good snapshot
            self._rollback(result)
            retry_bad = (self.policy == POLICY_ROLLBACK
                         and self._batch_retries[bad.key]
                         < self.max_step_retries)
            records = [(p.gstep, p.key, p.feed)
                       for p in pending[:bad_index]]
            if retry_bad:
                # retrying can loop on a deterministic failure, so it
                # spends the global budget (and backs off); skipping
                # always makes forward progress and costs nothing
                self._consume_retry("%s at step %d" % (bad_kind,
                                                       bad.gstep))
                self._batch_retries[bad.key] += 1
                result.retries += 1
                _metrics.counter("resilience/retries").inc()
                records.append((bad.gstep, bad.key, bad.feed))
                # steps after the retried one keep their original counters
                records.extend((p.gstep, p.key, p.feed)
                               for p in pending[bad_index + 1:])
            else:
                result.skipped_steps += 1
                _metrics.counter("resilience/skipped_steps").inc()
                # dropping the batch shifts every later step down one
                # counter slot — replay them contiguously so the scope's
                # __step_counter__ stays dense (RNG folds follow it)
                records.extend((p.gstep - 1, p.key, p.feed)
                               for p in pending[bad_index + 1:])
            pending = self._replay(records, result)
            # loop: re-validate the replayed window (a second poisoned
            # batch in the same window is caught on the next pass)
        # every batch in the window was dropped: the scope is exactly the
        # snapshot state — no new boundary to mark
        return []

    def _mark_boundary(self, result):
        """A validated (all-healthy) sync point: snapshot the scope and
        roll the checkpoint cadence."""
        step = self._current_step()
        snap = snapshot_scope(self.scope, step)
        snap.aux = self.detector.state()
        self._snapshots.append(snap)
        _metrics.gauge("resilience/snapshot_bytes").set(snap.nbytes)
        if (self._manager is not None and self.checkpoint_every > 0
                and (self._last_ckpt_step is None
                     or step - self._last_ckpt_step
                     >= self.checkpoint_every)):
            self._save_checkpoint(snap, result)

    def _save_checkpoint(self, snap, result, blocking=False):
        with _tracing.span("resilience/checkpoint", step=snap.step):
            # snapshot state is already a private host copy — skip the
            # manager's defensive re-copy (a full-model memcpy)
            self._manager.save(snap.state, snap.step, blocking=blocking,
                               host_copied=True)
        self._last_ckpt_step = snap.step
        result.checkpoints_saved += 1
        _metrics.counter("resilience/checkpoints").inc()

    def _drain_preempted(self, pending, result, signum):
        """SIGTERM/SIGINT path: finish what is in flight, validate it,
        write an emergency checkpoint from the last validated state, and
        hand control back to the caller."""
        result.preempted = True
        _metrics.counter("resilience/preemptions").inc()
        _blackbox.record_event("preemption_drain", signum=signum,
                               in_flight=len(pending))
        with _tracing.span("resilience/preemption_drain"):
            self._validate(pending, result)
            self.exe.sync()
            if self._manager is not None:
                snap = (self._snapshots[-1] if self._snapshots
                        else snapshot_scope(self.scope))
                self._save_checkpoint(snap, result, blocking=True)
                self._manager.wait()
        _blackbox.dump("sigterm_drain")
        warnings.warn(
            "preemption signal %d: drained %d in-flight steps, state "
            "checkpointed at step %d" % (signum, len(pending),
                                         result.step), RuntimeWarning)

    # -- the loop ----------------------------------------------------------
    def run(self, feeds, steps=None):
        """Drive `feeds` (an iterable of feed dicts) through the guarded
        loop; `steps` bounds how many batches are consumed. Returns a
        `TrainResult` (check `.preempted` before assuming completion)."""
        result = TrainResult()
        result.step = self._current_step()
        # retry accounting is per run(): the budget replenishes, and the
        # batch-ordinal retry keys from a previous run's feeds must not
        # bleed onto this run's unrelated batches
        self._retries_left = self.retry_budget
        self._batch_retries = collections.Counter()
        guard = PreemptionGuard() if self.handle_preemption else None
        if guard is not None:
            guard.install()
        pending = []
        try:
            # the pre-run state is the rollback floor: an anomaly in the
            # FIRST window must have somewhere good to return to
            snap = snapshot_scope(self.scope)
            snap.aux = self.detector.state()
            self._snapshots.append(snap)
            if self._manager is not None and self._last_ckpt_step is None:
                # cadence counts from here — the pre-run state is not a
                # checkpoint worth paying a write for
                self._last_ckpt_step = self._current_step()
            it = iter(feeds)
            dispatched = 0  # batches consumed; doubles as batch identity
            while steps is None or dispatched < steps:
                # the scope counter advances synchronously at each
                # dispatch, so it IS the step number the next run uses
                gstep = self._current_step()
                if self.injector.fire_at_step("sigterm_at_step", gstep):
                    os.kill(os.getpid(), signal.SIGTERM)
                if guard is not None and guard.triggered is not None:
                    self._drain_preempted(pending, result, guard.triggered)
                    return result
                try:
                    feed = next(it)
                except StopIteration:
                    break
                # dispatch the (possibly injection-poisoned) copy but
                # remember the ORIGINAL: a retry after rollback re-feeds
                # clean data, exactly like a transient corruption
                dispatch_feed = self._maybe_corrupt(feed, gstep)
                try:
                    fetches = self._dispatch(dispatch_feed, gstep, result)
                except Exception as exc:  # noqa: BLE001 — classified below
                    if not is_transient_error(exc):
                        raise
                    result.retries += 1
                    _metrics.counter("resilience/retries").inc()
                    # rollback first: a budget-exhausted raise must leave
                    # the scope at last-good state (see _replay)
                    self._rollback(result)
                    self._consume_retry(exc)
                    records = [(p.gstep, p.key, p.feed) for p in pending]
                    records.append((gstep, dispatched, feed))
                    pending = self._replay(records, result)
                    dispatched += 1
                    if len(pending) >= self.guard_every:
                        pending = self._validate(pending, result)
                    continue
                pending.append(_Pending(gstep, dispatched, feed, fetches))
                dispatched += 1
                if len(pending) >= self.guard_every:
                    pending = self._validate(pending, result)
            if guard is not None and guard.triggered is not None:
                self._drain_preempted(pending, result, guard.triggered)
                return result
            self._validate(pending, result)
            if self._manager is not None and self._snapshots:
                snap = self._snapshots[-1]
                if self._last_ckpt_step != snap.step:
                    self._save_checkpoint(snap, result, blocking=True)
                self._manager.wait()
        finally:
            if guard is not None:
                guard.uninstall()
        return result
