"""Initializers (parity: python/paddle/fluid/initializer.py — Constant,
Uniform, Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray).

An initializer appends one init op for a param to the *startup* program;
the startup run is one jitted XLA computation producing all initial state.
"""

import contextlib

import numpy as np

from . import framework

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "TruncatedNormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
    "BilinearInitializer",
    "force_init_on_cpu",
    "init_on_cpu",
]

_global_seed_counter = [0]


def _next_seed(seed):
    if seed:
        return seed
    _global_seed_counter[0] += 1
    return _global_seed_counter[0]


_force_init_on_cpu_ = False


def force_init_on_cpu():
    """Current init_on_cpu state (parity: initializer.py:35). On TPU the
    flag is advisory: init ops always trace into the startup program's one
    jitted step, and XLA places constant folding host-side anyway."""
    return _force_init_on_cpu_


@contextlib.contextmanager
def init_on_cpu():
    """Scope forcing initializer ops onto the CPU (parity:
    initializer.py:53). Initializers created inside tag their fill ops
    with force_cpu, the same attr fill_constant honors."""
    global _force_init_on_cpu_
    pre_state = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = pre_state


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant",
            outputs={"Out": [var]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random",
            outputs={"Out": [var]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self.low, "max": self.high,
                   "__op_seed__": _next_seed(self.seed)},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random",
            outputs={"Out": [var]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale,
                   "__op_seed__": _next_seed(self.seed)},
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale,
                   "__op_seed__": _next_seed(self.seed)},
        )


def _fan_in_out(var):
    shape = var.shape
    if not shape:
        return 1, 1
    if len(shape) < 2:
        return int(shape[0]), int(shape[0])
    if len(shape) == 2:
        return int(shape[0]), int(shape[1])
    # conv weight [c_out, c_in, *k]: receptive = prod(k)
    receptive = int(np.prod(shape[2:]))
    fan_in = int(shape[1]) * receptive
    fan_out = int(shape[0]) * receptive
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fi + fo)))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fi))
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init (for conv_transpose)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs a 4-D weight")
        c_out, c_in, h, w = shape
        f = np.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(h):
            for j in range(w):
                v = (1 - abs(i / f - c)) * (1 - abs(j / f - c))
                weight[:, :, i, j] = v
        NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            type="assign_value",
            outputs={"Out": [var]},
            attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                   "values": self.value.tolist()},
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
