"""Dygraph multi-process data parallelism (parity: python/paddle/fluid/
dygraph/parallel.py — `Env` :30, `prepare_context` :54, `DataParallel`;
C++ side imperative/nccl_context.cc).

TPU-native: the NCCL parallel context (gen_nccl_id handshake + per-process
communicators) becomes `jax.distributed` process-group initialization; the
per-variable allreduce in DataParallel.apply_collective_grads becomes a
`jax.lax.pmean`-shaped host-side mean over the data-parallel group. On a
single process the wrappers are transparent, matching the reference's
behaviour when nranks == 1.
"""

import os

import numpy as np

from .layers import Layer

__all__ = ["Env", "prepare_context", "ParallelEnv", "DataParallel"]


class Env:
    """Trainer-process identity from PADDLE_* env vars (parity:
    dygraph/parallel.py Env — nranks/local_rank/trainer_endpoints)."""

    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_gpus", "0"))
        self._trainer_endpoints = os.getenv(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


ParallelEnv = Env


class _ParallelStrategy:
    def __init__(self, env):
        self.nranks = env.nranks
        self.local_rank = env.local_rank
        self.trainer_endpoints = env.trainer_endpoints
        self.current_endpoint = env.current_endpoint


def prepare_context(place=None):
    """Initialize the multi-process context and return the strategy object
    (parity: dygraph/parallel.py prepare_context — which spins an NCCL
    context; here: jax.distributed process-group init over DCN)."""
    env = Env()
    strategy = _ParallelStrategy(env)
    if env.nranks > 1:
        # fail fast like the reference NCCL prepare_context does when the
        # context cannot be established — silent single-process fallback
        # would train N diverging replicas
        coord = os.environ.get("PADDLE_COORDINATOR_ADDR")
        if not coord:
            raise RuntimeError(
                "prepare_context: PADDLE_TRAINERS_NUM=%d but "
                "PADDLE_COORDINATOR_ADDR is unset; set it to the rank-0 "
                "coordinator endpoint so jax.distributed can form the "
                "process group" % env.nranks)
        import jax

        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=env.nranks,
            process_id=env.local_rank)
    return strategy


class DataParallel(Layer):
    """Wrap a dygraph Layer for data-parallel training (parity:
    dygraph/parallel.py DataParallel: scale_loss + apply_collective_grads)."""

    def __init__(self, layers, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers
        self._strategy = strategy or _ParallelStrategy(Env())

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._strategy.nranks <= 1:
            return loss
        from .math_ops import mul

        return mul(loss, 1.0 / self._strategy.nranks)

    def apply_collective_grads(self):
        """Mean-allreduce every trainable grad over the dp group. With one
        process this is a no-op, matching the reference fast path."""
        if self._strategy.nranks <= 1:
            return
        import jax

        if jax.process_count() <= 1:
            raise RuntimeError(
                "apply_collective_grads: nranks=%d but the jax process "
                "group has a single process — call prepare_context() "
                "before training" % self._strategy.nranks)
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        # SUM across processes (reference AllReduceOpHandle semantics) —
        # pairs with scale_loss's 1/nranks so the result is the global mean
        for p in self._layers.parameters():
            if p._grad is None:
                continue
            g = multihost_utils.process_allgather(
                jnp.asarray(np.asarray(p._grad)))
            p._grad = np.asarray(jnp.sum(g, axis=0))

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_dict(self, *a, **kw):
        return self._layers.set_dict(*a, **kw)
