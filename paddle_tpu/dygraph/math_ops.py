"""Eager arithmetic helpers for VarBase."""

import numpy as np

from .base import VarBase, _current_tracer, to_variable


def _run(op_type, x, y=None, attrs=None):
    t = _current_tracer()
    ins = {"X": [to_variable(x)]}
    if y is not None:
        yv = to_variable(y) if not np.isscalar(y) else to_variable(
            np.full((1,), y, dtype=np.asarray(to_variable(x).value).dtype))
        ins["Y"] = [yv]
    outs = t.trace_op(op_type, ins, ["Out"], attrs or {})
    return outs["Out"][0]


def add(x, y):
    return _run("elementwise_add", x, y)


def sub(x, y):
    return _run("elementwise_sub", x, y)


def mul(x, y):
    return _run("elementwise_mul", x, y)


def div(x, y):
    return _run("elementwise_div", x, y)
