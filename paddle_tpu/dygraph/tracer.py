"""Tracer re-export (parity: python/paddle/fluid/dygraph/tracer.py:32)."""

from .base import Tracer

__all__ = ["Tracer"]
