"""Dygraph (eager) mode core (parity: python/paddle/fluid/dygraph/base.py
guard :29 / to_variable :47 + C++ imperative/ Tracer C21).

Eager semantics TPU-style: ops run immediately as JAX calls (async dispatch
gives the overlap the reference got from streams); a host-side tape records
(fwd impl, inputs, outputs) and `VarBase.backward()` replays it in reverse
through the same per-op `jax.vjp` machinery as the static path
(imperative/layer.cc:131 Autograd::RunBackward parity).
"""

import contextlib

import numpy as np

import jax
import jax.numpy as jnp

from .. import framework
from ..core.lowering import LoweringContext
from ..ops import registry

__all__ = ["guard", "to_variable", "no_grad", "enable_dygraph",
           "disable_dygraph", "enabled"]


class Tape:
    def __init__(self):
        self.entries = []  # (op_type, ins{slot:[VarBase]}, attrs, outs{slot:[VarBase]})
        self.recording = True


class Tracer:
    """Eager tracer (parity: imperative/tracer.h:50)."""

    def __init__(self):
        self.tape = Tape()
        self._op_counter = 0
        self._key = jax.random.PRNGKey(0)
        self.is_test = False
        # TracedLayer program capture (dygraph/jit.py): when a list, EVERY
        # traced op is appended — including stop-gradient ones the autograd
        # tape skips — so the captured Program is the full forward
        self.capture = None

    def ctx(self):
        self._op_counter += 1
        return LoweringContext(
            base_key=jax.random.fold_in(self._key, self._op_counter),
            is_test=self.is_test,
        )

    def trace_op(self, op_type, ins, outs_wanted, attrs):
        """Run op eagerly; return dict slot -> list[VarBase]."""
        opdef = registry.get(op_type)
        jins = {
            slot: [v.value if isinstance(v, VarBase) else jnp.asarray(v)
                   for v in vs]
            for slot, vs in ins.items() if vs
        }
        # the ctx (and its RNG key) is captured on the tape so the backward
        # vjp-recompute sees the IDENTICAL dropout mask / random draw
        ctx = self.ctx()
        outs = opdef.impl(ctx, jins, attrs)
        vouts = {}
        stop = all(
            getattr(v, "stop_gradient", True)
            for vs in ins.values() for v in vs
        ) or not opdef.differentiable
        for slot in outs_wanted:
            produced = outs.get(slot, [])
            vouts[slot] = [VarBase(p, stop_gradient=stop) for p in produced]
        if self.tape.recording and not stop:
            self.tape.entries.append(
                (op_type, dict(ins), dict(attrs), vouts, ctx))
        if self.capture is not None:
            self.capture.append((op_type, dict(ins), dict(attrs), vouts))
        return vouts


_tracer = None


def enabled():
    return _tracer is not None


def _current_tracer():
    return _tracer


def enable_dygraph(place=None):
    global _tracer
    _tracer = Tracer()
    framework._dygraph_tracer_ = _tracer


def disable_dygraph():
    global _tracer
    _tracer = None
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


@contextlib.contextmanager
def no_grad():
    t = _current_tracer()
    if t is None:
        yield
        return
    prev = t.tape.recording
    t.tape.recording = False
    try:
        yield
    finally:
        t.tape.recording = prev


class VarBase:
    """Eager tensor (parity: imperative/layer.h:116 VarBase)."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False):
        self.value = jnp.asarray(value)
        self.name = name
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad = None

    # -- info ---------------------------------------------------------------
    @property
    def shape(self):
        return list(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def set_value(self, value):
        """Overwrite in place, keeping shape and dtype (parity:
        framework.py VarBase.set_value — checkpoint restore / manual
        weight surgery)."""
        new = jnp.asarray(value)
        if tuple(new.shape) != tuple(self.value.shape):
            raise ValueError(
                "set_value shape mismatch: var %s vs value %s"
                % (tuple(self.value.shape), tuple(new.shape)))
        self.value = new.astype(self.value.dtype)

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    # -- autograd -----------------------------------------------------------
    def backward(self):
        t = _current_tracer()
        if t is None:
            raise RuntimeError("backward() outside dygraph.guard()")
        run_backward(self, t.tape)

    def __repr__(self):
        return "VarBase(shape=%s, dtype=%s)" % (self.shape, self.dtype)

    # arithmetic sugar
    def _binop(self, other, op):
        from . import math_ops

        return getattr(math_ops, op)(self, other)

    def __add__(self, o):
        return self._binop(o, "add")

    def __sub__(self, o):
        return self._binop(o, "sub")

    def __mul__(self, o):
        return self._binop(o, "mul")

    def __truediv__(self, o):
        return self._binop(o, "div")


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name)


def run_backward(root, tape):
    """Reverse-replay the tape accumulating grads into VarBase._grad
    (parity: imperative/layer.cc Autograd::RunBackward)."""
    grads = {}  # id(VarBase) -> jnp array
    grads[id(root)] = jnp.ones_like(root.value)
    for op_type, ins, attrs, vouts, fwd_ctx in reversed(tape.entries):
        opdef = registry.get(op_type)
        out_cots_needed = any(
            id(v) in grads for vs in vouts.values() for v in vs
        )
        if not out_cots_needed:
            continue
        jins = {
            slot: [v.value if isinstance(v, VarBase) else jnp.asarray(v)
                   for v in vs]
            for slot, vs in ins.items() if vs
        }
        diff_slots = [
            s for s in jins
            if s not in opdef.nondiff_inputs
            and any(jnp.issubdtype(x.dtype, jnp.inexact) for x in jins[s])
        ]
        const_ins = {s: v for s, v in jins.items() if s not in diff_slots}
        diff_ins = {s: jins[s] for s in diff_slots}

        def f(d):
            # replay with the forward op's OWN ctx: identical RNG draws
            return opdef.impl(fwd_ctx, {**const_ins, **d}, attrs)

        primal_out, vjp_fn = jax.vjp(f, diff_ins)
        cots = {}
        for slot, prim_list in primal_out.items():
            vlist = vouts.get(slot, [])
            cl = []
            for i, prim in enumerate(prim_list):
                g = None
                if i < len(vlist):
                    g = grads.get(id(vlist[i]))
                if g is not None and jnp.issubdtype(prim.dtype, jnp.inexact):
                    cl.append(g.astype(prim.dtype))
                elif jnp.issubdtype(jnp.result_type(prim), jnp.inexact):
                    cl.append(jnp.zeros_like(prim))
                else:
                    cl.append(np.zeros(np.shape(prim),
                                       dtype=jax.dtypes.float0))
            cots[slot] = cl
        (gd,) = vjp_fn(cots)
        for slot in diff_slots:
            for v, g in zip(ins[slot], gd[slot]):
                if not isinstance(v, VarBase) or v.stop_gradient:
                    continue
                if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                    continue
                prev = grads.get(id(v))
                grads[id(v)] = g if prev is None else prev + g
    # write grads back onto leaves
    for op_type, ins, attrs, vouts, _ctx in tape.entries:
        for vs in list(ins.values()) + list(vouts.values()):
            for v in vs:
                if isinstance(v, VarBase) and id(v) in grads:
                    g = grads[id(v)]
                    v._grad = g if v._grad is None else v._grad + g
                    del grads[id(v)]
