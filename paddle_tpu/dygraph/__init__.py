"""Dygraph (eager) mode (parity: python/paddle/fluid/dygraph/ + C++
imperative/ — SURVEY C21, call stack §3.4)."""

from . import base
from .base import guard, to_variable, no_grad, enable_dygraph, disable_dygraph
from .layers import Layer
from . import nn
from .nn import *  # noqa: F401,F403
from .checkpoint import save_dygraph, load_dygraph  # noqa: F401
from .tracer import Tracer  # noqa: F401

__all__ = ["guard", "to_variable", "no_grad", "Layer", "save_dygraph",
           "load_dygraph", "enable_dygraph", "disable_dygraph"] + nn.__all__
