"""Dygraph (eager) mode (parity: python/paddle/fluid/dygraph/ + C++
imperative/ — SURVEY C21, call stack §3.4)."""

from . import base
from .base import (guard, to_variable, no_grad, enabled, enable_dygraph,
                   disable_dygraph)
from .layers import Layer
from . import nn
from .nn import *  # noqa: F401,F403
from .checkpoint import save_dygraph, load_dygraph  # noqa: F401
from .jit import TracedLayer  # noqa: F401
from .tracer import Tracer  # noqa: F401
from . import parallel  # noqa: F401
from .parallel import (Env, ParallelEnv, prepare_context,  # noqa: F401
                       DataParallel)

__all__ = ["guard", "to_variable", "no_grad", "enabled", "Layer",
           "TracedLayer",
           "save_dygraph", "load_dygraph", "enable_dygraph",
           "disable_dygraph", "Env", "ParallelEnv", "prepare_context",
           "DataParallel"] + nn.__all__
