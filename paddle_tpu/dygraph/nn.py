"""Dygraph layer classes (parity: python/paddle/fluid/dygraph/nn.py — FC,
Conv2D, Pool2D, BatchNorm, Embedding, LayerNorm, GRUUnit, PRelu,
BilinearTensorProduct, Conv2DTranspose, ...)."""

import numpy as np

from .base import VarBase, _current_tracer, to_variable
from .layers import Layer
from ..initializer import Constant, Normal, Xavier
from ..param_attr import ParamAttr

__all__ = ["Conv2D", "Conv3D", "Pool2D", "FC", "Linear", "BatchNorm",
           "Embedding", "LayerNorm", "GRUUnit", "PRelu",
           "BilinearTensorProduct", "Conv2DTranspose", "Conv3DTranspose",
           "SpectralNorm", "GroupNorm", "NCE", "Dropout", "SequenceConv",
           "RowConv", "TreeConv"]


def _trace(op_type, ins, outs, attrs=None):
    return _current_tracer().trace_op(op_type, ins, outs, attrs or {})


class FC(Layer):
    def __init__(self, name_scope, size, num_flatten_dims=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = ParamAttr._to_attr(param_attr)
        self._bias_attr = bias_attr
        self._act = act
        self._w = None
        self._b = None

    def _build_once(self, input):
        in_features = int(np.prod(input.shape[self._num_flatten_dims:]))
        self._w = self.create_parameter(
            [in_features, self._size], self._dtype,
            attr=self._param_attr)
        self.add_parameter("w", self._w)
        if self._bias_attr is not False:
            self._b = self.create_parameter([self._size], self._dtype,
                                            is_bias=True)
            self.add_parameter("b", self._b)

    def forward(self, input):
        if self._w is None:
            self._build_once(input)
        out = _trace("mul", {"X": [input], "Y": [self._w]}, ["Out"],
                     {"x_num_col_dims": self._num_flatten_dims,
                      "y_num_col_dims": 1})["Out"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": self._num_flatten_dims})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Linear(FC):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__("linear", output_dim, 1, param_attr, bias_attr, act,
                         dtype)
        self._w = self.create_parameter([input_dim, output_dim], dtype,
                                        attr=self._param_attr)
        self.add_parameter("w", self._w)
        if bias_attr is not False:
            self._b = self.create_parameter([output_dim], dtype, is_bias=True)
            self.add_parameter("b", self._b)


class Conv2D(Layer):
    def __init__(self, name_scope, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 2
        self._stride = stride if isinstance(stride, (list, tuple)) else [stride] * 2
        self._padding = padding if isinstance(padding, (list, tuple)) else [padding] * 2
        self._dilation = dilation if isinstance(dilation, (list, tuple)) else [dilation] * 2
        self._groups = groups or 1
        self._act = act
        self._param_attr = ParamAttr._to_attr(param_attr)
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def _build_once(self, input):
        c_in = input.shape[1]
        std = (2.0 / (self._filter_size[0] * self._filter_size[1] * c_in)) ** 0.5
        init = self._param_attr.initializer or Normal(0.0, std)
        self._w = self.create_parameter(
            [self._num_filters, c_in // self._groups] + self._filter_size,
            self._dtype, initializer=init)
        self.add_parameter("w", self._w)
        if self._bias_attr is not False:
            self._b = self.create_parameter([self._num_filters], self._dtype,
                                            is_bias=True)
            self.add_parameter("b", self._b)

    def forward(self, input):
        if self._w is None:
            self._build_once(input)
        out = _trace("conv2d", {"Input": [input], "Filter": [self._w]},
                     ["Output"],
                     {"strides": list(self._stride),
                      "paddings": list(self._padding),
                      "dilations": list(self._dilation),
                      "groups": self._groups})["Output"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": 1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Conv3D(Layer):
    def __init__(self, name_scope, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        _l = lambda v: list(v) if isinstance(v, (list, tuple)) else [v] * 3
        self._num_filters = num_filters
        self._filter_size = _l(filter_size)
        self._stride = _l(stride)
        self._padding = _l(padding)
        self._dilation = _l(dilation)
        self._groups = groups or 1
        self._act = act
        self._param_attr = ParamAttr._to_attr(param_attr)
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None:
            c_in = input.shape[1]
            fan_in = c_in * int(np.prod(self._filter_size))
            init = self._param_attr.initializer or Normal(
                0.0, (2.0 / fan_in) ** 0.5)
            self._w = self.create_parameter(
                [self._num_filters, c_in // self._groups] + self._filter_size,
                self._dtype, initializer=init)
            self.add_parameter("w", self._w)
            if self._bias_attr is not False:
                self._b = self.create_parameter([self._num_filters],
                                                self._dtype, is_bias=True)
                self.add_parameter("b", self._b)
        out = _trace("conv3d", {"Input": [input], "Filter": [self._w]},
                     ["Output"],
                     {"strides": list(self._stride),
                      "paddings": list(self._padding),
                      "dilations": list(self._dilation),
                      "groups": self._groups})["Output"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": 1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Conv2DTranspose(Layer):
    def __init__(self, name_scope, num_filters, filter_size, padding=0,
                 stride=1, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 2
        self._stride = stride if isinstance(stride, (list, tuple)) else [stride] * 2
        self._padding = padding if isinstance(padding, (list, tuple)) else [padding] * 2
        self._dilation = dilation if isinstance(dilation, (list, tuple)) else [dilation] * 2
        self._groups = groups or 1
        self._act = act
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None:
            c_in = input.shape[1]
            self._w = self.create_parameter(
                [c_in, self._num_filters // self._groups] + self._filter_size,
                self._dtype)
            self.add_parameter("w", self._w)
            self._b = self.create_parameter([self._num_filters], self._dtype,
                                            is_bias=True)
            self.add_parameter("b", self._b)
        out = _trace("conv2d_transpose",
                     {"Input": [input], "Filter": [self._w]}, ["Output"],
                     {"strides": list(self._stride),
                      "paddings": list(self._padding),
                      "dilations": list(self._dilation),
                      "groups": self._groups})["Output"][0]
        out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                     ["Out"], {"axis": 1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Conv3DTranspose(Layer):
    def __init__(self, name_scope, num_filters, filter_size, padding=0,
                 stride=1, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        _l = lambda v: list(v) if isinstance(v, (list, tuple)) else [v] * 3
        self._num_filters = num_filters
        self._filter_size = _l(filter_size)
        self._stride = _l(stride)
        self._padding = _l(padding)
        self._dilation = _l(dilation)
        self._groups = groups or 1
        self._act = act
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None:
            c_in = input.shape[1]
            self._w = self.create_parameter(
                [c_in, self._num_filters // self._groups] + self._filter_size,
                self._dtype)
            self.add_parameter("w", self._w)
            if self._bias_attr is not False:
                self._b = self.create_parameter([self._num_filters],
                                                self._dtype, is_bias=True)
                self.add_parameter("b", self._b)
        out = _trace("conv3d_transpose",
                     {"Input": [input], "Filter": [self._w]}, ["Output"],
                     {"strides": list(self._stride),
                      "paddings": list(self._padding),
                      "dilations": list(self._dilation),
                      "groups": self._groups})["Output"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": 1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class SequenceConv(Layer):
    """Context-window convolution over a [B, T, D] padded sequence batch
    (parity: dygraph/nn.py SequenceConv / sequence_conv_op.cc)."""

    def __init__(self, name_scope, num_filters, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._act = act
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None:
            d = input.shape[-1]
            self._w = self.create_parameter(
                [self._filter_size * d, self._num_filters], self._dtype)
            self.add_parameter("w", self._w)
            if self._bias_attr is not False:
                self._b = self.create_parameter([self._num_filters],
                                                self._dtype, is_bias=True)
                self.add_parameter("b", self._b)
        out = _trace("sequence_conv",
                     {"X": [input], "Filter": [self._w]}, ["Out"],
                     {"contextLength": self._filter_size,
                      "contextStart": -(self._filter_size // 2)})["Out"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": -1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class RowConv(Layer):
    """Lookahead row convolution (parity: dygraph/nn.py RowConv /
    row_conv_op.cc) on a [B, T, D] padded batch."""

    def __init__(self, name_scope, future_context_size, param_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._k = future_context_size + 1
        self._act = act
        self._w = None

    def forward(self, input):
        if self._w is None:
            d = input.shape[-1]
            self._w = self.create_parameter([self._k, d], self._dtype)
            self.add_parameter("w", self._w)
        out = _trace("row_conv", {"X": [input], "Filter": [self._w]},
                     ["Out"])["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class TreeConv(Layer):
    """Tree-based convolution (parity: dygraph/nn.py TreeConv /
    tree_conv_op.cc, TBCNN)."""

    def __init__(self, name_scope, output_size, num_filters=1,
                 max_depth=8, act=None, param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._output_size = output_size
        self._num_filters = num_filters
        self._act = act
        self._bias_attr = bias_attr
        self._w = None
        self._b = None

    def forward(self, nodes_vector, edge_set):
        if self._w is None:
            d = nodes_vector.shape[-1]
            self._w = self.create_parameter(
                [d, 3, self._output_size, self._num_filters], self._dtype)
            self.add_parameter("w", self._w)
            if self._bias_attr is not False:
                self._b = self.create_parameter(
                    [self._num_filters], self._dtype, is_bias=True)
                self.add_parameter("b", self._b)
        out = _trace("tree_conv",
                     {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                      "Filter": [self._w]}, ["Out"])["Out"][0]
        if self._b is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self._b]},
                         ["Out"], {"axis": -1})["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        _l = lambda v: v if isinstance(v, (list, tuple)) else [v] * 2
        self._attrs = {
            "pooling_type": pool_type, "ksize": _l(pool_size),
            "strides": _l(pool_stride), "paddings": _l(pool_padding),
            "global_pooling": global_pooling, "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return _trace("pool2d", {"X": [input]}, ["Out"], self._attrs)["Out"][0]


class BatchNorm(Layer):
    def __init__(self, name_scope, num_channels, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=False, fuse_with_relu=False,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(name_scope, dtype)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self.scale = self.create_parameter([num_channels], dtype,
                                           initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], dtype, is_bias=True)
        self._mean = VarBase(np.zeros([num_channels], np.float32),
                             stop_gradient=True, persistable=True)
        self._variance = VarBase(np.ones([num_channels], np.float32),
                                 stop_gradient=True, persistable=True)
        self.add_parameter("scale", self.scale)
        self.add_parameter("offset", self.bias)

    def forward(self, input):
        outs = _trace(
            "batch_norm",
            {"X": [input], "Scale": [self.scale], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            ["Y", "MeanOut", "VarianceOut"],
            {"momentum": self._momentum, "epsilon": self._epsilon,
             "is_test": not self.training,
             "data_layout": self._data_layout,
             "use_global_stats": self._use_global_stats})
        # moving stats update in place
        self._mean.value = outs["MeanOut"][0].value
        self._variance.value = outs["VarianceOut"][0].value
        out = outs["Y"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._padding_idx = -1 if padding_idx is None else padding_idx
        attr = ParamAttr._to_attr(param_attr)
        init = attr.initializer or Xavier()
        self.weight = self.create_parameter(size, dtype, initializer=init)
        self.add_parameter("weight", self.weight)

    def forward(self, input):
        return _trace("lookup_table",
                      {"W": [self.weight], "Ids": [input]}, ["Out"],
                      {"padding_idx": self._padding_idx})["Out"][0]


class LayerNorm(Layer):
    def __init__(self, name_scope, scale=True, shift=True,
                 begin_norm_axis=1, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32",
                 normalized_shape=None):
        super().__init__(name_scope, dtype)
        self._begin_norm_axis = begin_norm_axis
        self._epsilon = epsilon
        self._act = act
        self._scale_flag = scale
        self._shift_flag = shift
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None and self._scale_flag:
            feat = int(np.prod(input.shape[self._begin_norm_axis:]))
            self._w = self.create_parameter([feat], self._dtype,
                                            initializer=Constant(1.0))
            self.add_parameter("scale", self._w)
            if self._shift_flag:
                self._b = self.create_parameter([feat], self._dtype,
                                                is_bias=True)
                self.add_parameter("bias", self._b)
        ins = {"X": [input]}
        if self._w is not None:
            ins["Scale"] = [self._w]
        if self._b is not None:
            ins["Bias"] = [self._b]
        out = _trace("layer_norm", ins, ["Y"],
                     {"begin_norm_axis": self._begin_norm_axis,
                      "epsilon": self._epsilon})["Y"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__("dropout")
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        return _trace("dropout", {"X": [input]}, ["Out"],
                      {"dropout_prob": self._p, "is_test": not self.training,
                       "dropout_implementation": self._impl})["Out"][0]


class GRUUnit(Layer):
    def __init__(self, name_scope, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size  # 3 * hidden
        hidden = size // 3
        self._hidden = hidden
        self.weight = self.create_parameter([hidden, 3 * hidden], dtype)
        self.add_parameter("weight", self.weight)
        self.bias = self.create_parameter([1, 3 * hidden], dtype, is_bias=True)
        self.add_parameter("bias", self.bias)
        self._activation = activation
        self._gate_activation = gate_activation
        self._origin_mode = origin_mode

    def forward(self, input, hidden):
        outs = _trace(
            "gru_unit",
            {"Input": [input], "HiddenPrev": [hidden],
             "Weight": [self.weight], "Bias": [self.bias]},
            ["Hidden", "Gate", "ResetHiddenPrev"],
            {"activation": self._activation,
             "gate_activation": self._gate_activation,
             "origin_mode": self._origin_mode})
        return outs["Hidden"][0], outs["ResetHiddenPrev"][0], outs["Gate"][0]


class PRelu(Layer):
    def __init__(self, name_scope, mode, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        self._param_attr = param_attr
        self._alpha = None

    def forward(self, input):
        if self._alpha is None:
            if self._mode == "all":
                shape = [1]
            elif self._mode == "channel":
                shape = [1, input.shape[1], 1, 1]
            else:
                shape = [1] + list(input.shape[1:])
            self._alpha = self.create_parameter(shape, self._dtype,
                                                initializer=Constant(0.25))
            self.add_parameter("alpha", self._alpha)
        return _trace("prelu", {"X": [input], "Alpha": [self._alpha]},
                      ["Out"], {"mode": self._mode})["Out"][0]


class BilinearTensorProduct(Layer):
    def __init__(self, name_scope, size, name=None, act=None,
                 param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._act = act
        self._w = None
        self._b = None

    def forward(self, x, y):
        if self._w is None:
            self._w = self.create_parameter(
                [self._size, x.shape[1], y.shape[1]], self._dtype)
            self.add_parameter("w", self._w)
            self._b = self.create_parameter([1, self._size], self._dtype,
                                            is_bias=True)
            self.add_parameter("b", self._b)
        out = _trace("bilinear_tensor_product",
                     {"X": [x], "Y": [y], "Weight": [self._w],
                      "Bias": [self._b]}, ["Out"])["Out"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class SpectralNorm(Layer):
    def __init__(self, name_scope, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        self._u = None
        self._v = None

    def forward(self, weight):
        if self._u is None:
            h = weight.shape[self._dim]
            w = int(np.prod(weight.shape)) // h
            self._u = VarBase(np.random.randn(h).astype(np.float32),
                              stop_gradient=True, persistable=True)
            self._v = VarBase(np.random.randn(w).astype(np.float32),
                              stop_gradient=True, persistable=True)
        return _trace("spectral_norm",
                      {"Weight": [weight], "U": [self._u], "V": [self._v]},
                      ["Out"],
                      {"dim": self._dim, "power_iters": self._power_iters,
                       "eps": self._eps})["Out"][0]


class GroupNorm(Layer):
    def __init__(self, name_scope, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, data_layout="NCHW",
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self._w = None
        self._b = None

    def forward(self, input):
        if self._w is None:
            c = input.shape[1]
            self._w = self.create_parameter([c], self._dtype,
                                            initializer=Constant(1.0))
            self._b = self.create_parameter([c], self._dtype, is_bias=True)
            self.add_parameter("scale", self._w)
            self.add_parameter("bias", self._b)
        out = _trace("group_norm",
                     {"X": [input], "Scale": [self._w], "Bias": [self._b]},
                     ["Y"],
                     {"groups": self._groups, "epsilon": self._epsilon})["Y"][0]
        if self._act:
            out = _trace(self._act, {"X": [out]}, ["Out"])["Out"][0]
        return out


class NCE(Layer):
    """API-parity NCE head; on TPU lowers to sampled softmax fallback."""

    def __init__(self, name_scope, num_total_classes, param_attr=None,
                 bias_attr=None, num_neg_samples=None, sampler="uniform",
                 custom_dist=None, seed=0, is_sparse=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_total_classes = num_total_classes
        self._w = None

    def forward(self, input, label, sample_weight=None):
        if self._w is None:
            d = input.shape[-1]
            self._w = self.create_parameter(
                [self._num_total_classes, d], self._dtype)
            self._b = self.create_parameter([self._num_total_classes],
                                            self._dtype, is_bias=True)
            self.add_parameter("w", self._w)
            self.add_parameter("b", self._b)
        logits = _trace("matmul", {"X": [input], "Y": [self._w]}, ["Out"],
                        {"transpose_Y": True})["Out"][0]
        logits = _trace("elementwise_add", {"X": [logits], "Y": [self._b]},
                        ["Out"], {"axis": -1})["Out"][0]
        outs = _trace("softmax_with_cross_entropy",
                      {"Logits": [logits], "Label": [label]},
                      ["Loss"], {})
        return outs["Loss"][0]
