"""dygraph.Layer base (parity: python/paddle/fluid/dygraph/layers.py:31)."""

import numpy as np

import jax

from .base import VarBase, _current_tracer
from .. import unique_name

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = unique_name.generate(
            (name_scope or self.__class__.__name__.lower()))
        self._dtype = dtype
        self._parameters = {}
        self._sub_layers = {}
        self.training = True

    def full_name(self):
        return self._full_name

    def create_parameter(self, shape, dtype=None, initializer=None,
                         attr=None, is_bias=False):
        from ..initializer import Constant, Xavier

        init = initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = Constant(0.0) if is_bias else Xavier()
        key = jax.random.PRNGKey(abs(hash(self._full_name + str(len(
            self._parameters)))) % (2**31))
        val = _materialize_init(init, shape, dtype or self._dtype, key)
        name = unique_name.generate(self._full_name + (".b" if is_bias else ".w"))
        p = VarBase(val, name=name, stop_gradient=False, persistable=True)
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers=True):
        # __setattr__ auto-registers persistable VarBase attrs AND layers
        # call add_parameter explicitly, so the same object can appear under
        # two names ('_w' and 'w') — dedupe by identity
        out, seen = [], set()
        for p in self._parameters.values():
            if id(p) not in seen:
                seen.add(id(p))
                out.append(p)
        if include_sublayers:
            for l in self._sub_layers.values():
                for p in l.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        out.append(p)
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def train(self):
        self.training = True
        t = _current_tracer()
        if t:
            t.is_test = False
        for l in self.sublayers():
            l.training = True

    def eval(self):
        self.training = False
        t = _current_tracer()
        if t:
            t.is_test = True
        for l in self.sublayers():
            l.training = False

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def state_dict(self, include_sublayers=True, prefix=""):
        out = {}
        for k, p in self._parameters.items():
            out[prefix + k] = p.numpy()
        if include_sublayers:
            for name, l in self._sub_layers.items():
                out.update(l.state_dict(prefix=prefix + name + "."))
        return out

    def set_dict(self, state, include_sublayers=True, prefix=""):
        for k, p in self._parameters.items():
            if prefix + k in state:
                p.value = jax.numpy.asarray(state[prefix + k])
        if include_sublayers:
            for name, l in self._sub_layers.items():
                l.set_dict(state, prefix=prefix + name + ".")

    load_dict = set_dict

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and value.persistable:
            self.__dict__.setdefault("_parameters", {})[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


def _materialize_init(init, shape, dtype, key):
    """Evaluate a static-graph Initializer eagerly for dygraph params."""
    from .. import initializer as I

    shape = tuple(shape)
    if isinstance(init, I.ConstantInitializer):
        return np.full(shape, init.value, dtype=np.float32)
    if isinstance(init, I.UniformInitializer):
        return np.asarray(jax.random.uniform(
            key, shape, minval=init.low, maxval=init.high))
    if isinstance(init, I.NormalInitializer):
        return np.asarray(jax.random.normal(key, shape) * init.scale + init.loc)
    if isinstance(init, I.TruncatedNormalInitializer):
        return np.asarray(jax.random.truncated_normal(key, -2, 2, shape)
                          * init.scale + init.loc)
    if isinstance(init, I.XavierInitializer):
        fi, fo = I._fan_in_out(_FakeVar(shape))
        fi = init.fan_in or fi
        fo = init.fan_out or fo
        if init.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return np.asarray(jax.random.uniform(key, shape, minval=-limit,
                                                 maxval=limit))
        std = float(np.sqrt(2.0 / (fi + fo)))
        return np.asarray(jax.random.normal(key, shape) * std)
    if isinstance(init, I.MSRAInitializer):
        fi, _ = I._fan_in_out(_FakeVar(shape))
        fi = init.fan_in or fi
        if init.uniform:
            limit = float(np.sqrt(6.0 / fi))
            return np.asarray(jax.random.uniform(key, shape, minval=-limit,
                                                 maxval=limit))
        return np.asarray(jax.random.normal(key, shape)
                          * float(np.sqrt(2.0 / fi)))
    if isinstance(init, I.NumpyArrayInitializer):
        return init.value.reshape(shape)
    raise TypeError("unsupported initializer %r for dygraph" % (init,))


class _FakeVar:
    def __init__(self, shape):
        self.shape = shape
