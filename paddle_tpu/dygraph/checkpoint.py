"""Dygraph checkpoint save/load (parity: python/paddle/fluid/dygraph/
checkpoint.py — save/load state dict per Layer)."""

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    np.savez(model_path + ".pdparams", **arrays)


def load_dygraph(model_path):
    path = model_path + ".pdparams.npz"
    if not os.path.exists(path):
        path = model_path + ".pdparams"
    with np.load(path) as data:
        state = {k: data[k] for k in data.files}
    return state, None
