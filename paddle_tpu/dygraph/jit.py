"""TracedLayer — capture an eager Layer call into a static Program
(parity: python/paddle/fluid/dygraph/jit.py TracedLayer of the reference
line; SURVEY C21 + the round-3 VERDICT's dygraph-to-jit item).

Why it matters on TPU: eager ops dispatch one XLA computation each and pay
the per-call launch floor (~ms over the axon tunnel — BASELINE.md's
dygraph row), so an eager model is launch-bound. Tracing the SAME Layer
object records every executed op into a Program; running that through the
Executor compiles the whole forward into ONE jitted XLA step with the
program cache — static-graph speed from dygraph code, and the artifact
feeds save_inference_model / the serving exporter unchanged.

    with fluid.dygraph.guard():
        model = MyLayer()
        out, traced = fluid.dygraph.TracedLayer.trace(model, [to_variable(x)])
        fast = traced([x2])                 # one jitted step
        traced.save_inference_model("./sd") # standard inference artifact
"""

import numpy as np

from .. import framework
from ..core.scope import Scope, scope_guard
from .base import VarBase, _current_tracer

__all__ = ["TracedLayer"]


class TracedLayer:
    """A static Program recorded from one eager forward, plus the scope
    holding the layer's parameter values. Construct via `trace`."""

    def __init__(self, program, feed_vars, fetch_vars, scope,
                 param_sources=()):
        self.program = program
        self._feed_vars = feed_vars
        self._fetch_vars = fetch_vars
        self._scope = scope
        # (scope name, live VarBase) pairs: the traced program SHARES the
        # dygraph parameter storage — continued eager training is visible
        # to later __call__/save (reference TracedLayer semantics)
        self._param_sources = list(param_sources)
        self._exe = None
        # pre-bound executor plan per feed signature (round-4 VERDICT
        # weak #5: Executor.run's per-call program scan / fetch
        # normalization / cache-key build cost ~17% at launch-bound step
        # sizes; the traced program is frozen, so bind once)
        self._steps = {}
        self._feed_names = [v.name for v in feed_vars]
        self._fetch_names = [v.name for v in fetch_vars]

    def _refresh_params(self):
        for name, vb in self._param_sources:
            if self._scope.get(name) is not vb.value:
                self._scope.set(name, vb.value)

    # ------------------------------------------------------------------
    @staticmethod
    def trace(layer, inputs):
        """Run `layer(*inputs)` once eagerly while recording every op;
        returns (eager outputs, TracedLayer). Inputs must be VarBase (use
        to_variable); control flow is captured AS EXECUTED on these
        example inputs — data-dependent Python branches freeze the taken
        path, exactly like the reference tracer."""
        tracer = _current_tracer()
        if tracer is None:
            raise RuntimeError(
                "TracedLayer.trace must run inside fluid.dygraph.guard()")
        if tracer.capture is not None:
            raise RuntimeError("TracedLayer.trace calls cannot nest")
        for v in inputs:
            if not isinstance(v, VarBase):
                raise TypeError(
                    "TracedLayer.trace inputs must be VarBase "
                    "(fluid.dygraph.to_variable), got %r" % (type(v),))
        tracer.capture = []
        try:
            outs = layer(*inputs)
        finally:
            entries, tracer.capture = tracer.capture, None
        out_list = list(outs) if isinstance(outs, (list, tuple)) else [outs]

        program = framework.Program()
        block = program.global_block()
        scope = Scope()
        var_of = {}  # id(VarBase) -> program Variable
        param_sources = []  # (scope name, VarBase) for live params

        def _var_for(v):
            """Map an eager value to a program Variable, creating inputs/
            params/constants on first sight."""
            if isinstance(v, VarBase):
                key = id(v)
                if key in var_of:
                    return var_of[key]
                if v.persistable:
                    name = v.name or framework.unique_name.generate(
                        "traced_param")
                    pv = block.create_var(
                        name=name, shape=tuple(v.value.shape),
                        dtype=str(v.value.dtype), persistable=True)
                    scope.set(name, v.value)
                    param_sources.append((name, v))
                else:
                    # an eager value born OUTSIDE the traced call (e.g. a
                    # to_variable constant): bake it in as a persistable
                    name = framework.unique_name.generate("traced_const")
                    pv = block.create_var(
                        name=name, shape=tuple(v.value.shape),
                        dtype=str(v.value.dtype), persistable=True)
                    scope.set(name, v.value)
                var_of[key] = pv
                return pv
            arr = np.asarray(v)
            name = framework.unique_name.generate("traced_const")
            pv = block.create_var(name=name, shape=tuple(arr.shape),
                                  dtype=str(arr.dtype), persistable=True)
            scope.set(name, arr)
            return pv

        # the example inputs become feed vars
        feed_vars = []
        for i, v in enumerate(inputs):
            name = "traced_input_%d" % i
            pv = block.create_var(name=name, shape=tuple(v.value.shape),
                                  dtype=str(v.value.dtype), is_data=True)
            var_of[id(v)] = pv
            feed_vars.append(pv)

        for op_type, ins, attrs, vouts in entries:
            prog_ins = {slot: [_var_for(v) for v in vs]
                        for slot, vs in ins.items() if vs}
            prog_outs = {}
            for slot, vs in vouts.items():
                ovs = []
                for v in vs:
                    name = framework.unique_name.generate("traced_var")
                    pv = block.create_var(name=name,
                                          shape=tuple(v.value.shape),
                                          dtype=str(v.value.dtype))
                    var_of[id(v)] = pv
                    ovs.append(pv)
                prog_outs[slot] = ovs
            block.append_op(type=op_type, inputs=prog_ins,
                            outputs=prog_outs, attrs=dict(attrs))

        fetch_vars = []
        for v in out_list:
            if id(v) not in var_of:
                raise RuntimeError(
                    "traced output was not produced by a recorded op — "
                    "return values must flow through layer ops")
            fetch_vars.append(var_of[id(v)])
        return outs, TracedLayer(program, feed_vars, fetch_vars, scope,
                                 param_sources)

    # ------------------------------------------------------------------
    def __call__(self, inputs):
        """Run the captured Program as ONE jitted executor step; returns a
        list of numpy arrays (one per traced output).

        The executor plan is PRE-BOUND: the traced program is frozen at
        trace time, so the compiled step binds directly to (feed
        signature) — no per-call program scan, fetch normalization, or
        strong-cache key construction (Executor.run's generality tax,
        measured at ~17% on launch-bound steps, BASELINE.md dygraph
        row)."""
        from ..executor import _CompiledStep, _feed_signature
        from ..flags import flag

        self._refresh_params()
        feed = {}
        for pv, v in zip(self._feed_vars, inputs):
            feed[pv.name] = v.value if isinstance(v, VarBase) \
                else np.asarray(v)
        key = (_feed_signature(feed), bool(flag("check_nan_inf")))
        step = self._steps.get(key)
        if step is None:
            step = _CompiledStep(self.program, self._feed_names,
                                 self._fetch_names, self._scope)
            self._steps[key] = step
        return [np.asarray(f) for f in step.run(self._scope, feed)]

    # ------------------------------------------------------------------
    def save_inference_model(self, dirname, feed=None, fetch=None):
        """Persist the captured Program + parameters as the standard
        inference artifact (io.save_inference_model), loadable by the
        AnalysisPredictor / serving exporter. `feed`/`fetch` select by
        index into the traced inputs/outputs (reference signature)."""
        from .. import io
        from ..executor import Executor
        from ..core.place import default_place

        feed_vars = (self._feed_vars if feed is None
                     else [self._feed_vars[i] for i in feed])
        fetch_vars = (self._fetch_vars if fetch is None
                      else [self._fetch_vars[i] for i in fetch])
        exe = Executor(default_place())
        self._refresh_params()
        with scope_guard(self._scope):
            io.save_inference_model(
                dirname, [v.name for v in feed_vars], fetch_vars, exe,
                main_program=self.program)
