"""Fleet — high-level distributed API (parity: incubate/fleet/base/
fleet_base.py:38 `Fleet.init/init_worker/init_server/distributed_optimizer`;
collective mode incubate/fleet/collective/__init__.py:72
CollectiveOptimizer; role makers reading PADDLE_* env vars,
test_fit_a_line.py:75-93).

TPU-native: the collective backend is the JAX distributed runtime over
ICI/DCN (jax.distributed.initialize replaces gen_nccl_id RPC + NCCLContextMap
— SURVEY §5.8). Parameter-server roles map onto the same worker set: the
"server" capability (sharded optimizer state) is ShardedAdam
(parallel/zero.py), selected via DistributeTranspilerConfig-style options.
"""

import os

from . import mesh as mesh_mod

__all__ = ["Fleet", "fleet", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "DistributedStrategy"]


class PaddleCloudRoleMaker:
    """Reads the PADDLE_* env contract (fleet_base.py / role_maker.py):
    PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS,
    PADDLE_CURRENT_ENDPOINT, TRAINING_ROLE."""

    def __init__(self, is_collective=True):
        self._is_collective = is_collective
        self._id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = eps.split(",") if eps else []
        self._current = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER")

    def worker_index(self):
        return self._id

    def worker_num(self):
        return self._num

    def is_worker(self):
        return self._role == "TRAINER"

    def is_server(self):
        return self._role == "PSERVER"

    def is_first_worker(self):
        return self._id == 0

    def get_trainer_endpoints(self):
        return self._endpoints


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, role="TRAINER", worker_num=1,
                 server_endpoints=None, is_collective=True):
        super().__init__(is_collective)
        self._id = current_id
        self._num = worker_num
        self._role = "TRAINER" if role in ("TRAINER", 1) else "PSERVER"
        self._endpoints = server_endpoints or []


class DistributedStrategy:
    """CollectiveOptimizer strategy knobs (+ the TPU-native extensions)."""

    def __init__(self):
        self.mode = "collective"       # collective | sharded (reduce/ZeRO)
        self.nccl_comm_num = 1         # accepted for parity; unused (ICI)
        self.use_dgc = False
        self.dgc_sparsity = 0.99
        self.gradient_merge_k = 1      # multi-batch-merge (P10)
        self.amp = False


class Fleet:
    """Singleton facade (fleet_base.py:38)."""

    def __init__(self):
        self._role_maker = None
        self._strategy = None

    # -- lifecycle (init :175, init_worker :207, init_server :211) ---------
    def init(self, role_maker=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        # multi-host bring-up: replaces gen_nccl_id_op + NCCLContextMap
        # rank joining (platform/nccl_helper.h:130)
        if self._role_maker.worker_num() > 1 and os.environ.get(
                "PADDLE_COORDINATOR_ADDR"):
            import jax

            jax.distributed.initialize(
                coordinator_address=os.environ["PADDLE_COORDINATOR_ADDR"],
                num_processes=self._role_maker.worker_num(),
                process_id=self._role_maker.worker_index())
        return self

    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        pass

    def stop_worker(self):
        pass

    def barrier_worker(self):
        import jax

        # a tiny psum over all devices acts as the barrier
        if self.worker_num() > 1:
            import jax.numpy as jnp

            jax.block_until_ready(
                jax.pmap(lambda x: jax.lax.psum(x, "i"), "i")(
                    jnp.ones((jax.local_device_count(),))))

    # -- info ---------------------------------------------------------------
    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return (self._role_maker.is_worker() if self._role_maker else True)

    def is_server(self):
        return (self._role_maker.is_server() if self._role_maker else False)

    def server_num(self):
        return (self._role_maker.server_num()
                if self._role_maker and hasattr(self._role_maker,
                                                "server_num") else 0)

    def worker_endpoints(self):
        return (self._role_maker.get_trainer_endpoints()
                if self._role_maker else [])

    # -- the main entry (distributed_optimizer :223) ------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(optimizer, self._strategy, self)


class CollectiveOptimizer:
    """Wraps a fluid-API optimizer for data-parallel training
    (incubate/fleet/collective/__init__.py:72). minimize() behaves like the
    wrapped optimizer; the Program is then run through
    CompiledProgram.with_data_parallel, where gradient allreduce comes from
    sharding propagation over the mesh (compiler.py), replacing the nccl2
    transpile at :130-134."""

    def __init__(self, optimizer, strategy, fleet_ref):
        self._optimizer = optimizer
        self._strategy = strategy
        self._fleet = fleet_ref

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        # mark the program so CompiledProgram picks the data-parallel path
        prog = loss.block.program
        prog._fleet_opt = {
            "mode": self._strategy.mode,
            "use_dgc": self._strategy.use_dgc,
            "gradient_merge_k": self._strategy.gradient_merge_k,
        }
        return result

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


fleet = Fleet()
