"""Host-offloaded sharded embedding tables (M5 / SURVEY §7: the
parameter-server capability — giant sparse embeddings served from pserver
RAM, P6/P7 distributed lookup table + Downpour — becomes host-RAM sharding
on TPU).

The table lives in host memory (numpy), sharded by row hash across
`num_shards` logical shards (the pserver endpoints of the reference). The
device-side op gathers only the rows a step touches via jax.pure_callback
(a few KB over PCIe instead of the whole table in HBM), and the backward
pass pushes sparse row gradients back with jax.experimental.io_callback —
the TPU analogue of PullSparseVarsSync/PushSparseVarsWithLabelAsync
(framework/fleet/fleet_wrapper.h:62/:95).

The prefetched fast path (docs/RECOMMENDER.md) replaces the in-step
pure_callback gather: the HostEmbeddingPrefetcher announces batch t+1's
ids a step ahead and the compiled step reads the staged [n, dim] buffer
through `prefetched_embedding_lookup` instead."""


import numpy as np

from ..observability import metrics as _metrics

__all__ = ["HostEmbeddingTable", "host_embedding_lookup",
           "prefetched_embedding_lookup", "EmbeddingStateError",
           "tables_state_dict", "load_tables_state_dict"]

_TABLES = {}


class EmbeddingStateError(ValueError):
    """A table state_dict does not match the table's geometry (shard
    count, row split or embedding dim). Raised by load_state_dict instead
    of numpy's cryptic broadcast error (or, worse, a silent broadcast)."""


def fold_ids(ids, mod):
    """THE id-folding rule, shared by every host-side path (table
    hash_ids, DataFeedDesc.set_hash_mod): reinterpret signed ids as
    uint64 (bit-pattern wraparound, the convention for feature hashes)
    and reduce modulo `mod`. One definition so training-time folds and
    serving-time pull(raw_ids) always agree."""
    ids = np.asarray(ids)
    u = ids.astype(np.uint64) if ids.dtype != np.uint64 else ids
    return (u % np.uint64(mod)).astype(np.int64)


class HostEmbeddingTable:
    """Sharded host-RAM embedding with built-in sparse SGD/Adagrad update
    (the pserver's optimizer block, distribute_lookup_table.py parity)."""

    def __init__(self, name, num_rows, dim, num_shards=1, optimizer="sgd",
                 learning_rate=0.1, init_scale=0.01, seed=0,
                 dtype=np.float32, hash_ids=False):
        if name in _TABLES:
            raise ValueError("embedding table %r already exists" % name)
        self.name = name
        self.num_rows = num_rows
        self.dim = dim
        # raw ids outside [0, num_rows) (e.g. uint64 feature hashes) are
        # folded into the row space on the HOST — the device graph never
        # carries 64-bit ids (JAX canonicalizes int64 device arrays to
        # int32; lookup_sparse_table's auto-growth becomes fixed-size
        # modulo hashing)
        self.hash_ids = hash_ids
        self._pusher = None
        self.num_shards = num_shards
        self.optimizer = optimizer
        self.learning_rate = learning_rate
        rng = np.random.RandomState(seed)
        # row i lives on shard i % num_shards (RoundRobin dispatch parity);
        # storage is one array per shard to mirror pserver ownership
        self._shards = []
        for s in range(num_shards):
            rows = len(range(s, num_rows, num_shards))
            self._shards.append(
                (rng.rand(rows, dim).astype(dtype) - 0.5) * 2 * init_scale)
        if optimizer == "adagrad":
            self._accum = [np.zeros_like(sh) for sh in self._shards]
        from ..analysis.concurrency import make_lock

        self._lock = make_lock("parallel.host_table")
        # applied-push observers (HostEmbeddingPrefetcher coherence): each
        # fn(global_rows, n_pushes) fires AFTER an optimizer application,
        # outside the table lock, on whichever thread applied it
        self._push_observers = []
        _TABLES[name] = self

    # -- shard addressing -------------------------------------------------

    def _locate(self, ids):
        ids = np.asarray(ids).reshape(-1)
        # keep unsigned 64-bit hashes exact until the fold (a plain int64
        # cast of a uint64 above 2^63 would go negative)
        ids = ids.astype(np.uint64 if ids.dtype == np.uint64 else np.int64)
        if self.hash_ids:
            ids = fold_ids(ids, self.num_rows)
        else:
            ids = ids.astype(np.int64)
            if ids.size and (ids.min() < 0 or ids.max() >= self.num_rows):
                raise ValueError(
                    "table %r: id out of range [0, %d) — construct the "
                    "table with hash_ids=True to fold raw feature hashes "
                    "into the row space" % (self.name, self.num_rows))
        shard = ids % self.num_shards
        local = ids // self.num_shards
        return shard, local

    def global_rows(self, ids):
        """Fold raw ids into canonical table row indices ([N] int64 in
        [0, num_rows)). The prefetcher keys its dedup/cache maps on these
        so training-time folds and pull(raw_ids) agree by construction."""
        shard, local = self._locate(ids)
        return local * self.num_shards + shard

    @staticmethod
    def _shard_groups(shard):
        """Group flat positions by owning shard with ONE stable argsort
        instead of num_shards full boolean-mask passes (the old
        O(num_shards·N) loop made 64-shard tables pay 64 scans per
        step). Stable order keeps each group's positions in original
        request order, so duplicate-id gradient accumulation is bitwise
        the masked loop's. Yields (shard_idx, positions)."""
        order = np.argsort(shard, kind="stable")
        uniq, starts = np.unique(shard[order], return_index=True)
        bounds = np.append(starts, order.size)
        for k in range(uniq.size):
            yield int(uniq[k]), order[bounds[k]:bounds[k + 1]]

    # -- pull / push (the RPC surface of the reference) -------------------

    def pull(self, ids):
        """Gather rows for `ids` ([N] int) -> [N, dim]."""
        shard, local = self._locate(ids)
        out = np.empty((len(shard), self.dim), self._shards[0].dtype)
        with self._lock:
            if self.num_shards == 1:
                out[...] = self._shards[0][local]
            else:
                for s, sel in self._shard_groups(shard):
                    out[sel] = self._shards[s][local[sel]]
        if _metrics.enabled():
            _metrics.counter("embed/pull_rows").inc(len(shard))
        return out

    def push(self, ids, grads):
        """Sparse update: scatter row grads back through the optimizer.
        With a Communicator attached the (ids, grads) pair is queued and
        applied by the background send thread (communicator.cc:100
        SendThread parity); otherwise applied inline."""
        pusher = self._pusher
        if pusher is not None:
            pusher.enqueue(np.asarray(ids).copy(), np.asarray(grads).copy())
            return
        self._apply_push(ids, grads)

    def _apply_push(self, ids, grads, n_pushes=1):
        """O(touched rows) work and memory: grads for duplicate ids are
        segment-summed into a [n_touched, dim] buffer — never a dense
        full-shard array (the 1e8-row use case this module exists for).
        `n_pushes` is how many logical step-pushes this application
        carries (the Communicator merges before applying)."""
        shard, local = self._locate(ids)
        grads = np.asarray(grads).reshape(len(shard), self.dim)
        lr = self.learning_rate
        touched_total = 0
        with self._lock:
            if self.num_shards == 1:
                groups = [(0, None)]
            else:
                groups = self._shard_groups(shard)
            for s, sel in groups:
                if sel is None:
                    rows, g_in = local, grads
                else:
                    rows, g_in = local[sel], grads[sel]
                touched, inv = np.unique(rows, return_inverse=True)
                g = np.zeros((len(touched), self.dim),
                             self._shards[s].dtype)
                np.add.at(g, inv, g_in)  # duplicate ids accumulate
                if self.optimizer == "adagrad":
                    acc = self._accum[s][touched] + g * g
                    self._accum[s][touched] = acc
                    self._shards[s][touched] -= lr * g / (np.sqrt(acc)
                                                          + 1e-6)
                else:  # sgd
                    self._shards[s][touched] -= lr * g
                touched_total += len(touched)
        if _metrics.enabled():
            _metrics.counter("embed/push_rows").inc(touched_total)
        if self._push_observers:
            rows_global = local * self.num_shards + shard
            for fn in list(self._push_observers):
                fn(rows_global, n_pushes)

    # -- push observation (prefetcher coherence) --------------------------

    def add_push_observer(self, fn):
        self._push_observers.append(fn)

    def remove_push_observer(self, fn):
        try:
            self._push_observers.remove(fn)
        except ValueError:
            pass

    # -- whole-table io (checkpoint parity io.py:280) ---------------------

    def state_dict(self):
        d = {"shard_%d" % s: sh for s, sh in enumerate(self._shards)}
        if self.optimizer == "adagrad":
            d.update({"accum_%d" % s: a for s, a in enumerate(self._accum)})
        return d

    def load_state_dict(self, d):
        """Restore shard (and adagrad accumulator) arrays, validating
        every entry against the table geometry first — a state saved
        from a table with a different shard count, row count or dim
        raises EmbeddingStateError naming the mismatch instead of numpy
        broadcasting (or crashing) row-splits together."""
        extra = sorted(k for k in d
                       if k.startswith(("shard_", "accum_"))
                       and int(k.split("_")[1]) >= self.num_shards)
        if extra:
            raise EmbeddingStateError(
                "table %r has %d shards but the state carries %s — it "
                "was saved from a table with a different num_shards"
                % (self.name, self.num_shards, extra))
        staged = []
        for s in range(self.num_shards):
            key = "shard_%d" % s
            if key not in d:
                raise EmbeddingStateError(
                    "table %r: state is missing %r (table has %d shards; "
                    "state keys: %s)"
                    % (self.name, key, self.num_shards, sorted(d)))
            arr = np.asarray(d[key])
            if arr.shape != self._shards[s].shape:
                raise EmbeddingStateError(
                    "table %r shard %d: state has shape %s but the table "
                    "(num_rows=%d, dim=%d, num_shards=%d) holds %s — "
                    "geometry must match exactly"
                    % (self.name, s, arr.shape, self.num_rows, self.dim,
                       self.num_shards, self._shards[s].shape))
            staged.append((self._shards[s], arr))
            if self.optimizer == "adagrad" and ("accum_%d" % s) in d:
                acc = np.asarray(d["accum_%d" % s])
                if acc.shape != self._accum[s].shape:
                    raise EmbeddingStateError(
                        "table %r accum_%d: state has shape %s but the "
                        "table holds %s"
                        % (self.name, s, acc.shape, self._accum[s].shape))
                staged.append((self._accum[s], acc))
        # validate-then-commit: a mid-load raise must not leave the table
        # half old state, half new
        with self._lock:
            for dst, src in staged:
                dst[...] = src

    @staticmethod
    def get(name):
        try:
            return _TABLES[name]
        except KeyError:
            raise KeyError(
                "no host embedding table named %r; existing tables: %s"
                % (name, sorted(_TABLES) or "(none)")) from None

    @staticmethod
    def reset_registry():
        _TABLES.clear()


def tables_state_dict():
    """{table_name: state_dict} for every registered table — the sparse
    half of a training checkpoint (flush the Communicator first; see
    checkpoint.host_embedding_state)."""
    return {name: t.state_dict() for name, t in _TABLES.items()}


def load_tables_state_dict(state):
    """Restore tables_state_dict() output into the live registry. Every
    named table must already exist (tables are created by model build,
    not by restore) and match geometry."""
    for name, d in state.items():
        HostEmbeddingTable.get(name).load_state_dict(d)


def host_embedding_lookup(table_name, ids, anchor=None):
    """JAX-traceable lookup with sparse push-on-backward.

    Forward: pure_callback gather of the touched rows. Backward: io_callback
    that pushes the row gradients into the table's optimizer — gradients
    never materialize a dense [num_rows, dim] array on device.

    `anchor` is a float scalar the gradient machinery differentiates with
    respect to (ids are integers, so without it no cotangent would reach
    this op and the push would never fire); its returned grad is zero."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    table = _TABLES[table_name]
    dim = table.dim
    if anchor is None:
        anchor = jnp.zeros((), jnp.float32)

    @jax.custom_vjp
    def lookup(anchor_, ids_):
        flat = ids_.reshape((-1,))
        out = jax.pure_callback(
            lambda i: _TABLES[table_name].pull(i),
            jax.ShapeDtypeStruct((flat.shape[0], dim), np.float32),
            flat)
        return out.reshape(ids_.shape + (dim,))

    def fwd(anchor_, ids_):
        return lookup(anchor_, ids_), (anchor_, ids_)

    def bwd(res, ct):
        anchor_, ids_ = res
        flat = ids_.reshape((-1,))
        g = ct.reshape((-1, dim))
        io_callback(
            lambda i, gg: _TABLES[table_name].push(i, gg),
            None, flat, g, ordered=True)
        ids_grad = (jnp.zeros(ids_.shape, ids_.dtype)
                    if jnp.issubdtype(ids_.dtype, jnp.inexact) else
                    np.zeros(np.shape(ids_), jax.dtypes.float0))
        return (jnp.zeros_like(anchor_), ids_grad)

    lookup.defvjp(fwd, bwd)
    return lookup(anchor, ids)


def _zero_cotangent(x):
    import jax
    import jax.numpy as jnp

    if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
        return jnp.zeros(jnp.shape(x), jnp.result_type(x))
    return np.zeros(np.shape(x), jax.dtypes.float0)


def prefetched_embedding_lookup(table_name, ids, anchor, rows, inv,
                                hit=None, slot=None, cache=None):
    """The prefetch fast path of host_embedding_lookup (docs/
    RECOMMENDER.md): no host callback in the forward. `rows` is the
    [n, dim] unique-row buffer the HostEmbeddingPrefetcher gathered a
    step ahead, `inv` the [n_flat_ids] inverse indices into it. With the
    hot-row cache on, `hit`/`slot` mark unique rows served from the
    device-resident `cache` array instead of the staged buffer.

    The backward is EXACTLY the legacy one — an ordered io_callback push
    of (flat ids, row grads) — so post-push table state is bitwise the
    synchronous path's on the same id/grad stream."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    dim = _TABLES[table_name].dim
    has_cache = cache is not None
    extras = (hit, slot, cache) if has_cache else ()

    @jax.custom_vjp
    def lookup(anchor_, ids_, rows_, inv_, extras_):
        if extras_:
            hit_, slot_, cache_ = extras_
            uniq = jnp.where((hit_ != 0)[:, None],
                             cache_[slot_], rows_)
        else:
            uniq = rows_
        out = uniq[inv_]
        return out.reshape(ids_.shape + (dim,))

    def fwd(anchor_, ids_, rows_, inv_, extras_):
        return lookup(anchor_, ids_, rows_, inv_, extras_), \
            (anchor_, ids_, rows_, inv_, extras_)

    def bwd(res, ct):
        anchor_, ids_, rows_, inv_, extras_ = res
        flat = ids_.reshape((-1,))
        g = ct.reshape((-1, dim))
        io_callback(
            lambda i, gg: _TABLES[table_name].push(i, gg),
            None, flat, g, ordered=True)
        return (jnp.zeros_like(anchor_), _zero_cotangent(ids_),
                _zero_cotangent(rows_), _zero_cotangent(inv_),
                tuple(_zero_cotangent(x) for x in extras_))

    lookup.defvjp(fwd, bwd)
    return lookup(anchor, ids, rows, inv, extras)
