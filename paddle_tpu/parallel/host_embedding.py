"""Host-offloaded sharded embedding tables (M5 / SURVEY §7: the
parameter-server capability — giant sparse embeddings served from pserver
RAM, P6/P7 distributed lookup table + Downpour — becomes host-RAM sharding
on TPU).

The table lives in host memory (numpy), sharded by row hash across
`num_shards` logical shards (the pserver endpoints of the reference). The
device-side op gathers only the rows a step touches via jax.pure_callback
(a few KB over PCIe instead of the whole table in HBM), and the backward
pass pushes sparse row gradients back with jax.experimental.io_callback —
the TPU analogue of PullSparseVarsSync/PushSparseVarsWithLabelAsync
(framework/fleet/fleet_wrapper.h:62/:95)."""


import numpy as np

__all__ = ["HostEmbeddingTable", "host_embedding_lookup"]

_TABLES = {}


def fold_ids(ids, mod):
    """THE id-folding rule, shared by every host-side path (table
    hash_ids, DataFeedDesc.set_hash_mod): reinterpret signed ids as
    uint64 (bit-pattern wraparound, the convention for feature hashes)
    and reduce modulo `mod`. One definition so training-time folds and
    serving-time pull(raw_ids) always agree."""
    ids = np.asarray(ids)
    u = ids.astype(np.uint64) if ids.dtype != np.uint64 else ids
    return (u % np.uint64(mod)).astype(np.int64)


class HostEmbeddingTable:
    """Sharded host-RAM embedding with built-in sparse SGD/Adagrad update
    (the pserver's optimizer block, distribute_lookup_table.py parity)."""

    def __init__(self, name, num_rows, dim, num_shards=1, optimizer="sgd",
                 learning_rate=0.1, init_scale=0.01, seed=0,
                 dtype=np.float32, hash_ids=False):
        if name in _TABLES:
            raise ValueError("embedding table %r already exists" % name)
        self.name = name
        self.num_rows = num_rows
        self.dim = dim
        # raw ids outside [0, num_rows) (e.g. uint64 feature hashes) are
        # folded into the row space on the HOST — the device graph never
        # carries 64-bit ids (JAX canonicalizes int64 device arrays to
        # int32; lookup_sparse_table's auto-growth becomes fixed-size
        # modulo hashing)
        self.hash_ids = hash_ids
        self._pusher = None
        self.num_shards = num_shards
        self.optimizer = optimizer
        self.learning_rate = learning_rate
        rng = np.random.RandomState(seed)
        # row i lives on shard i % num_shards (RoundRobin dispatch parity);
        # storage is one array per shard to mirror pserver ownership
        self._shards = []
        for s in range(num_shards):
            rows = len(range(s, num_rows, num_shards))
            self._shards.append(
                (rng.rand(rows, dim).astype(dtype) - 0.5) * 2 * init_scale)
        if optimizer == "adagrad":
            self._accum = [np.zeros_like(sh) for sh in self._shards]
        from ..analysis.concurrency import make_lock

        self._lock = make_lock("parallel.host_table")
        _TABLES[name] = self

    # -- shard addressing -------------------------------------------------

    def _locate(self, ids):
        ids = np.asarray(ids).reshape(-1)
        # keep unsigned 64-bit hashes exact until the fold (a plain int64
        # cast of a uint64 above 2^63 would go negative)
        ids = ids.astype(np.uint64 if ids.dtype == np.uint64 else np.int64)
        if self.hash_ids:
            ids = fold_ids(ids, self.num_rows)
        else:
            ids = ids.astype(np.int64)
            if ids.size and (ids.min() < 0 or ids.max() >= self.num_rows):
                raise ValueError(
                    "table %r: id out of range [0, %d) — construct the "
                    "table with hash_ids=True to fold raw feature hashes "
                    "into the row space" % (self.name, self.num_rows))
        shard = ids % self.num_shards
        local = ids // self.num_shards
        return shard, local

    # -- pull / push (the RPC surface of the reference) -------------------

    def pull(self, ids):
        """Gather rows for `ids` ([N] int) -> [N, dim]."""
        shard, local = self._locate(ids)
        out = np.empty((len(shard), self.dim), self._shards[0].dtype)
        with self._lock:
            for s in range(self.num_shards):
                m = shard == s
                if m.any():
                    out[m] = self._shards[s][local[m]]
        return out

    def push(self, ids, grads):
        """Sparse update: scatter row grads back through the optimizer.
        With a Communicator attached the (ids, grads) pair is queued and
        applied by the background send thread (communicator.cc:100
        SendThread parity); otherwise applied inline."""
        pusher = self._pusher
        if pusher is not None:
            pusher.enqueue(np.asarray(ids).copy(), np.asarray(grads).copy())
            return
        self._apply_push(ids, grads)

    def _apply_push(self, ids, grads):
        """O(touched rows) work and memory: grads for duplicate ids are
        segment-summed into a [n_touched, dim] buffer — never a dense
        full-shard array (the 1e8-row use case this module exists for)."""
        shard, local = self._locate(ids)
        grads = np.asarray(grads).reshape(len(shard), self.dim)
        lr = self.learning_rate
        with self._lock:
            for s in range(self.num_shards):
                m = shard == s
                if not m.any():
                    continue
                rows = local[m]
                touched, inv = np.unique(rows, return_inverse=True)
                g = np.zeros((len(touched), self.dim),
                             self._shards[s].dtype)
                np.add.at(g, inv, grads[m])  # duplicate ids accumulate
                if self.optimizer == "adagrad":
                    acc = self._accum[s][touched] + g * g
                    self._accum[s][touched] = acc
                    self._shards[s][touched] -= lr * g / (np.sqrt(acc)
                                                          + 1e-6)
                else:  # sgd
                    self._shards[s][touched] -= lr * g

    # -- whole-table io (checkpoint parity io.py:280) ---------------------

    def state_dict(self):
        d = {"shard_%d" % s: sh for s, sh in enumerate(self._shards)}
        if self.optimizer == "adagrad":
            d.update({"accum_%d" % s: a for s, a in enumerate(self._accum)})
        return d

    def load_state_dict(self, d):
        for s in range(self.num_shards):
            self._shards[s][...] = d["shard_%d" % s]
            if self.optimizer == "adagrad" and ("accum_%d" % s) in d:
                self._accum[s][...] = d["accum_%d" % s]

    @staticmethod
    def get(name):
        return _TABLES[name]

    @staticmethod
    def reset_registry():
        _TABLES.clear()


def host_embedding_lookup(table_name, ids, anchor=None):
    """JAX-traceable lookup with sparse push-on-backward.

    Forward: pure_callback gather of the touched rows. Backward: io_callback
    that pushes the row gradients into the table's optimizer — gradients
    never materialize a dense [num_rows, dim] array on device.

    `anchor` is a float scalar the gradient machinery differentiates with
    respect to (ids are integers, so without it no cotangent would reach
    this op and the push would never fire); its returned grad is zero."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    table = _TABLES[table_name]
    dim = table.dim
    if anchor is None:
        anchor = jnp.zeros((), jnp.float32)

    @jax.custom_vjp
    def lookup(anchor_, ids_):
        flat = ids_.reshape((-1,))
        out = jax.pure_callback(
            lambda i: _TABLES[table_name].pull(i),
            jax.ShapeDtypeStruct((flat.shape[0], dim), np.float32),
            flat)
        return out.reshape(ids_.shape + (dim,))

    def fwd(anchor_, ids_):
        return lookup(anchor_, ids_), (anchor_, ids_)

    def bwd(res, ct):
        anchor_, ids_ = res
        flat = ids_.reshape((-1,))
        g = ct.reshape((-1, dim))
        io_callback(
            lambda i, gg: _TABLES[table_name].push(i, gg),
            None, flat, g, ordered=True)
        ids_grad = (jnp.zeros(ids_.shape, ids_.dtype)
                    if jnp.issubdtype(ids_.dtype, jnp.inexact) else
                    np.zeros(np.shape(ids_), jax.dtypes.float0))
        return (jnp.zeros_like(anchor_), ids_grad)

    lookup.defvjp(fwd, bwd)
    return lookup(anchor, ids)
