"""Structural checks on the lowered 1F1B pipeline step.

The schedule's claim — embedding only on stage 0, vocab head only on the
last stage — is enforced by lax.cond, which lowers to stablehlo.case. These
helpers parse the lowered module text and verify every vocab-sized
dot_general / embedding gather executes only under a conditional (directly
in a case/if region, or in an outlined private func reachable solely from
one). Used by tests/test_pipeline_1f1b.py and the driver's
dryrun_multichip per-stage FLOP assertion.
"""

import re

__all__ = ["case_region_spans", "func_spans", "make_inside_checker",
           "assert_stage_local_flops"]


def case_region_spans(text):
    """Line-index spans of stablehlo.case/if regions (inline in StableHLO)."""
    lines = text.splitlines()
    spans = []
    open_cases = []  # (start line, depth before the op)
    depth = 0
    for i, line in enumerate(lines):
        if "stablehlo.case" in line or "stablehlo.if" in line:
            open_cases.append((i, depth))
        depth += line.count("{") - line.count("}")
        while open_cases and depth <= open_cases[-1][1]:
            start, _ = open_cases.pop()
            spans.append((start, i))
    return spans


def func_spans(text):
    """[(name, start, end)] for every func.func in the module."""
    lines = text.splitlines()
    out = []
    cur = None
    depth = 0
    for i, line in enumerate(lines):
        m = re.search(r"func\.func.*?@([\w.]+)", line)
        if m and cur is None:
            cur = (m.group(1), i, depth)
        depth += line.count("{") - line.count("}")
        if cur is not None and depth <= cur[2]:
            out.append((cur[0], cur[1], i))
            cur = None
    return out


def make_inside_checker(text):
    """inside(i): line i executes only under a conditional — directly in a
    case/if region, or in an outlined private func whose every call site
    is (transitively) inside one."""
    lines = text.splitlines()
    spans = case_region_spans(text)
    funcs = func_spans(text)

    def enclosing_func(i):
        for name, a, b in funcs:
            if a < i <= b:
                return name
        return None

    memo = {}

    def inside(i, depth=0):
        if any(a < i < b for a, b in spans):
            return True
        if depth > 3:
            return False
        fn = enclosing_func(i)
        if fn is None or fn in memo:
            return memo.get(fn, False)
        memo[fn] = False  # cycle guard
        call_sites = [k for k, l in enumerate(lines)
                      if ("call @%s(" % fn) in l or ("call @%s " % fn) in l]
        ok = bool(call_sites) and all(
            inside(k, depth + 1) for k in call_sites)
        memo[fn] = ok
        return ok

    return inside, spans


def assert_stage_local_flops(lowered_text, vocab_size):
    """Raise if the vocab head or embedding gather appears in straight-line
    code of the pipeline step (i.e. every pp stage would compute it)."""
    inside, spans = make_inside_checker(lowered_text)
    if not spans:
        raise AssertionError(
            "pipeline step has no conditional regions — stage-local "
            "embed/head skipping is not in the lowering")
    lines = lowered_text.splitlines()
    dot_pat = re.compile(r"dot_general.*[<x]%d[x>]" % vocab_size)
    bad_dots = [i for i, l in enumerate(lines)
                if dot_pat.search(l) and not inside(i)]
    if bad_dots:
        raise AssertionError(
            "vocab-head dot_general in straight-line pipeline code "
            "(every stage would compute it): lines %r" % bad_dots[:5])
    gather_pat = re.compile(r"(gather|take).*%d" % vocab_size)
    bad_gathers = [i for i, l in enumerate(lines)
                   if "stablehlo" in l and gather_pat.search(l)
                   and not inside(i)]
    if bad_gathers:
        raise AssertionError(
            "embedding gather in straight-line pipeline code (every stage "
            "would embed): lines %r" % bad_gathers[:5])
