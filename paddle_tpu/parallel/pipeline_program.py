"""Any-program pipeline parallelism through the descriptor path.

The reference's defining multi-device contract is "rewrite ANY user program
for N devices" (framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:165) — but its builder only does data
parallelism. Pipeline parallelism is a new-design axis (SURVEY §5.7);
round 3 delivered it only inside the hand-written SPMD trainer
(parallel/transformer.py). This module brings the SAME 1F1B schedule to an
arbitrary Fluid program built from `fluid.layers`:

    strategy = BuildStrategy()
    strategy.pipeline_stages = 4            # pp axis size
    strategy.pipeline_microbatches = 8      # defaults to pp
    CompiledProgram(prog).with_data_parallel(loss_name=..., build_strategy=strategy)

Design (TPU-native, no graph rewrite):
 - The program's op list is [forward | backward | optimizer]; the forward
   section is split into `pp` contiguous stages, either by explicit
   `with fluid.pipeline_stage(i):` annotation or by a balanced-FLOP
   auto-split. Backward ops are NOT executed — each stage's gradients come
   from `jax.vjp` of its lowered forward (the same kernels the grad ops
   would re-run, so results are identical); optimizer/clip/regularizer ops
   then run unchanged on the accumulated grads.
 - One `shard_map` over the ("dp", "pp", "tp") step mesh, MANUAL over dp/pp
   and GSPMD-auto over tp: the 1F1B ring schedule (ppermute neighbor
   exchange, O(pp) input stash, fwd fill while bwd drains) is hand-written
   over the manual axes, while the planner's Megatron tp shardings keep
   working untouched inside every stage body.
 - Stage bodies become branches of one `lax.switch` on the pp rank index —
   SPMD requires every rank to run the same traced program; the switch
   executes only the resident stage's ops at run time.
 - Activations cross stage cuts as packed wire buffers (one fp32 buffer +
   one int32 buffer, padded to the widest cut) so heterogeneous cut
   signatures ride a single fixed-shape ppermute ring. Packing is
   reshape/cast/concat — exact for bf16/fp16/fp32 payloads and transparent
   to reverse-mode AD.

Semantics: microbatching requires the loss to be a MEAN over batch
elements (the usual Fluid `mean(cross_entropy)` shape); gradients then
equal the full-batch gradient exactly, which the parity test asserts
against the single-device executor. Ops with cross-batch state (batch_norm
running stats) are rejected with a clear error — use layer_norm or run BN
under dp-only parallelism.
"""

import numpy as np

import jax
import jax.numpy as jnp
from ..core.jax_compat import axis_index as _axis_index, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.lowering import LoweringContext, execute_op
from ..framework import dtype_to_np

__all__ = ["PipelineProgramStep", "split_sections", "assign_stages"]


# ---------------------------------------------------------------------------
# program analysis
# ---------------------------------------------------------------------------


def _is_backward_op(op):
    return "__fwd_op__" in op.attrs or op.attrs.get("__op_role__") == "backward"


def split_sections(block):
    """(fwd_ops, post_ops): forward ops before the first backward op, and
    the non-backward tail (optimizer / clip / regularizer / lr ops)."""
    ops = block.ops
    bwd = next((i for i, op in enumerate(ops) if _is_backward_op(op)), None)
    if bwd is None:
        return list(ops), []
    return list(ops[:bwd]), [op for op in ops[bwd:] if not _is_backward_op(op)]


def _numel(shape):
    n = 1
    for d in shape or ():
        if d is not None and d > 0:
            n *= d
    return n


def _op_cost(op):
    """Relative FLOP estimate for stage balancing. Static shapes with the
    batch dim as -1 are fine — only the ratio between ops matters."""
    sub_cost = 0.0
    for key in ("sub_block", "true_block", "false_block"):
        sub = op.attrs.get(key) if op.attrs else None
        if sub is not None and getattr(sub, "ops", None) is not None:
            sub_cost += sum(_op_cost(o) for o in sub.ops)
    out_n = sum(_numel(v.shape) for vs in op.outputs.values() for v in vs
                if v.shape is not None)
    t = op.type
    if t in ("mul", "matmul"):
        ys = op.inputs.get("Y", [])
        k = 1
        if ys and ys[0].shape and len(ys[0].shape) >= 2:
            k = max(1, ys[0].shape[-2] or 1)
        return sub_cost + 2.0 * out_n * k
    if t in ("conv2d", "depthwise_conv2d", "conv3d"):
        fs = op.inputs.get("Filter", [])
        k = _numel(fs[0].shape[1:]) if fs and fs[0].shape else 1
        return sub_cost + 2.0 * out_n * k
    if t == "flash_attention":
        qs = op.inputs.get("Q", [])
        seq = 1
        if qs and qs[0].shape and len(qs[0].shape) >= 2:
            seq = max(1, qs[0].shape[1] or 1)
        return sub_cost + 4.0 * out_n * seq
    return sub_cost + float(out_n)


def assign_stages(fwd_ops, pp):
    """Stage id per forward op: honor `__pipeline_stage__` stamps from
    `fluid.pipeline_stage(i)` when present (unstamped ops inherit the
    previous stamp), else balanced cumulative-cost auto-split into pp
    contiguous chunks."""
    stamped = [op.attrs.get("__pipeline_stage__") for op in fwd_ops]
    if any(s is not None for s in stamped):
        stages, cur = [], 0
        for i, s in enumerate(stamped):
            if s is not None:
                s = int(s)
                if s < cur:
                    raise ValueError(
                        "pipeline_stage annotations must be non-decreasing "
                        "in program order: op #%d (%s) is stage %d after "
                        "stage %d" % (i, fwd_ops[i].type, s, cur))
                cur = s
            if cur >= pp:
                raise ValueError(
                    "pipeline_stage %d out of range for pipeline_stages=%d"
                    % (cur, pp))
            stages.append(cur)
        return stages
    costs = [_op_cost(op) for op in fwd_ops]
    n = len(costs)
    if n < pp:
        raise ValueError(
            "cannot split %d forward ops into %d pipeline stages — "
            "reduce pipeline_stages/pipeline_virtual_stages" % (n, pp))
    # minimax contiguous partition into EXACTLY pp non-empty segments
    # (DP): unlike a greedy midpoint walk, one dominant op can never
    # leave an interior stage empty, and the bottleneck stage cost —
    # which sets the pipeline's tick time — is provably minimal
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)
    inf = float("inf")
    best = [[inf] * (n + 1) for _ in range(pp + 1)]
    cut = [[0] * (n + 1) for _ in range(pp + 1)]
    best[0][0] = 0.0
    for k in range(1, pp + 1):
        for j in range(k, n - (pp - k) + 1):
            for i in range(k - 1, j):
                v = max(best[k - 1][i], prefix[j] - prefix[i])
                if v < best[k][j]:
                    best[k][j] = v
                    cut[k][j] = i
    bounds = [n]
    j = n
    for k in range(pp, 0, -1):
        j = cut[k][j]
        bounds.append(j)
    bounds.reverse()
    stages = []
    for s in range(pp):
        stages.extend([s] * (bounds[s + 1] - bounds[s]))
    return stages


# ---------------------------------------------------------------------------
# wire packing: heterogeneous cut signatures over one fixed-shape ring
# ---------------------------------------------------------------------------


class _CutLayout:
    """Ordered (name, shape, np dtype) entries for one stage cut, split
    into float (fp32 wire, differentiable) and int (int32 wire) segments."""

    def __init__(self, entries):
        for n, _, d in entries:
            # the wire is fp32/int32: exact for every dtype JAX produces
            # with x64 disabled (the default); 64-bit payloads would be
            # silently narrowed, so reject them instead
            if np.dtype(d).itemsize > 4:
                raise NotImplementedError(
                    "activation %r crossing a pipeline stage cut has dtype "
                    "%s; the stage wire is fp32/int32 and would narrow it "
                    "(jax_enable_x64 programs are unsupported under "
                    "pipeline_stages > 1)" % (n, d))
        self.fent = [(n, s, d) for n, s, d in entries
                     if np.issubdtype(d, np.inexact)]
        self.ient = [(n, s, d) for n, s, d in entries
                     if not np.issubdtype(d, np.inexact)]
        self.nf = sum(_numel(s) for _, s, _ in self.fent)
        self.ni = sum(_numel(s) for _, s, _ in self.ient)

    def pack(self, env, nf_max, ni_max):
        fparts = [env[n].astype(jnp.float32).reshape(-1)
                  for n, _, _ in self.fent]
        iparts = [env[n].astype(jnp.int32).reshape(-1)
                  for n, _, _ in self.ient]
        f = (jnp.concatenate(fparts) if fparts
             else jnp.zeros((0,), jnp.float32))
        i = (jnp.concatenate(iparts) if iparts
             else jnp.zeros((0,), jnp.int32))
        return (jnp.pad(f, (0, nf_max - f.shape[0])),
                jnp.pad(i, (0, ni_max - i.shape[0])))

    def unpack(self, env, f, i):
        off = 0
        for n, s, d in self.fent:
            k = _numel(s)
            env[n] = jax.lax.slice_in_dim(f, off, off + k).reshape(s) \
                .astype(d)
            off += k
        off = 0
        for n, s, d in self.ient:
            k = _numel(s)
            env[n] = jax.lax.slice_in_dim(i, off, off + k).reshape(s) \
                .astype(d)
            off += k


class _ResidLayout:
    """Packed layout for one stage's vjp residual leaves (activation-
    stash mode): inexact leaves ride the fp32 buffer (bf16/f16/f32 cast
    is exact), 4-byte integer kinds bitcast onto the int32 buffer
    (uint32 RNG keys round-trip bit-exactly), narrower ints/bool ride
    int32 by value. The treedef is captured from an eval_shape probe of
    the SAME vjp the real trace runs, so unflattening stashed leaves at
    backward time reconstructs an identical vjp function."""

    def __init__(self, treedef, avals, rebind):
        self.treedef = treedef
        self.records = []  # (kind, shape, dtype, rebind_ref)
        for (shape, dtype), ref in zip(avals, rebind):
            d = np.dtype(dtype)
            if ref is not None:
                # this residual IS a live param/constant (identity-
                # matched at probe time): rebind at backward instead of
                # stashing N in-flight fp32 copies of the weights
                self.records.append(("rebind", tuple(shape), d, ref))
                continue
            if d == jax.dtypes.float0:
                # float0 cotangent placeholders (integer/bool primals in
                # the vjp) carry no bytes — strip them from the stash and
                # re-materialize zeros at unpack, the same treatment
                # core/lowering.py and dygraph/base.py give float0 grads
                kind = "float0"
            elif d == np.float64:
                # under jax_enable_x64 a float64 residual would silently
                # lose mantissa bits through the shared fp32 buffer —
                # refuse instead of downcasting (ADVICE round 5)
                raise NotImplementedError(
                    "pipeline_activation_stash cannot pack a float64 "
                    "residual losslessly through the fp32 stash buffer "
                    "(jax_enable_x64 run) — use the default recompute "
                    "mode for float64 models")
            elif np.issubdtype(d, np.inexact) or d == jnp.bfloat16:
                kind = "f"
            elif d.kind in "iub" and d.itemsize == 4:
                kind = "bitcast"
            elif d.kind in "iub" and d.itemsize < 4:
                kind = "i"
            else:
                raise NotImplementedError(
                    "pipeline_activation_stash cannot pack a residual of "
                    "dtype %s — use the default recompute mode" % d)
            self.records.append((kind, tuple(shape), d, None))
        self.nf = sum(_numel(s) for k, s, _, _ in self.records
                      if k == "f")
        self.ni = sum(_numel(s) for k, s, _, _ in self.records
                      if k in ("bitcast", "i"))

    def pack(self, leaves, nf_max, ni_max):
        fparts, iparts = [], []
        for leaf, (kind, s, d, _) in zip(leaves, self.records):
            if kind in ("rebind", "float0"):
                continue
            if kind == "f":
                fparts.append(leaf.astype(jnp.float32).reshape(-1))
            elif kind == "bitcast":
                iparts.append(jax.lax.bitcast_convert_type(
                    leaf, jnp.int32).reshape(-1))
            else:
                iparts.append(leaf.astype(jnp.int32).reshape(-1))
        f = (jnp.concatenate(fparts) if fparts
             else jnp.zeros((0,), jnp.float32))
        i = (jnp.concatenate(iparts) if iparts
             else jnp.zeros((0,), jnp.int32))
        return (jnp.pad(f, (0, nf_max - f.shape[0])),
                jnp.pad(i, (0, ni_max - i.shape[0])))

    def unpack(self, f, i, sources):
        """sources: {"d": dparam leaves, "c": cparam leaves} — the LIVE
        values rebound into their residual positions (constant within a
        step, so value-identical to what a stash would return)."""
        leaves = []
        foff = ioff = 0
        for kind, s, d, ref in self.records:
            if kind == "rebind":
                leaves.append(sources[ref[0]][ref[1]])
                continue
            if kind == "float0":
                leaves.append(np.zeros(s, dtype=jax.dtypes.float0))
                continue
            k = _numel(s)
            if kind == "f":
                leaves.append(jax.lax.slice_in_dim(f, foff, foff + k)
                              .reshape(s).astype(d))
                foff += k
            elif kind == "bitcast":
                leaves.append(jax.lax.bitcast_convert_type(
                    jax.lax.slice_in_dim(i, ioff, ioff + k).reshape(s),
                    d))
                ioff += k
            else:
                leaves.append(jax.lax.slice_in_dim(i, ioff, ioff + k)
                              .reshape(s).astype(d))
                ioff += k
        return leaves


# ---------------------------------------------------------------------------
# the pipelined step
# ---------------------------------------------------------------------------


class PipelineProgramStep:
    """One jitted dp×pp×tp step for an arbitrary Fluid training program.

    Built lazily per feed signature by CompiledProgram (same caching
    contract as _DataParallelStep)."""

    def __init__(self, program, feed_names, fetch_names, mesh,
                 build_strategy, loss_name):
        from ..compiler import BuildStrategy

        if loss_name is None:
            raise ValueError(
                "pipeline_stages > 1 needs with_data_parallel(loss_name=...) "
                "so the 1F1B schedule knows which scalar to differentiate")
        # Multi-process (DCN) meshes are allowed when the pp axis stays
        # within a process: the 1F1B ring's ppermute then rides local
        # devices (ICI on TPU pods) and only the dp gradient psum crosses
        # processes — the reference's multi-NODE shape (nccl_helper.h:130
        # multi-node ncclCommInitRank; dp between nodes, model parallel
        # within). A pp axis that itself spans processes needs
        # cross-process collective-permute, which XLA:CPU's Gloo backend
        # does not provide — on TPU (DCN ppermute exists) it is untested
        # here for lack of multi-host hardware, so refuse off-TPU.
        ax = mesh.axis_names.index("pp") if "pp" in mesh.axis_names else None
        if ax is not None:
            cols = np.moveaxis(mesh.devices, ax, 0)
            cols = cols.reshape(cols.shape[0], -1)
            pp_crosses = any(
                len({d.process_index for d in cols[:, j]}) > 1
                for j in range(cols.shape[1]))
            if pp_crosses and mesh.devices.flat[0].platform == "cpu":
                raise NotImplementedError(
                    "the pipeline axis spans processes, which needs "
                    "cross-process collective-permute (unavailable on "
                    "XLA:CPU). Lay out the mesh so pp is within a "
                    "process — dp over processes, pp/tp/sp within — or "
                    "run on a TPU pod slice.")
        from ..flags import flag as _flag

        if bool(_flag("check_nan_inf")):
            # per-op nan flags live inside the 1F1B scan's switch branches
            # and cannot be packed out per-tick; refuse loudly rather than
            # let a debugging user believe the checks are on
            raise NotImplementedError(
                "FLAGS_check_nan_inf is not supported under "
                "pipeline_stages > 1 — reproduce on a dp/tp mesh (or "
                "single device) to localize the NaN, then re-enable "
                "pipelining")
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.mesh = mesh
        from ..compiler import mesh_spans_processes

        self._multiprocess = mesh_spans_processes(mesh)
        self._mesh_devs = set(mesh.devices.flat)
        self.loss_name = loss_name
        block = program.global_block()
        self.block = block
        shape = dict(mesh.shape)
        self.dp = int(shape.get("dp", 1))
        self.pp = int(shape.get("pp", 1))
        self.M = int(getattr(build_strategy, "pipeline_microbatches", None)
                     or self.pp)
        if self.M < self.pp:
            raise ValueError(
                "pipeline_microbatches (%d) must be >= pipeline_stages (%d)"
                % (self.M, self.pp))
        self.v = int(getattr(build_strategy, "pipeline_virtual_stages", 1)
                     or 1)
        self.S = self.v * self.pp  # virtual stages; stage s on rank s%pp
        self.stash_activations = bool(getattr(
            build_strategy, "pipeline_activation_stash", False))
        self._seed = program.random_seed or 0
        from .pipeline_schedule import build_schedule

        self.schedule = build_schedule(self.pp, self.M, self.v)

        self.fwd_ops, self.post_ops = split_sections(block)
        if not any(_is_backward_op(op) for op in block.ops):
            raise ValueError(
                "pipeline_stages > 1 needs a training program (append "
                "backward via optimizer.minimize); for inference use "
                "dp/tp sharding instead")
        if self.v > 1 and any(
                op.attrs.get("__pipeline_stage__") is not None
                for op in self.fwd_ops):
            # explicit stamps mean PHYSICAL stages 0..pp-1; silently
            # reinterpreting them as virtual-stage ids would leave v-1
            # chunks empty (all of K's extra ticks, none of the win)
            raise NotImplementedError(
                "fluid.pipeline_stage(i) annotations name physical "
                "stages and do not compose with "
                "pipeline_virtual_stages > 1 — drop the annotations "
                "(the balanced auto-split spreads ops over all %d "
                "virtual chunks) or set pipeline_virtual_stages=1"
                % self.S)
        self.stage_of = assign_stages(self.fwd_ops, self.S)

        # ---- dataflow over the forward section -------------------------
        feed_set = set(self.feed_names)
        produced_at = {}
        last_use = {}
        for op, s in zip(self.fwd_ops, self.stage_of):
            for name in op.input_names():
                v = block._find_var_recursive(name)
                if name in feed_set or v is None or v.persistable:
                    continue
                if name in produced_at:
                    last_use[name] = max(last_use.get(name, s), s)
            for name in op.output_names():
                v = block._find_var_recursive(name)
                if v is not None and v.persistable:
                    raise ValueError(
                        "forward op %r writes persistable var %r — ops with "
                        "cross-batch state (batch_norm running stats) don't "
                        "commute with pipeline microbatching; use "
                        "layer_norm, or dp/tp parallelism for this model"
                        % (op.type, name))
                if name in feed_set:
                    # stage branches re-read feeds fresh each microbatch, so
                    # a later stage would silently see the pre-write value
                    raise ValueError(
                        "forward op %r writes feed var %r in place — "
                        "pipeline stages read feeds immutably; copy the "
                        "feed into a new var (e.g. layers.assign) first"
                        % (op.type, name))
                prev = produced_at.get(name)
                if prev is not None and prev != s:
                    # the cut-crossing sets track one producing stage per
                    # var; a rewrite in a later stage would make every
                    # earlier consumer read the wrong (not-yet-computed)
                    # value, so reject it up front
                    raise ValueError(
                        "var %r is rewritten in place at stage %d after "
                        "being produced at stage %d — in-place rewrites "
                        "across pipeline stages are unsupported; adjust "
                        "pipeline_stage annotations so all writes to a var "
                        "land in one stage" % (name, s, prev))
                produced_at[name] = s
        self.produced_at = produced_at
        # crossing[c]: produced at stage <= c, still consumed after cut c
        self.crossing = []
        for c in range(self.S - 1):
            names = sorted(
                n for n in produced_at
                if produced_at[n] <= c and last_use.get(n, -1) > c)
            self.crossing.append(names)

        # ---- parameters ------------------------------------------------
        fwd_reads = set()
        for op in self.fwd_ops:
            fwd_reads.update(op.input_names())
        pg = dict(getattr(program, "param_grad_map", {}) or {})
        self.dparam_names = sorted(
            p for p, g in pg.items()
            if p in fwd_reads and block._find_var_recursive(g) is not None)
        self.grad_of = {p: pg[p] for p in self.dparam_names}
        self.cparam_names = sorted(
            n for n in fwd_reads
            if n not in self.grad_of and n not in feed_set
            and (lambda v: v is not None and v.persistable)(
                block._find_var_recursive(n)))

        # ---- persistable state classification (jit signature) ----------
        from ..compiler import classify_persistable_state

        self.mut_names, self.const_names, self.state_out = \
            classify_persistable_state(block, self.fetch_names)

        # ---- scalar forward fetches (loss, metrics) --------------------
        post_produced = set()
        for op in self.post_ops:
            post_produced.update(op.output_names())
        self.post_produced = post_produced
        scalar = []
        for name in dict.fromkeys([self.loss_name] + self.fetch_names):
            if name in produced_at:
                v = block._find_var_recursive(name)
                if v is not None and v.shape is not None \
                        and _numel(v.shape) == 1 and -1 not in v.shape:
                    scalar.append(name)
                elif name in self.fetch_names:
                    raise ValueError(
                        "fetch %r is a non-scalar forward activation; under "
                        "pipeline parallelism activations live per-"
                        "microbatch per-stage. Fetch scalars (loss/metrics) "
                        "or persistables instead" % name)
        if self.loss_name not in scalar:
            raise ValueError(
                "loss %r must be a scalar produced by the forward section"
                % self.loss_name)
        self.scalar_names = scalar
        self.loss_idx = scalar.index(self.loss_name)
        self.loss_stage = produced_at[self.loss_name]
        for name in self.fetch_names:
            if name in scalar or name in post_produced:
                continue
            v = block._find_var_recursive(name)
            if v is None or not v.persistable:
                raise ValueError(
                    "fetch %r is neither a scalar forward var, an optimizer "
                    "output, nor a persistable — not fetchable under "
                    "pipeline parallelism" % name)

        # validate post-section reads are resolvable
        grad_names = set(self.grad_of.values())
        resolvable = (set(self.mut_names) | set(self.const_names)
                      | set(self.state_out) | grad_names
                      | set(scalar) | feed_set | post_produced)
        for op in self.post_ops:
            for name in op.input_names():
                if name not in resolvable:
                    raise ValueError(
                        "optimizer-section op %r reads %r, which the "
                        "pipelined step cannot provide (it is a non-scalar "
                        "forward activation)" % (op.type, name))

        # ---- sharding plan (tp over the auto axis, ZeRO over dp) -------
        from ..parallel.planner import plan_program

        from ..compiler import grad_seed_scale_of

        zero_mode = (getattr(build_strategy, "reduce_strategy", 0)
                     == BuildStrategy.ReduceStrategy.Reduce)
        self._grad_seed_scale = grad_seed_scale_of(build_strategy, self.dp)
        self._plan = plan_program(program, mesh,
                                  build_strategy=build_strategy,
                                  zero_sharding=zero_mode)
        self._state_shardings = {
            n: NamedSharding(mesh, self._plan.spec_of(n))
            for n in set(self.mut_names) | set(self.const_names)
            | set(self.state_out)}
        # activation seams, stored as bare PartitionSpecs: inside the
        # manual dp/pp region they must bind to the CONTEXT abstract mesh
        # (Manual axis types) — a concrete-mesh NamedSharding there poisons
        # downstream avals with a mismatched all-Auto mesh
        self._tp_constraint_specs = dict(self._plan.constraints)
        # Inside a lax.switch branch only the resident stage's ranks run, so
        # GSPMD may NOT emit collective-permute / all-to-all there (pair
        # style collectives rendezvous across every device and deadlock;
        # group-style all-reduce / all-gather are per-group and safe).
        # Slicing a tp-sharded dim (split/slice/concat boundaries) is what
        # GSPMD lowers with collective-permute, so pin those ops' INPUTS
        # tp-replicated on the last dim: the column-parallel producer then
        # all-gathers (legal) and the split becomes shard-local; the next
        # row-parallel matmul re-shards by a local slice (no comm).
        tp = int(dict(mesh.shape).get("tp", 1))
        if tp > 1:
            def _pin(v):
                if v is None or v.shape is None or not len(v.shape) \
                        or v.persistable or getattr(v, "is_data", False) \
                        or v.name in self._tp_constraint_specs:
                    return
                spec = P(*([P.UNCONSTRAINED] * (len(v.shape) - 1) + [None]))
                self._tp_constraint_specs[v.name] = spec

            def _row_sharded(name):
                spec = tuple(self._plan.specs.get(name, P()))
                if not spec:
                    return False
                d0 = spec[0]
                axes = d0 if isinstance(d0, (tuple, list)) else (d0,)
                return "tp" in axes

            def _walk(ops):
                for op in ops:
                    for key in ("sub_block", "true_block", "false_block"):
                        sub = op.attrs.get(key) if op.attrs else None
                        if sub is not None and getattr(sub, "ops", None) \
                                is not None:
                            _walk(sub.ops)
                    if op.type in ("split", "concat", "slice", "stack"):
                        # slicing a tp-sharded dim lowers to permutes; pin
                        # the input so the producer all-gathers instead
                        for vs in op.inputs.values():
                            for v in vs:
                                _pin(v)
                    elif op.type in ("mul", "matmul"):
                        # a row-parallel matmul pulls tp-last sharding
                        # backward through its X chain (reshapes, attention
                        # heads), which Shardy lowers with permutes: pin the
                        # X input replicated so the transition is a local
                        # slice, and the partial-sum output to a psum
                        ys = op.inputs.get("Y", [])
                        if ys and getattr(ys[0], "persistable", False) \
                                and _row_sharded(ys[0].name):
                            for v in op.inputs.get("X", []):
                                _pin(v)
                            for vs in op.outputs.values():
                                for v in vs:
                                    _pin(v)

            _walk(self.fwd_ops)
        self._repl = NamedSharding(mesh, P())

        mut_sh = {n: self._state_shardings[n] for n in self.mut_names}
        const_sh = {n: self._state_shardings[n] for n in self.const_names}
        self._jitted = jax.jit(
            self._step,
            donate_argnums=(0,),
            in_shardings=(mut_sh, const_sh, None, None),
        )

    # ------------------------------------------------------------------
    # trace-time construction
    # ------------------------------------------------------------------
    def _probe_layouts(self, dstructs, cstructs, feed_structs):
        """Chain jax.eval_shape through the forward section on microbatch
        shapes to size every cut's wire layout."""
        want = sorted({n for names in self.crossing for n in names})
        constraints = self._context_constraints()

        def run(dp_, cp_, fd_):
            env = {}
            env.update(cp_)
            env.update(dp_)
            env.update(fd_)
            ctx = LoweringContext(base_key=jax.random.PRNGKey(0),
                                  mesh=self.mesh)
            ctx.act_constraints = constraints
            ctx.no_pair_collectives = True
            for op in self.fwd_ops:
                execute_op(op, env, ctx)
            return {n: env[n] for n in want}

        shapes = jax.eval_shape(run, dstructs, cstructs, feed_structs)
        layouts = []
        for names in self.crossing:
            layouts.append(_CutLayout([
                (n, tuple(shapes[n].shape), np.dtype(shapes[n].dtype))
                for n in names]))
        return layouts

    def _probe_residuals(self, branches, cparams, dstructs, micro,
                         repl_feeds, base_key, nf, ni):
        """Per-virtual-stage vjp residual layouts for activation-stash
        mode: eval_shape the SAME vjp the real trace runs and capture
        (treedef, leaf avals) by side effect — deterministic tracing
        makes the probe's treedef identical to the real one, so
        unflattening stashed leaves reconstructs the vjp exactly.
        Residual leaves that ARE the live params/constants (tracer
        identity) are marked for rebinding instead of stashing — the
        stash then holds only genuine per-microbatch activations."""
        feed_structs = {n: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                        for n, a in micro.items()}
        feed_structs.update({
            n: jax.ShapeDtypeStruct(np.shape(a), a.dtype)
            for n, a in repl_feeds.items()})
        key_struct = jax.ShapeDtypeStruct(np.shape(base_key),
                                          base_key.dtype)
        f_struct = jax.ShapeDtypeStruct((nf,), np.float32)
        i_struct = jax.ShapeDtypeStruct((ni,), np.int32)
        c_leaves = jax.tree.leaves(cparams)
        layouts = []
        for br in branches:
            cap = {}

            def probe(dp_, f_in, i_in, feeds_mb, key, _br=br, _cap=cap):
                def g(dpp, fi):
                    f_o, i_o, scal = _br((dpp, fi, i_in, feeds_mb, key))
                    return (f_o, scal), i_o

                out, vjp_fn, _aux = jax.vjp(g, dp_, f_in, has_aux=True)
                leaves, treedef = jax.tree.flatten(vjp_fn)
                dp_leaves = jax.tree.leaves(dp_)
                rebind = []
                for leaf in leaves:
                    ref = None
                    for j, p in enumerate(dp_leaves):
                        if leaf is p:
                            ref = ("d", j)
                            break
                    if ref is None:
                        for j, p in enumerate(c_leaves):
                            if leaf is p:
                                ref = ("c", j)
                                break
                    rebind.append(ref)
                _cap["treedef"] = treedef
                _cap["avals"] = [(l.shape, l.dtype) for l in leaves]
                _cap["rebind"] = rebind
                return out

            jax.eval_shape(probe, dstructs, f_struct, i_struct,
                           feed_structs, key_struct)
            layouts.append(_ResidLayout(cap["treedef"], cap["avals"],
                                        cap["rebind"]))
        return layouts

    def _context_constraints(self):
        """NamedShardings for the activation seams, bound to the CURRENT
        abstract mesh (Manual over dp/pp inside the 1F1B region)."""
        from .mesh import current_abstract_mesh

        cmesh = current_abstract_mesh(self.mesh)
        return {n: NamedSharding(cmesh, spec)
                for n, spec in self._tp_constraint_specs.items()}

    def _make_branches(self, cparams, layouts, nf, ni, n_scal):
        """One lax.switch branch per stage: unpack wire -> run the stage's
        ops -> pack outgoing wire + scalar-fetch vector."""
        constraints = self._context_constraints()
        branches = []
        for s in range(self.S):
            in_lay = layouts[s - 1] if s > 0 else None
            out_lay = layouts[s] if s < self.S - 1 else None
            stage_ops = [op for op, st in zip(self.fwd_ops, self.stage_of)
                         if st == s]
            scal_here = [(k, n) for k, n in enumerate(self.scalar_names)
                         if self.produced_at.get(n) == s]

            def branch(operand, _in=in_lay, _out=out_lay, _ops=stage_ops,
                       _scal=scal_here):
                dp_, f_in, i_in, feeds_mb, mb_key = operand
                env = dict(cparams)
                env.update(dp_)
                env.update(feeds_mb)
                if _in is not None:
                    _in.unpack(env, f_in, i_in)
                ctx = LoweringContext(base_key=mb_key, mesh=self.mesh)
                ctx.act_constraints = constraints
                ctx.no_pair_collectives = True
                for op in _ops:
                    execute_op(op, env, ctx)
                if _out is not None:
                    f_out, i_out = _out.pack(env, nf, ni)
                else:
                    f_out = jnp.zeros((nf,), jnp.float32)
                    i_out = jnp.zeros((ni,), jnp.int32)
                scal = jnp.zeros((n_scal,), jnp.float32)
                for k, name in _scal:
                    scal = scal.at[k].set(
                        env[name].astype(jnp.float32).reshape(()))
                return f_out, i_out, scal

            branches.append(branch)
        return branches

    # ------------------------------------------------------------------
    # the traced step
    # ------------------------------------------------------------------
    def _step(self, mut_state, const_state, feeds, step_counter):
        state = {}
        state.update(const_state)
        state.update(mut_state)
        dparams = {n: state[n] for n in self.dparam_names}
        cparams = {n: state[n] for n in self.cparam_names}
        base_key = jax.random.fold_in(
            jax.random.PRNGKey(self._seed), step_counter)

        dp, pp, M = self.dp, self.pp, self.M
        # feed classification: data feeds shard over dp and microbatch;
        # everything else is replicated into every stage body
        # only declared data vars (layers.data) microbatch-split: slicing a
        # replicated auxiliary feed (a table, a mask) would silently change
        # semantics, unlike _DataParallelStep where feed sharding is just a
        # GSPMD layout choice
        batched, repl_feeds = {}, {}
        for name, arr in feeds.items():
            v = self.block._find_var_recursive(name)
            if v is not None and bool(getattr(v, "is_data", False)):
                if np.ndim(arr) < 1 or arr.shape[0] % (dp * M) != 0:
                    raise ValueError(
                        "feed %r batch %s must divide dp*microbatches = %d "
                        "for pipeline parallelism"
                        % (name, np.shape(arr), dp * M))
                sp_tp = dict(self.mesh.shape)
                if (arr.shape[0] // (dp * M) < 2
                        and int(sp_tp.get("sp", 1)) > 1
                        and int(sp_tp.get("tp", 1)) > 1):
                    # XLA:CPU's SPMD partitioner CHECK-aborts (not
                    # raises) subgrouping a size-1 batch dim under
                    # sp x tp — turn the process-killing abort into an
                    # actionable error (docs/PARALLEL.md caveat)
                    raise ValueError(
                        "feed %r microbatch size %d is 1 under combined "
                        "sequence AND tensor parallelism — the SPMD "
                        "partitioner cannot subgroup a size-1 batch dim;"
                        " use batch >= %d" % (
                            name, arr.shape[0] // (dp * M), 2 * dp * M))
                batched[name] = arr
            else:
                repl_feeds[name] = arr

        grads, scal = shard_map(
            self._pipeline_1f1b, mesh=self.mesh,
            in_specs=(P(), P(), P("dp"), P(), P()),
            out_specs=(P(), P()),
            axis_names={"dp", "pp"}, check_vma=False)(
                dparams, cparams, batched, repl_feeds, base_key)

        # ---- optimizer section on accumulated grads (GSPMD region) -----
        env = dict(state)
        env.update(feeds)
        for k, name in enumerate(self.scalar_names):
            v = self.block._find_var_recursive(name)
            val = scal[k]
            if v is not None and v.shape is not None:
                val = val.reshape(tuple(v.shape)).astype(dtype_to_np(v.dtype))
            env[name] = val
        for p, gname in self.grad_of.items():
            gv = self.block._find_var_recursive(gname)
            g = grads[p]
            if gv is not None and gv.dtype is not None:
                g = g.astype(dtype_to_np(gv.dtype))
            env[gname] = g
        ctx = LoweringContext(base_key=base_key, mesh=self.mesh)
        for op in self.post_ops:
            execute_op(op, env, ctx)

        fetches = [jax.lax.with_sharding_constraint(env[n], self._repl)
                   for n in self.fetch_names]
        new_state = {
            n: jax.lax.with_sharding_constraint(
                env[n], self._state_shardings[n])
            for n in self.state_out if n in env}
        return fetches, new_state

    def _pipeline_1f1b(self, dparams, cparams, batched, repl_feeds,
                       base_key):
        """The manual-region (interleaved) 1F1B schedule: runs per
        (dp, pp) rank with tp left to GSPMD, driven by the host-built
        schedule tables (pipeline_schedule.py) — each tick looks up its
        units/stash slots instead of computing index arithmetic, which
        makes virtual-stage interleaving (v>1) the same code path as
        classic 1F1B (v=1). Returns (psummed grads pytree, mean scalar
        vector)."""
        dp, pp, M, v = self.dp, self.pp, self.M, self.v
        sched = self.schedule
        my_pp = _axis_index("pp")
        my_dp = _axis_index("dp")

        micro = {}
        for name, arr in batched.items():
            mb = arr.shape[0] // M
            micro[name] = arr.reshape((M, mb) + arr.shape[1:])

        # wire layouts from microbatch-shaped abstract values
        feed_structs = {
            n: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
            for n, a in micro.items()}
        feed_structs.update({
            n: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype
                                    if not hasattr(a, "dtype") else a.dtype)
            for n, a in repl_feeds.items()})
        dstructs = {n: jax.ShapeDtypeStruct(v_.shape, v_.dtype)
                    for n, v_ in dparams.items()}
        cstructs = {n: jax.ShapeDtypeStruct(np.shape(v_), v_.dtype)
                    for n, v_ in cparams.items()}
        layouts = self._probe_layouts(dstructs, cstructs, feed_structs)
        nf = max([l.nf for l in layouts] + [1])
        ni = max([l.ni for l in layouts] + [1])
        n_scal = max(len(self.scalar_names), 1)

        branches = self._make_branches(cparams, layouts, nf, ni, n_scal)

        def feeds_at(i):
            d = {n: jax.lax.dynamic_index_in_dim(a, i, axis=0,
                                                 keepdims=False)
                 for n, a in micro.items()}
            d.update(repl_feeds)
            return d

        def key_at(i):
            return jax.random.fold_in(base_key, my_dp * M + i)

        def stage_apply(vs, dp_, f_in, i_in, i):
            # vs = chunk*pp + my_pp: the virtual stage resident here
            return jax.lax.switch(
                vs, branches, (dp_, f_in, i_in, feeds_at(i), key_at(i)))

        # ---- activation stash mode: vjp at FORWARD time, packed
        # residual leaves ride the input-stash slots (identical
        # lifetime); the backward unit unflattens and applies — no
        # chunk-forward rematerialization ----
        if self.stash_activations:
            resid_layouts = self._probe_residuals(
                branches, cparams, dstructs, micro, repl_feeds, base_key,
                nf, ni)
            nfr = max([l.nf for l in resid_layouts] + [1])
            nir = max([l.ni for l in resid_layouts] + [1])

            def _fwd_branch(s):
                br, lay = branches[s], resid_layouts[s]

                def b(operand):
                    dp_, f_in, i_in, feeds_mb, key = operand

                    def g(dpp, fi):
                        f_o, i_o, scal = br((dpp, fi, i_in, feeds_mb,
                                             key))
                        return (f_o, scal), i_o

                    (f_o, scal), vjp_fn, i_o = jax.vjp(
                        g, dp_, f_in, has_aux=True)
                    fr, ir = lay.pack(jax.tree.leaves(vjp_fn), nfr, nir)
                    return f_o, i_o, scal, fr, ir

                return b

            def _bwd_branch(s):
                lay = resid_layouts[s]

                def b(operand):
                    fr, ir, wire_cot, scal_cot = operand
                    sources = {"d": jax.tree.leaves(dparams),
                               "c": jax.tree.leaves(cparams)}
                    vjp_fn = jax.tree.unflatten(
                        lay.treedef, lay.unpack(fr, ir, sources))
                    return vjp_fn((wire_cot, scal_cot))

                return b

            fwd_branches = [_fwd_branch(s) for s in range(self.S)]
            bwd_branches = [_bwd_branch(s) for s in range(self.S)]
        else:
            nfr, nir = nf, ni  # input-wire stash doubles as "residual"

        seed = self._grad_seed_scale / float(M * dp)
        loss_onehot = jnp.zeros((n_scal,), jnp.float32).at[
            self.loss_idx].set(1.0)
        loss_vs = self.loss_stage  # virtual-stage index of the loss
        A, B, C = (sched.arrive_slots, sched.input_slots,
                   sched.cot_slots)
        zf = jnp.zeros((nf,), jnp.float32)
        zi = jnp.zeros((ni,), jnp.int32)

        xs = {k: jnp.asarray(getattr(sched, k)) for k in (
            "fwd_mb", "fwd_chunk", "fwd_read", "fwd_save", "fwd_recv",
            "bwd_mb", "bwd_chunk", "bwd_read", "cot_read", "cot_recv")}

        def tick(carry, row):
            (fwd_f, fwd_i, bwd_f, arr_f, arr_i, in_f, in_i, cot_f,
             gacc, sacc) = carry
            at = {k: jnp.take(r_, my_pp) for k, r_ in row.items()}

            # ---- land last tick's ring wires into the stashes ----
            arr_f = jnp.where(
                at["fwd_recv"] >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    arr_f, fwd_f, jnp.clip(at["fwd_recv"], 0, A - 1), 0),
                arr_f)
            arr_i = jnp.where(
                at["fwd_recv"] >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    arr_i, fwd_i, jnp.clip(at["fwd_recv"], 0, A - 1), 0),
                arr_i)
            cot_f = jnp.where(
                at["cot_recv"] >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    cot_f, bwd_f, jnp.clip(at["cot_recv"], 0, C - 1), 0),
                cot_f)

            # ---- forward unit ----
            valid_f = at["fwd_mb"] >= 0
            i_fc = jnp.clip(at["fwd_mb"], 0, M - 1)
            vs_f = jnp.clip(at["fwd_chunk"], 0, v - 1) * pp + my_pp
            rd = jnp.clip(at["fwd_read"], 0, A - 1)
            f_in = jnp.where(
                at["fwd_read"] >= 0,
                jax.lax.dynamic_index_in_dim(arr_f, rd, 0, keepdims=False),
                zf)
            i_in = jnp.where(
                at["fwd_read"] >= 0,
                jax.lax.dynamic_index_in_dim(arr_i, rd, 0, keepdims=False),
                zi)
            if self.stash_activations:
                f_out, i_out, scal_f, save_f, save_i = jax.lax.switch(
                    vs_f, fwd_branches,
                    (dparams, f_in, i_in, feeds_at(i_fc), key_at(i_fc)))
            else:
                f_out, i_out, scal_f = stage_apply(vs_f, dparams, f_in,
                                                   i_in, i_fc)
                save_f, save_i = f_in, i_in
            sv = jnp.clip(at["fwd_save"], 0, B - 1)
            in_f = jnp.where(
                valid_f,
                jax.lax.dynamic_update_index_in_dim(in_f, save_f, sv, 0),
                in_f)
            in_i = jnp.where(
                valid_f,
                jax.lax.dynamic_update_index_in_dim(in_i, save_i, sv, 0),
                in_i)
            sacc = sacc + jnp.where(valid_f, scal_f, 0.0)

            # ---- backward unit (vjp re-runs the chunk forward) ----
            valid_b = at["bwd_mb"] >= 0
            i_bc = jnp.clip(at["bwd_mb"], 0, M - 1)
            vs_b = jnp.clip(at["bwd_chunk"], 0, v - 1) * pp + my_pp
            br = jnp.clip(at["bwd_read"], 0, B - 1)
            f_in_b = jax.lax.dynamic_index_in_dim(in_f, br, 0,
                                                  keepdims=False)
            i_in_b = jax.lax.dynamic_index_in_dim(in_i, br, 0,
                                                  keepdims=False)
            cr = jnp.clip(at["cot_read"], 0, C - 1)
            cot_in = jnp.where(
                at["cot_read"] >= 0,
                jax.lax.dynamic_index_in_dim(cot_f, cr, 0, keepdims=False),
                zf)
            # cotangent routing: the loss stage seeds; earlier stages
            # relay the ring cotangent; later (post-loss metric) stages
            # send 0
            wire_cot = jnp.where(vs_b < loss_vs, 1.0, 0.0) * cot_in
            scal_cot = loss_onehot * jnp.where(
                vs_b == loss_vs, jnp.float32(seed), 0.0)
            if self.stash_activations:
                gP, g_in = jax.lax.switch(
                    vs_b, bwd_branches, (f_in_b, i_in_b, wire_cot,
                                         scal_cot))
            else:
                def g(dp_, f_in_):
                    f_o, _, scal = stage_apply(vs_b, dp_, f_in_, i_in_b,
                                               i_bc)
                    return f_o, scal

                _, svjp = jax.vjp(g, dparams, f_in_b)
                gP, g_in = svjp((wire_cot, scal_cot))
            gacc = jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0.0).astype(
                    jnp.float32), gacc, gP)

            # ---- ring exchange (unconditional, all ranks) ----
            fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
            fwd_f2 = jax.lax.ppermute(f_out, "pp", fwd_perm)
            fwd_i2 = jax.lax.ppermute(i_out, "pp", fwd_perm)
            bwd_f2 = jax.lax.ppermute(g_in, "pp", bwd_perm)
            return (fwd_f2, fwd_i2, bwd_f2, arr_f, arr_i, in_f, in_i,
                    cot_f, gacc, sacc), None

        init = (zf, zi, zf,
                jnp.zeros((A, nf), jnp.float32),
                jnp.zeros((A, ni), jnp.int32),
                jnp.zeros((B, nfr), jnp.float32),
                jnp.zeros((B, nir), jnp.int32),
                jnp.zeros((C, nf), jnp.float32),
                jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             dparams),
                jnp.zeros((n_scal,), jnp.float32))
        carry, _ = jax.lax.scan(tick, init, xs)
        gacc, sacc = carry[-2], carry[-1]

        grads = jax.tree.map(lambda g: jax.lax.psum(g, ("dp", "pp")), gacc)
        # each scalar is owned by exactly one stage: pp-psum recovers its
        # M-microbatch sum, the dp-psum sums replicas -> mean over both
        scal = jax.lax.psum(sacc, ("dp", "pp")) / float(M * dp)
        return grads, scal

    # ------------------------------------------------------------------
    # host-side driver (same contract as _DataParallelStep.run)
    # ------------------------------------------------------------------
    def run(self, scope, feed):
        from ..compiler import (lift_to_global, normalize_feed_value,
                                read_persistable_state)

        mut, const = read_persistable_state(scope, self.mut_names,
                                            self.const_names)
        feeds = {name: normalize_feed_value(self.block, name, feed[name])
                 for name in self.feed_names}
        if self._multiprocess:
            # DCN case: jit on a multi-process mesh takes only global
            # jax.Arrays. Feeds lift replicated (every worker feeds the
            # identical global batch; the shard_map in_specs reshard the
            # data feeds over dp), state lifts to its planned sharding
            # unless the scope already holds a correctly-sharded array
            # from the previous step.
            def _is_global(a):
                return (isinstance(a, jax.Array)
                        and set(a.sharding.device_set) == self._mesh_devs)

            feeds = {n: (a if _is_global(a)
                         else lift_to_global(a, self._repl))
                     for n, a in feeds.items()}
            for store in (mut, const):
                for name, val in store.items():
                    want = self._state_shardings.get(name, self._repl)
                    if isinstance(val, jax.Array) and \
                            val.sharding.is_equivalent_to(want,
                                                          np.ndim(val)):
                        continue
                    store[name] = lift_to_global(val, want)
        ctr = np.uint32(scope.get("__step_counter__", 0) or 0)
        fetches, new_state = self._jitted(mut, const, feeds, ctr)
        for name, val in new_state.items():
            scope.set(name, val)
        scope.set("__step_counter__", int(ctr) + 1)
        return fetches
