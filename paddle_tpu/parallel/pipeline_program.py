"""Any-program pipeline parallelism through the descriptor path.

The reference's defining multi-device contract is "rewrite ANY user program
for N devices" (framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:165) — but its builder only does data
parallelism. Pipeline parallelism is a new-design axis (SURVEY §5.7);
round 3 delivered it only inside the hand-written SPMD trainer
(parallel/transformer.py). This module brings the SAME 1F1B schedule to an
arbitrary Fluid program built from `fluid.layers`:

    strategy = BuildStrategy()
    strategy.pipeline_stages = 4            # pp axis size
    strategy.pipeline_microbatches = 8      # defaults to pp
    CompiledProgram(prog).with_data_parallel(loss_name=..., build_strategy=strategy)

Design (TPU-native, no graph rewrite):
 - The program's op list is [forward | backward | optimizer]; the forward
   section is split into `pp` contiguous stages, either by explicit
   `with fluid.pipeline_stage(i):` annotation or by a balanced-FLOP
   auto-split. Backward ops are NOT executed — each stage's gradients come
   from `jax.vjp` of its lowered forward (the same kernels the grad ops
   would re-run, so results are identical); optimizer/clip/regularizer ops
   then run unchanged on the accumulated grads.
 - One `shard_map` over the ("dp", "pp", "tp") step mesh, MANUAL over dp/pp
   and GSPMD-auto over tp: the 1F1B ring schedule (ppermute neighbor
   exchange, O(pp) input stash, fwd fill while bwd drains) is hand-written
   over the manual axes, while the planner's Megatron tp shardings keep
   working untouched inside every stage body.
 - Stage bodies become branches of one `lax.switch` on the pp rank index —
   SPMD requires every rank to run the same traced program; the switch
   executes only the resident stage's ops at run time.
 - Activations cross stage cuts as packed wire buffers (one fp32 buffer +
   one int32 buffer, padded to the widest cut) so heterogeneous cut
   signatures ride a single fixed-shape ppermute ring. Packing is
   reshape/cast/concat — exact for bf16/fp16/fp32 payloads and transparent
   to reverse-mode AD.

Semantics: microbatching requires the loss to be a MEAN over batch
elements (the usual Fluid `mean(cross_entropy)` shape); gradients then
equal the full-batch gradient exactly, which the parity test asserts
against the single-device executor. Ops with cross-batch state (batch_norm
running stats) are rejected with a clear error — use layer_norm or run BN
under dp-only parallelism.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.lowering import LoweringContext, execute_op
from ..framework import dtype_to_np

__all__ = ["PipelineProgramStep", "split_sections", "assign_stages"]


# ---------------------------------------------------------------------------
# program analysis
# ---------------------------------------------------------------------------


def _is_backward_op(op):
    return "__fwd_op__" in op.attrs or op.attrs.get("__op_role__") == "backward"


def split_sections(block):
    """(fwd_ops, post_ops): forward ops before the first backward op, and
    the non-backward tail (optimizer / clip / regularizer / lr ops)."""
    ops = block.ops
    bwd = next((i for i, op in enumerate(ops) if _is_backward_op(op)), None)
    if bwd is None:
        return list(ops), []
    return list(ops[:bwd]), [op for op in ops[bwd:] if not _is_backward_op(op)]


def _numel(shape):
    n = 1
    for d in shape or ():
        if d is not None and d > 0:
            n *= d
    return n


def _op_cost(op):
    """Relative FLOP estimate for stage balancing. Static shapes with the
    batch dim as -1 are fine — only the ratio between ops matters."""
    sub_cost = 0.0
    for key in ("sub_block", "true_block", "false_block"):
        sub = op.attrs.get(key) if op.attrs else None
        if sub is not None and getattr(sub, "ops", None) is not None:
            sub_cost += sum(_op_cost(o) for o in sub.ops)
    out_n = sum(_numel(v.shape) for vs in op.outputs.values() for v in vs
                if v.shape is not None)
    t = op.type
    if t in ("mul", "matmul"):
        ys = op.inputs.get("Y", [])
        k = 1
        if ys and ys[0].shape and len(ys[0].shape) >= 2:
            k = max(1, ys[0].shape[-2] or 1)
        return sub_cost + 2.0 * out_n * k
    if t in ("conv2d", "depthwise_conv2d", "conv3d"):
        fs = op.inputs.get("Filter", [])
        k = _numel(fs[0].shape[1:]) if fs and fs[0].shape else 1
        return sub_cost + 2.0 * out_n * k
    if t == "flash_attention":
        qs = op.inputs.get("Q", [])
        seq = 1
        if qs and qs[0].shape and len(qs[0].shape) >= 2:
            seq = max(1, qs[0].shape[1] or 1)
        return sub_cost + 4.0 * out_n * seq
    return sub_cost + float(out_n)


def assign_stages(fwd_ops, pp):
    """Stage id per forward op: honor `__pipeline_stage__` stamps from
    `fluid.pipeline_stage(i)` when present (unstamped ops inherit the
    previous stamp), else balanced cumulative-cost auto-split into pp
    contiguous chunks."""
    stamped = [op.attrs.get("__pipeline_stage__") for op in fwd_ops]
    if any(s is not None for s in stamped):
        stages, cur = [], 0
        for i, s in enumerate(stamped):
            if s is not None:
                s = int(s)
                if s < cur:
                    raise ValueError(
                        "pipeline_stage annotations must be non-decreasing "
                        "in program order: op #%d (%s) is stage %d after "
                        "stage %d" % (i, fwd_ops[i].type, s, cur))
                cur = s
            if cur >= pp:
                raise ValueError(
                    "pipeline_stage %d out of range for pipeline_stages=%d"
                    % (cur, pp))
            stages.append(cur)
        return stages
    costs = [_op_cost(op) for op in fwd_ops]
    total = sum(costs) or 1.0
    stages, acc, cur = [], 0.0, 0
    for c in costs:
        # cut when the op's midpoint crosses the next boundary
        while cur < pp - 1 and acc + c / 2.0 > (cur + 1) * total / pp:
            cur += 1
        stages.append(cur)
        acc += c
    return stages


# ---------------------------------------------------------------------------
# wire packing: heterogeneous cut signatures over one fixed-shape ring
# ---------------------------------------------------------------------------


class _CutLayout:
    """Ordered (name, shape, np dtype) entries for one stage cut, split
    into float (fp32 wire, differentiable) and int (int32 wire) segments."""

    def __init__(self, entries):
        for n, _, d in entries:
            # the wire is fp32/int32: exact for every dtype JAX produces
            # with x64 disabled (the default); 64-bit payloads would be
            # silently narrowed, so reject them instead
            if np.dtype(d).itemsize > 4:
                raise NotImplementedError(
                    "activation %r crossing a pipeline stage cut has dtype "
                    "%s; the stage wire is fp32/int32 and would narrow it "
                    "(jax_enable_x64 programs are unsupported under "
                    "pipeline_stages > 1)" % (n, d))
        self.fent = [(n, s, d) for n, s, d in entries
                     if np.issubdtype(d, np.inexact)]
        self.ient = [(n, s, d) for n, s, d in entries
                     if not np.issubdtype(d, np.inexact)]
        self.nf = sum(_numel(s) for _, s, _ in self.fent)
        self.ni = sum(_numel(s) for _, s, _ in self.ient)

    def pack(self, env, nf_max, ni_max):
        fparts = [env[n].astype(jnp.float32).reshape(-1)
                  for n, _, _ in self.fent]
        iparts = [env[n].astype(jnp.int32).reshape(-1)
                  for n, _, _ in self.ient]
        f = (jnp.concatenate(fparts) if fparts
             else jnp.zeros((0,), jnp.float32))
        i = (jnp.concatenate(iparts) if iparts
             else jnp.zeros((0,), jnp.int32))
        return (jnp.pad(f, (0, nf_max - f.shape[0])),
                jnp.pad(i, (0, ni_max - i.shape[0])))

    def unpack(self, env, f, i):
        off = 0
        for n, s, d in self.fent:
            k = _numel(s)
            env[n] = jax.lax.slice_in_dim(f, off, off + k).reshape(s) \
                .astype(d)
            off += k
        off = 0
        for n, s, d in self.ient:
            k = _numel(s)
            env[n] = jax.lax.slice_in_dim(i, off, off + k).reshape(s) \
                .astype(d)
            off += k


# ---------------------------------------------------------------------------
# the pipelined step
# ---------------------------------------------------------------------------


class PipelineProgramStep:
    """One jitted dp×pp×tp step for an arbitrary Fluid training program.

    Built lazily per feed signature by CompiledProgram (same caching
    contract as _DataParallelStep)."""

    def __init__(self, program, feed_names, fetch_names, mesh,
                 build_strategy, loss_name):
        from ..compiler import BuildStrategy

        if loss_name is None:
            raise ValueError(
                "pipeline_stages > 1 needs with_data_parallel(loss_name=...) "
                "so the 1F1B schedule knows which scalar to differentiate")
        # Multi-process (DCN) meshes are allowed when the pp axis stays
        # within a process: the 1F1B ring's ppermute then rides local
        # devices (ICI on TPU pods) and only the dp gradient psum crosses
        # processes — the reference's multi-NODE shape (nccl_helper.h:130
        # multi-node ncclCommInitRank; dp between nodes, model parallel
        # within). A pp axis that itself spans processes needs
        # cross-process collective-permute, which XLA:CPU's Gloo backend
        # does not provide — on TPU (DCN ppermute exists) it is untested
        # here for lack of multi-host hardware, so refuse off-TPU.
        ax = mesh.axis_names.index("pp") if "pp" in mesh.axis_names else None
        if ax is not None:
            cols = np.moveaxis(mesh.devices, ax, 0)
            cols = cols.reshape(cols.shape[0], -1)
            pp_crosses = any(
                len({d.process_index for d in cols[:, j]}) > 1
                for j in range(cols.shape[1]))
            if pp_crosses and mesh.devices.flat[0].platform == "cpu":
                raise NotImplementedError(
                    "the pipeline axis spans processes, which needs "
                    "cross-process collective-permute (unavailable on "
                    "XLA:CPU). Lay out the mesh so pp is within a "
                    "process — dp over processes, pp/tp/sp within — or "
                    "run on a TPU pod slice.")
        from ..flags import flag as _flag

        if bool(_flag("check_nan_inf")):
            # per-op nan flags live inside the 1F1B scan's switch branches
            # and cannot be packed out per-tick; refuse loudly rather than
            # let a debugging user believe the checks are on
            raise NotImplementedError(
                "FLAGS_check_nan_inf is not supported under "
                "pipeline_stages > 1 — reproduce on a dp/tp mesh (or "
                "single device) to localize the NaN, then re-enable "
                "pipelining")
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.mesh = mesh
        from ..compiler import mesh_spans_processes

        self._multiprocess = mesh_spans_processes(mesh)
        self._mesh_devs = set(mesh.devices.flat)
        self.loss_name = loss_name
        block = program.global_block()
        self.block = block
        shape = dict(mesh.shape)
        self.dp = int(shape.get("dp", 1))
        self.pp = int(shape.get("pp", 1))
        self.M = int(getattr(build_strategy, "pipeline_microbatches", None)
                     or self.pp)
        if self.M < self.pp:
            raise ValueError(
                "pipeline_microbatches (%d) must be >= pipeline_stages (%d)"
                % (self.M, self.pp))
        self._seed = program.random_seed or 0

        self.fwd_ops, self.post_ops = split_sections(block)
        if not any(_is_backward_op(op) for op in block.ops):
            raise ValueError(
                "pipeline_stages > 1 needs a training program (append "
                "backward via optimizer.minimize); for inference use "
                "dp/tp sharding instead")
        self.stage_of = assign_stages(self.fwd_ops, self.pp)

        # ---- dataflow over the forward section -------------------------
        feed_set = set(self.feed_names)
        produced_at = {}
        last_use = {}
        for op, s in zip(self.fwd_ops, self.stage_of):
            for name in op.input_names():
                v = block._find_var_recursive(name)
                if name in feed_set or v is None or v.persistable:
                    continue
                if name in produced_at:
                    last_use[name] = max(last_use.get(name, s), s)
            for name in op.output_names():
                v = block._find_var_recursive(name)
                if v is not None and v.persistable:
                    raise ValueError(
                        "forward op %r writes persistable var %r — ops with "
                        "cross-batch state (batch_norm running stats) don't "
                        "commute with pipeline microbatching; use "
                        "layer_norm, or dp/tp parallelism for this model"
                        % (op.type, name))
                if name in feed_set:
                    # stage branches re-read feeds fresh each microbatch, so
                    # a later stage would silently see the pre-write value
                    raise ValueError(
                        "forward op %r writes feed var %r in place — "
                        "pipeline stages read feeds immutably; copy the "
                        "feed into a new var (e.g. layers.assign) first"
                        % (op.type, name))
                prev = produced_at.get(name)
                if prev is not None and prev != s:
                    # the cut-crossing sets track one producing stage per
                    # var; a rewrite in a later stage would make every
                    # earlier consumer read the wrong (not-yet-computed)
                    # value, so reject it up front
                    raise ValueError(
                        "var %r is rewritten in place at stage %d after "
                        "being produced at stage %d — in-place rewrites "
                        "across pipeline stages are unsupported; adjust "
                        "pipeline_stage annotations so all writes to a var "
                        "land in one stage" % (name, s, prev))
                produced_at[name] = s
        self.produced_at = produced_at
        # crossing[c]: produced at stage <= c, still consumed after cut c
        self.crossing = []
        for c in range(self.pp - 1):
            names = sorted(
                n for n in produced_at
                if produced_at[n] <= c and last_use.get(n, -1) > c)
            self.crossing.append(names)

        # ---- parameters ------------------------------------------------
        fwd_reads = set()
        for op in self.fwd_ops:
            fwd_reads.update(op.input_names())
        pg = dict(getattr(program, "param_grad_map", {}) or {})
        self.dparam_names = sorted(
            p for p, g in pg.items()
            if p in fwd_reads and block._find_var_recursive(g) is not None)
        self.grad_of = {p: pg[p] for p in self.dparam_names}
        self.cparam_names = sorted(
            n for n in fwd_reads
            if n not in self.grad_of and n not in feed_set
            and (lambda v: v is not None and v.persistable)(
                block._find_var_recursive(n)))

        # ---- persistable state classification (jit signature) ----------
        from ..compiler import classify_persistable_state

        self.mut_names, self.const_names, self.state_out = \
            classify_persistable_state(block, self.fetch_names)

        # ---- scalar forward fetches (loss, metrics) --------------------
        post_produced = set()
        for op in self.post_ops:
            post_produced.update(op.output_names())
        self.post_produced = post_produced
        scalar = []
        for name in dict.fromkeys([self.loss_name] + self.fetch_names):
            if name in produced_at:
                v = block._find_var_recursive(name)
                if v is not None and v.shape is not None \
                        and _numel(v.shape) == 1 and -1 not in v.shape:
                    scalar.append(name)
                elif name in self.fetch_names:
                    raise ValueError(
                        "fetch %r is a non-scalar forward activation; under "
                        "pipeline parallelism activations live per-"
                        "microbatch per-stage. Fetch scalars (loss/metrics) "
                        "or persistables instead" % name)
        if self.loss_name not in scalar:
            raise ValueError(
                "loss %r must be a scalar produced by the forward section"
                % self.loss_name)
        self.scalar_names = scalar
        self.loss_idx = scalar.index(self.loss_name)
        self.loss_stage = produced_at[self.loss_name]
        for name in self.fetch_names:
            if name in scalar or name in post_produced:
                continue
            v = block._find_var_recursive(name)
            if v is None or not v.persistable:
                raise ValueError(
                    "fetch %r is neither a scalar forward var, an optimizer "
                    "output, nor a persistable — not fetchable under "
                    "pipeline parallelism" % name)

        # validate post-section reads are resolvable
        grad_names = set(self.grad_of.values())
        resolvable = (set(self.mut_names) | set(self.const_names)
                      | set(self.state_out) | grad_names
                      | set(scalar) | feed_set | post_produced)
        for op in self.post_ops:
            for name in op.input_names():
                if name not in resolvable:
                    raise ValueError(
                        "optimizer-section op %r reads %r, which the "
                        "pipelined step cannot provide (it is a non-scalar "
                        "forward activation)" % (op.type, name))

        # ---- sharding plan (tp over the auto axis, ZeRO over dp) -------
        from ..parallel.planner import plan_program

        from ..compiler import grad_seed_scale_of

        zero_mode = (getattr(build_strategy, "reduce_strategy", 0)
                     == BuildStrategy.ReduceStrategy.Reduce)
        self._grad_seed_scale = grad_seed_scale_of(build_strategy, self.dp)
        self._plan = plan_program(program, mesh,
                                  build_strategy=build_strategy,
                                  zero_sharding=zero_mode)
        self._state_shardings = {
            n: NamedSharding(mesh, self._plan.spec_of(n))
            for n in set(self.mut_names) | set(self.const_names)
            | set(self.state_out)}
        # activation seams, stored as bare PartitionSpecs: inside the
        # manual dp/pp region they must bind to the CONTEXT abstract mesh
        # (Manual axis types) — a concrete-mesh NamedSharding there poisons
        # downstream avals with a mismatched all-Auto mesh
        self._tp_constraint_specs = dict(self._plan.constraints)
        # Inside a lax.switch branch only the resident stage's ranks run, so
        # GSPMD may NOT emit collective-permute / all-to-all there (pair
        # style collectives rendezvous across every device and deadlock;
        # group-style all-reduce / all-gather are per-group and safe).
        # Slicing a tp-sharded dim (split/slice/concat boundaries) is what
        # GSPMD lowers with collective-permute, so pin those ops' INPUTS
        # tp-replicated on the last dim: the column-parallel producer then
        # all-gathers (legal) and the split becomes shard-local; the next
        # row-parallel matmul re-shards by a local slice (no comm).
        tp = int(dict(mesh.shape).get("tp", 1))
        if tp > 1:
            def _pin(v):
                if v is None or v.shape is None or not len(v.shape) \
                        or v.persistable or getattr(v, "is_data", False) \
                        or v.name in self._tp_constraint_specs:
                    return
                spec = P(*([P.UNCONSTRAINED] * (len(v.shape) - 1) + [None]))
                self._tp_constraint_specs[v.name] = spec

            def _row_sharded(name):
                spec = tuple(self._plan.specs.get(name, P()))
                if not spec:
                    return False
                d0 = spec[0]
                axes = d0 if isinstance(d0, (tuple, list)) else (d0,)
                return "tp" in axes

            def _walk(ops):
                for op in ops:
                    for key in ("sub_block", "true_block", "false_block"):
                        sub = op.attrs.get(key) if op.attrs else None
                        if sub is not None and getattr(sub, "ops", None) \
                                is not None:
                            _walk(sub.ops)
                    if op.type in ("split", "concat", "slice", "stack"):
                        # slicing a tp-sharded dim lowers to permutes; pin
                        # the input so the producer all-gathers instead
                        for vs in op.inputs.values():
                            for v in vs:
                                _pin(v)
                    elif op.type in ("mul", "matmul"):
                        # a row-parallel matmul pulls tp-last sharding
                        # backward through its X chain (reshapes, attention
                        # heads), which Shardy lowers with permutes: pin the
                        # X input replicated so the transition is a local
                        # slice, and the partial-sum output to a psum
                        ys = op.inputs.get("Y", [])
                        if ys and getattr(ys[0], "persistable", False) \
                                and _row_sharded(ys[0].name):
                            for v in op.inputs.get("X", []):
                                _pin(v)
                            for vs in op.outputs.values():
                                for v in vs:
                                    _pin(v)

            _walk(self.fwd_ops)
        self._repl = NamedSharding(mesh, P())

        mut_sh = {n: self._state_shardings[n] for n in self.mut_names}
        const_sh = {n: self._state_shardings[n] for n in self.const_names}
        self._jitted = jax.jit(
            self._step,
            donate_argnums=(0,),
            in_shardings=(mut_sh, const_sh, None, None),
        )

    # ------------------------------------------------------------------
    # trace-time construction
    # ------------------------------------------------------------------
    def _probe_layouts(self, dstructs, cstructs, feed_structs):
        """Chain jax.eval_shape through the forward section on microbatch
        shapes to size every cut's wire layout."""
        want = sorted({n for names in self.crossing for n in names})
        constraints = self._context_constraints()

        def run(dp_, cp_, fd_):
            env = {}
            env.update(cp_)
            env.update(dp_)
            env.update(fd_)
            ctx = LoweringContext(base_key=jax.random.PRNGKey(0),
                                  mesh=self.mesh)
            ctx.act_constraints = constraints
            ctx.no_pair_collectives = True
            for op in self.fwd_ops:
                execute_op(op, env, ctx)
            return {n: env[n] for n in want}

        shapes = jax.eval_shape(run, dstructs, cstructs, feed_structs)
        layouts = []
        for names in self.crossing:
            layouts.append(_CutLayout([
                (n, tuple(shapes[n].shape), np.dtype(shapes[n].dtype))
                for n in names]))
        return layouts

    def _context_constraints(self):
        """NamedShardings for the activation seams, bound to the CURRENT
        abstract mesh (Manual over dp/pp inside the 1F1B region)."""
        from .mesh import current_abstract_mesh

        cmesh = current_abstract_mesh(self.mesh)
        return {n: NamedSharding(cmesh, spec)
                for n, spec in self._tp_constraint_specs.items()}

    def _make_branches(self, cparams, layouts, nf, ni, n_scal):
        """One lax.switch branch per stage: unpack wire -> run the stage's
        ops -> pack outgoing wire + scalar-fetch vector."""
        constraints = self._context_constraints()
        branches = []
        for s in range(self.pp):
            in_lay = layouts[s - 1] if s > 0 else None
            out_lay = layouts[s] if s < self.pp - 1 else None
            stage_ops = [op for op, st in zip(self.fwd_ops, self.stage_of)
                         if st == s]
            scal_here = [(k, n) for k, n in enumerate(self.scalar_names)
                         if self.produced_at.get(n) == s]

            def branch(operand, _in=in_lay, _out=out_lay, _ops=stage_ops,
                       _scal=scal_here):
                dp_, f_in, i_in, feeds_mb, mb_key = operand
                env = dict(cparams)
                env.update(dp_)
                env.update(feeds_mb)
                if _in is not None:
                    _in.unpack(env, f_in, i_in)
                ctx = LoweringContext(base_key=mb_key, mesh=self.mesh)
                ctx.act_constraints = constraints
                ctx.no_pair_collectives = True
                for op in _ops:
                    execute_op(op, env, ctx)
                if _out is not None:
                    f_out, i_out = _out.pack(env, nf, ni)
                else:
                    f_out = jnp.zeros((nf,), jnp.float32)
                    i_out = jnp.zeros((ni,), jnp.int32)
                scal = jnp.zeros((n_scal,), jnp.float32)
                for k, name in _scal:
                    scal = scal.at[k].set(
                        env[name].astype(jnp.float32).reshape(()))
                return f_out, i_out, scal

            branches.append(branch)
        return branches

    # ------------------------------------------------------------------
    # the traced step
    # ------------------------------------------------------------------
    def _step(self, mut_state, const_state, feeds, step_counter):
        state = {}
        state.update(const_state)
        state.update(mut_state)
        dparams = {n: state[n] for n in self.dparam_names}
        cparams = {n: state[n] for n in self.cparam_names}
        base_key = jax.random.fold_in(
            jax.random.PRNGKey(self._seed), step_counter)

        dp, pp, M = self.dp, self.pp, self.M
        # feed classification: data feeds shard over dp and microbatch;
        # everything else is replicated into every stage body
        # only declared data vars (layers.data) microbatch-split: slicing a
        # replicated auxiliary feed (a table, a mask) would silently change
        # semantics, unlike _DataParallelStep where feed sharding is just a
        # GSPMD layout choice
        batched, repl_feeds = {}, {}
        for name, arr in feeds.items():
            v = self.block._find_var_recursive(name)
            if v is not None and bool(getattr(v, "is_data", False)):
                if np.ndim(arr) < 1 or arr.shape[0] % (dp * M) != 0:
                    raise ValueError(
                        "feed %r batch %s must divide dp*microbatches = %d "
                        "for pipeline parallelism"
                        % (name, np.shape(arr), dp * M))
                batched[name] = arr
            else:
                repl_feeds[name] = arr

        grads, scal = shard_map(
            self._pipeline_1f1b, mesh=self.mesh,
            in_specs=(P(), P(), P("dp"), P(), P()),
            out_specs=(P(), P()),
            axis_names={"dp", "pp"}, check_vma=False)(
                dparams, cparams, batched, repl_feeds, base_key)

        # ---- optimizer section on accumulated grads (GSPMD region) -----
        env = dict(state)
        env.update(feeds)
        for k, name in enumerate(self.scalar_names):
            v = self.block._find_var_recursive(name)
            val = scal[k]
            if v is not None and v.shape is not None:
                val = val.reshape(tuple(v.shape)).astype(dtype_to_np(v.dtype))
            env[name] = val
        for p, gname in self.grad_of.items():
            gv = self.block._find_var_recursive(gname)
            g = grads[p]
            if gv is not None and gv.dtype is not None:
                g = g.astype(dtype_to_np(gv.dtype))
            env[gname] = g
        ctx = LoweringContext(base_key=base_key, mesh=self.mesh)
        for op in self.post_ops:
            execute_op(op, env, ctx)

        fetches = [jax.lax.with_sharding_constraint(env[n], self._repl)
                   for n in self.fetch_names]
        new_state = {
            n: jax.lax.with_sharding_constraint(
                env[n], self._state_shardings[n])
            for n in self.state_out if n in env}
        return fetches, new_state

    def _pipeline_1f1b(self, dparams, cparams, batched, repl_feeds,
                       base_key):
        """The manual-region 1F1B schedule: runs per (dp, pp) rank with tp
        left to GSPMD. Returns (psummed grads pytree, mean scalar vector)."""
        dp, pp, M = self.dp, self.pp, self.M
        my_pp = jax.lax.axis_index("pp")
        my_dp = jax.lax.axis_index("dp")

        micro = {}
        for name, arr in batched.items():
            mb = arr.shape[0] // M
            micro[name] = arr.reshape((M, mb) + arr.shape[1:])

        # wire layouts from microbatch-shaped abstract values
        feed_structs = {
            n: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
            for n, a in micro.items()}
        feed_structs.update({
            n: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype
                                    if not hasattr(a, "dtype") else a.dtype)
            for n, a in repl_feeds.items()})
        dstructs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for n, v in dparams.items()}
        cstructs = {n: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                    for n, v in cparams.items()}
        layouts = self._probe_layouts(dstructs, cstructs, feed_structs)
        nf = max([l.nf for l in layouts] + [1])
        ni = max([l.ni for l in layouts] + [1])
        n_scal = max(len(self.scalar_names), 1)

        branches = self._make_branches(cparams, layouts, nf, ni, n_scal)

        def feeds_at(i):
            d = {n: jax.lax.dynamic_index_in_dim(a, i, axis=0,
                                                 keepdims=False)
                 for n, a in micro.items()}
            d.update(repl_feeds)
            return d

        def key_at(i):
            return jax.random.fold_in(base_key, my_dp * M + i)

        def stage_apply(dp_, f_in, i_in, i):
            return jax.lax.switch(
                my_pp, branches, (dp_, f_in, i_in, feeds_at(i), key_at(i)))

        seed = self._grad_seed_scale / float(M * dp)
        loss_onehot = jnp.zeros((n_scal,), jnp.float32).at[
            self.loss_idx].set(1.0)
        S_ring = 2 * pp
        K = M + 2 * pp - 2

        def tick(carry, t):
            (fwd_f, fwd_i, bwd_f, stash_f, stash_i, gacc, sacc) = carry

            # ---- forward unit: microbatch i_f = t - my_pp ----
            i_f = t - my_pp
            valid_f = (i_f >= 0) & (i_f < M)
            i_fc = jnp.clip(i_f, 0, M - 1)
            f_out, i_out, scal_f = stage_apply(dparams, fwd_f, fwd_i, i_fc)
            slot = jnp.mod(i_fc, S_ring)
            stash_f = jnp.where(
                valid_f,
                jax.lax.dynamic_update_index_in_dim(stash_f, fwd_f, slot,
                                                    axis=0),
                stash_f)
            stash_i = jnp.where(
                valid_f,
                jax.lax.dynamic_update_index_in_dim(stash_i, fwd_i, slot,
                                                    axis=0),
                stash_i)
            sacc = sacc + jnp.where(valid_f, scal_f, 0.0)

            # ---- backward unit: microbatch i_b = t - (2pp-2-my_pp) ----
            i_b = t - (2 * pp - 2 - my_pp)
            valid_b = (i_b >= 0) & (i_b < M)
            i_bc = jnp.clip(i_b, 0, M - 1)
            bslot = jnp.mod(i_bc, S_ring)
            f_in_b = jax.lax.dynamic_index_in_dim(stash_f, bslot, axis=0,
                                                  keepdims=False)
            i_in_b = jax.lax.dynamic_index_in_dim(stash_i, bslot, axis=0,
                                                  keepdims=False)

            def g(dp_, f_in):
                f_o, _, scal = stage_apply(dp_, f_in, i_in_b, i_bc)
                return f_o, scal

            _, svjp = jax.vjp(g, dparams, f_in_b)
            # cotangent routing: the loss stage seeds; earlier stages relay
            # the ring cotangent; later stages (post-loss metrics) send 0
            wire_cot = jnp.where(my_pp < self.loss_stage, 1.0, 0.0) * bwd_f
            scal_cot = loss_onehot * jnp.where(
                my_pp == self.loss_stage, jnp.float32(seed), 0.0)
            gP, g_in = svjp((wire_cot, scal_cot))
            gacc = jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0.0).astype(
                    jnp.float32), gacc, gP)

            # ---- ring exchange (unconditional, all ranks) ----
            fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
            fwd_f2 = jax.lax.ppermute(f_out, "pp", fwd_perm)
            fwd_i2 = jax.lax.ppermute(i_out, "pp", fwd_perm)
            bwd_f2 = jax.lax.ppermute(g_in, "pp", bwd_perm)
            return (fwd_f2, fwd_i2, bwd_f2, stash_f, stash_i, gacc,
                    sacc), None

        zf = jnp.zeros((nf,), jnp.float32)
        zi = jnp.zeros((ni,), jnp.int32)
        init = (zf, zi, zf,
                jnp.zeros((S_ring, nf), jnp.float32),
                jnp.zeros((S_ring, ni), jnp.int32),
                jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             dparams),
                jnp.zeros((n_scal,), jnp.float32))
        (_, _, _, _, _, gacc, sacc), _ = jax.lax.scan(
            tick, init, jnp.arange(K))

        grads = jax.tree.map(lambda g: jax.lax.psum(g, ("dp", "pp")), gacc)
        # each scalar is owned by exactly one stage: pp-psum recovers its
        # M-microbatch sum, the dp-psum sums replicas -> mean over both
        scal = jax.lax.psum(sacc, ("dp", "pp")) / float(M * dp)
        return grads, scal

    # ------------------------------------------------------------------
    # host-side driver (same contract as _DataParallelStep.run)
    # ------------------------------------------------------------------
    def run(self, scope, feed):
        from ..compiler import (lift_to_global, normalize_feed_value,
                                read_persistable_state)

        mut, const = read_persistable_state(scope, self.mut_names,
                                            self.const_names)
        feeds = {name: normalize_feed_value(self.block, name, feed[name])
                 for name in self.feed_names}
        if self._multiprocess:
            # DCN case: jit on a multi-process mesh takes only global
            # jax.Arrays. Feeds lift replicated (every worker feeds the
            # identical global batch; the shard_map in_specs reshard the
            # data feeds over dp), state lifts to its planned sharding
            # unless the scope already holds a correctly-sharded array
            # from the previous step.
            def _is_global(a):
                return (isinstance(a, jax.Array)
                        and set(a.sharding.device_set) == self._mesh_devs)

            feeds = {n: (a if _is_global(a)
                         else lift_to_global(a, self._repl))
                     for n, a in feeds.items()}
            for store in (mut, const):
                for name, val in store.items():
                    want = self._state_shardings.get(name, self._repl)
                    if isinstance(val, jax.Array) and \
                            val.sharding.is_equivalent_to(want,
                                                          np.ndim(val)):
                        continue
                    store[name] = lift_to_global(val, want)
        ctr = np.uint32(scope.get("__step_counter__", 0) or 0)
        fetches, new_state = self._jitted(mut, const, feeds, ctr)
        for name, val in new_state.items():
            scope.set(name, val)
        scope.set("__step_counter__", int(ctr) + 1)
        return fetches
