"""Parallelism package (SURVEY §2.3 P1-P12 TPU-native equivalents).

The reference's ParallelExecutor + NCCL op-handle machinery (C10-C14) maps
to jax.sharding over a device Mesh; this package holds the mesh/planner
layer, the data-parallel ParallelExecutor facade, and (beyond the 2019
reference) tensor/pipeline/sequence/expert parallelism built TPU-first.
"""

from .mesh import get_mesh, mesh_axis_sizes  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .ring_attention import ring_attention, ring_attention_sharded  # noqa
from .zero import ShardedAdam, ZeroLayoutError  # noqa: F401
from .dgc import dgc_allreduce, make_dgc_step  # noqa: F401
from .fleet import (fleet, Fleet, PaddleCloudRoleMaker,  # noqa: F401
                    UserDefinedRoleMaker, DistributedStrategy)

__all__ = ["ParallelExecutor", "get_mesh", "mesh_axis_sizes",
           "ring_attention", "ring_attention_sharded", "ShardedAdam",
           "ZeroLayoutError",
           "dgc_allreduce", "make_dgc_step", "fleet", "Fleet",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "DistributedStrategy"]
