"""Program-level sharding planner — the TPU-native multi-device graph builder.

Parity: the reference rewrites ANY user program into an N-device SSA graph
with hand-placed collectives
(framework/ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:165,
CreateAllReduceOp :450, ReduceSSAGraphBuilder multi_devices_graph_pass.h:164).
TPU-native there is NO graph rewrite: the planner assigns every persistable
var a `PartitionSpec` over the step mesh — explicit annotations first
(`ParamAttr(shard_spec=...)` / `BuildStrategy.sharding_specs`), else
auto-derived Megatron-style column/row alternation for fc / embedding
chains — the executor jits the SAME program with those shardings, and XLA
GSPMD propagation inserts the all-reduce / all-gather / reduce-scatter
collectives the reference placed op by op.

Correctness NEVER depends on the plan: GSPMD preserves semantics for any
assignment. The plan buys memory (ZeRO-1 optimizer-state sharding in Reduce
mode) and ICI-efficient tensor parallelism; a bad heuristic only costs speed.
"""

from jax.sharding import PartitionSpec as P

__all__ = ["ShardingPlan", "plan_program"]

# activation mark propagates "last dim is tp-sharded" through these
_ELEMENTWISE_FWD = {
    "relu", "gelu", "tanh", "sigmoid", "dropout", "scale", "cast",
    "elementwise_add", "elementwise_mul", "elementwise_sub", "relu6",
    "swish", "hard_swish", "leaky_relu", "elu", "pow", "square", "abs",
}

# optimizer ops: anything with a Param slot; these slots are NOT state
_NON_STATE_SLOTS = {"Param", "Grad", "LearningRate", "Input", "X"}


class ShardingPlan:
    """specs: {persistable var name: PartitionSpec} (absent -> replicated).
    constraints: {activation var name: PartitionSpec with UNCONSTRAINED
    dims} applied as with_sharding_constraint seams at lowering time."""

    def __init__(self):
        self.specs = {}
        self.constraints = {}

    def spec_of(self, name):
        return self.specs.get(name, P())

    def summary(self):
        return {n: tuple(s) for n, s in sorted(self.specs.items())}


def _sanitize(spec, mesh_axes):
    """Drop axis names the step mesh doesn't have — annotations like
    (None, "tp") are inert on a dp-only mesh instead of erroring."""
    dims = []
    for d in tuple(spec):
        if d is None or d is P.UNCONSTRAINED:
            dims.append(d)
        elif isinstance(d, (tuple, list)):
            kept = tuple(a for a in d if a in mesh_axes)
            dims.append(kept if kept else None)
        else:
            dims.append(d if d in mesh_axes else None)
    return P(*dims)


def _explicit_spec(var, build_strategy, mesh_axes):
    bs_specs = getattr(build_strategy, "sharding_specs", None) or {}
    if var.name in bs_specs:
        return _sanitize(P(*bs_specs[var.name]), mesh_axes)
    ss = getattr(var, "shard_spec", None)
    if ss is not None:
        return _sanitize(P(*ss), mesh_axes)
    return None


def _divisible(dim, n):
    return dim is not None and dim > 0 and dim % n == 0


def _op_stream(block):
    """All ops, descending into control-flow / recompute sub-blocks inline
    (sub-block vars share outer names, so sharding marks flow through)."""
    for op in block.ops:
        for key in ("sub_block", "true_block", "false_block"):
            sub = op.attrs.get(key) if op.attrs else None
            if sub is not None and getattr(sub, "ops", None) is not None:
                yield from _op_stream(sub)
        yield op


def plan_program(program, mesh, build_strategy=None, zero_sharding=False):
    """Derive a ShardingPlan for `program` over `mesh`.

    mesh axes: "dp" (data) and optionally "tp" (tensor). When the mesh has a
    tp axis of size > 1, fc/embedding params are auto-assigned Megatron
    column/row specs unless explicitly annotated. When `zero_sharding`
    (BuildStrategy.ReduceStrategy.Reduce), optimizer-state vars are sharded
    over dp on their leading dim — per-device optimizer bytes drop ~1/dp
    (reduce_op_handle.cc parity, ZeRO-1)."""
    plan = ShardingPlan()
    block = program.global_block()
    mesh_axes = set(mesh.shape)
    tp = dict(mesh.shape).get("tp", 1)
    dp = dict(mesh.shape).get("dp", 1)
    ops = list(_op_stream(block))

    axis_sizes = dict(mesh.shape)

    def _fit(var, spec):
        """Demote spec dims the var's static shape can't divide — jit
        in_shardings (unlike with_sharding_constraint) reject uneven
        dimension sharding. Specs longer than the var's rank truncate
        (docs/PARALLEL.md contract: annotations demote, never error)."""
        shape = getattr(var, "shape", None)
        if shape is None:
            return spec
        spec = P(*tuple(spec)[:len(shape)])
        dims = []
        for i, d in enumerate(tuple(spec)):
            if d is None or shape[i] is None or shape[i] < 0:
                dims.append(d)
                continue
            axes = d if isinstance(d, (tuple, list)) else (d,)
            n = 1
            for a in axes:
                n *= axis_sizes.get(a, 1)
            dims.append(d if n and shape[i] % n == 0 else None)
        return P(*dims)

    def note(var, spec):
        if var.name not in plan.specs:
            plan.specs[var.name] = _fit(var, spec)

    def explicit(var):
        s = _explicit_spec(var, build_strategy, mesh_axes)
        if s is not None:
            plan.specs[var.name] = _fit(var, s)
            return True
        return False

    # 2. Megatron auto-walk: alternate column / row splits along each
    # matmul chain; elementwise ops propagate the "tp-sharded last dim"
    # mark, reductions over the feature dim clear it. Conv chains get the
    # channel-wise analogue: out-channel (dim 0 of OIHW) column split,
    # then in-channel row split with a psum seam — NCHW activations carry
    # a "channel-sharded" mark through elementwise/BN/pool ops (channels
    # are disjoint per rank, so BN's per-channel stats need no collective).
    sharded_last = set()
    ch_sharded = set()  # NCHW activations sharded on dim 1 (channels)
    for op in ops:
        t = op.type
        if t in ("mul", "matmul"):
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            if not xs or not ys:
                continue
            x, y = xs[0], ys[0]
            out = op.outputs.get("Out", [None])[0]
            if not getattr(y, "persistable", False) or y.shape is None \
                    or len(y.shape) != 2:
                continue
            if explicit(y):
                if plan.specs[y.name] and tuple(plan.specs[y.name])[-1:] \
                        == ("tp",) and out is not None:
                    sharded_last.add(out.name)
                continue
            if tp <= 1:
                continue
            if x.name not in sharded_last:
                if _divisible(y.shape[1], tp):
                    note(y, P(None, "tp"))
                    if out is not None:
                        sharded_last.add(out.name)
            else:
                if _divisible(y.shape[0], tp):
                    note(y, P("tp", None))
                # row-parallel output is psum'd back to replicated-over-tp
                if out is not None and out.shape is not None:
                    nd = len(out.shape)
                    plan.constraints[out.name] = P(
                        *([P.UNCONSTRAINED] * (nd - 1) + [None]))
        elif t == "conv2d":
            # (depthwise/grouped convs are left replicated: their filter
            # layout couples both channel dims, no clean column/row split)
            xs = op.inputs.get("Input", [])
            ws = op.inputs.get("Filter", [])
            out = op.outputs.get("Output", [None])[0]
            if not xs or not ws:
                continue
            x, w = xs[0], ws[0]
            if not getattr(w, "persistable", False) or w.shape is None \
                    or len(w.shape) != 4:
                continue
            if explicit(w):
                spec = tuple(plan.specs[w.name])
                if spec[:1] == ("tp",) and out is not None:
                    ch_sharded.add(out.name)
                continue
            if tp <= 1 or (op.attrs or {}).get("groups", 1) not in (1, None):
                continue
            if x.name not in ch_sharded:
                if _divisible(w.shape[0], tp):
                    note(w, P("tp", None, None, None))
                    if out is not None:
                        ch_sharded.add(out.name)
            else:
                if _divisible(w.shape[1], tp):
                    note(w, P(None, "tp", None, None))
                # row-parallel conv output psums back to channel-replicated
                if out is not None and out.shape is not None:
                    nd = len(out.shape)
                    plan.constraints[out.name] = P(
                        *([P.UNCONSTRAINED, None]
                          + [P.UNCONSTRAINED] * (nd - 2)))
        elif t == "batch_norm":
            xs = op.inputs.get("X", [])
            out = op.outputs.get("Y", [None])[0]
            if not xs or out is None or xs[0].name not in ch_sharded:
                continue
            # per-channel params follow the sharded channel axis; channel
            # stats are rank-local because channels are disjoint
            for slot in ("Scale", "Bias", "Mean", "Variance"):
                for v in op.inputs.get(slot, []):
                    if getattr(v, "persistable", False) \
                            and v.shape is not None and len(v.shape) == 1 \
                            and not explicit(v) and _divisible(v.shape[0],
                                                               tp):
                        note(v, P("tp"))
            for vs in op.outputs.values():
                for v in vs:
                    if getattr(v, "persistable", False) \
                            and v.shape is not None and len(v.shape) == 1 \
                            and _divisible(v.shape[0], tp):
                        note(v, P("tp"))
            ch_sharded.add(out.name)
        elif (t == "pool2d" or t in _ELEMENTWISE_FWD) \
                and op.inputs.get("X") \
                and op.inputs["X"][0].name in ch_sharded \
                and t != "elementwise_add":
            for vs in op.outputs.values():
                for v in vs:
                    ch_sharded.add(v.name)
        elif t == "elementwise_add" and op.inputs.get("X") \
                and op.inputs.get("Y") \
                and op.inputs["X"][0].name in ch_sharded:
            # conv bias (1-D persistable [C]) follows the sharded channel
            # axis; two ch-sharded operands (residual add) keep the mark
            y_in = op.inputs["Y"][0]
            out = op.outputs.get("Out", [None])[0]
            if getattr(y_in, "persistable", False) \
                    and y_in.shape is not None and len(y_in.shape) == 1:
                if not explicit(y_in) and tp > 1 \
                        and _divisible(y_in.shape[0], tp):
                    note(y_in, P("tp"))
                if out is not None:
                    ch_sharded.add(out.name)
            elif y_in.name in ch_sharded and out is not None:
                ch_sharded.add(out.name)
        elif t in ("lookup_table", "lookup_table_v2"):
            ws = op.inputs.get("W", [])
            if not ws:
                continue
            w = ws[0]
            if explicit(w):
                continue
            if tp > 1 and w.shape is not None and len(w.shape) == 2 \
                    and _divisible(w.shape[0], tp):
                # vocab-row sharding (Megatron VocabParallelEmbedding);
                # GSPMD lowers the gather to a masked lookup + psum
                note(w, P("tp", None))
        elif t == "elementwise_add":
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            out = op.outputs.get("Out", [None])[0]
            if not xs or not ys or out is None:
                continue
            x, y = xs[0], ys[0]
            if getattr(y, "persistable", False) and y.shape is not None \
                    and len(y.shape) == 1:
                # bias: follow the activation it lands on
                if not explicit(y) and tp > 1 and x.name in sharded_last \
                        and _divisible(y.shape[0], tp):
                    note(y, P("tp"))
                if x.name in sharded_last:
                    sharded_last.add(out.name)
            elif x.name in sharded_last and y.name in sharded_last:
                sharded_last.add(out.name)
        elif t in _ELEMENTWISE_FWD:
            xs = op.inputs.get("X", [])
            out = op.outputs.get("Out", [None])[0]
            if xs and out is not None and xs[0].name in sharded_last:
                sharded_last.add(out.name)
        elif t == "split":
            xs = op.inputs.get("X", [])
            if xs and xs[0].name in sharded_last:
                for vs in op.outputs.values():
                    for v in vs:
                        sharded_last.add(v.name)
        # any other op (layer_norm, softmax, reshape, reduce_*) does not
        # propagate the mark: the chain re-seeds at the next column split

    # 3. explicit annotations for params the walk never touched
    for op in ops:
        for vs in op.inputs.values():
            for v in vs:
                if getattr(v, "persistable", False) \
                        and v.name not in plan.specs:
                    explicit(v)

    # 3.5 diagnose the silent no-op: a tp degree that shards NOTHING means
    # the walk found no eligible fc/embedding chain and no annotation
    # matched — the user pays a tp-sliced mesh (smaller dp) for zero
    # model parallelism, so say so once, host-side
    if tp > 1 and not any(
            "tp" in (a for d in tuple(s) if d is not None
                     for a in (d if isinstance(d, (tuple, list)) else (d,)))
            for s in plan.specs.values()):
        import warnings

        warnings.warn(
            "tensor_parallel_degree=%d produced no tp-sharded parameters: "
            "no fc/embedding chain was auto-shardable (dims must divide "
            "tp) and no shard_spec annotation matched. The program runs "
            "correctly but fully replicated over the tp axis — annotate "
            "params via ParamAttr(shard_spec=...) or "
            "BuildStrategy.sharding_specs, or drop the tp degree." % tp,
            RuntimeWarning, stacklevel=3)

    # 4. ZeRO-1 (Reduce mode): shard optimizer state over dp on dim 0.
    # State var = any persistable input of an op carrying a Param slot,
    # shaped like the param, that is not the param/grad itself.
    if zero_sharding and dp > 1:
        for op in ops:
            params = op.inputs.get("Param")
            if not params:
                continue
            pshape = params[0].shape
            for slot, vs in op.inputs.items():
                if slot in _NON_STATE_SLOTS:
                    continue
                for v in vs:
                    if not getattr(v, "persistable", False):
                        continue
                    if v.shape is None or len(v.shape) == 0 \
                            or tuple(v.shape) != tuple(pshape or ()):
                        continue
                    if v.shape[0] < dp:
                        continue
                    base = plan.specs.get(v.name)
                    if base is not None and len(base) > 0 \
                            and base[0] is not None:
                        continue  # dim 0 already taken (e.g. row-tp)
                    rest = tuple(base[1:]) if base else ()
                    rest = rest + (None,) * max(
                        0, len(v.shape) - 1 - len(rest))
                    plan.specs[v.name] = _fit(v, P("dp", *rest))
    return plan
