"""Async host-embedding prefetch + hot-row device cache (docs/
RECOMMENDER.md; Monolith, arXiv:2209.07663 — overlap the sparse
parameter exchange with compute and keep hot rows near the accelerator).

The legacy `host_embedding_lookup` pays a synchronous host round-trip
inside every compiled step: the forward is a blocking `jax.pure_callback`
gather under the table lock. This module removes it from the hot path:

  1. `HostEmbeddingPrefetcher.announce_iter` rides the train_from_dataset
     batch stream — as batch t+1 is pulled for H2D staging (the PR-2
     FeedPrefetcher lookahead), its ids are handed to a background worker
     that dedups them (`np.unique`), gathers the unique rows from the
     host table OFF the critical path, and pads them into a
     `[n_flat_ids, dim]` buffer.
  2. The `embed_prefetch_rewrite` pass rewires `lookup_table_host` ops on
     the compile clone to `lookup_table_prefetched`, which reads that
     buffer (+ inverse indices) as ordinary prefetched device feeds — no
     in-step callback. The legacy op remains the fallback for any run
     without a staged pipeline (direct exe.run, flag unset).
  3. A frequency/LRU-admission `HotRowCache` keeps hot rows resident in a
     device-side `[cache_rows, dim]` array; unique rows found in the
     cache skip the host gather entirely, and pushes write through
     (refresh-on-dirty) so the cached path stays bitwise-agreed with
     `pull(raw_ids)`.

Bitwise coherence contract: the step for batch t must observe the table
exactly as the synchronous path would — i.e. after the pushes of steps
0..t-1 and nothing else. `finalize_into` therefore (a) barriers on the
applied-push count (each table reports optimizer applications through
its push observers, including merged Communicator batches), and (b)
re-pulls any staged/cached row dirtied since its gather. The pinned
identity tests in tests/test_embedding_pipeline.py enforce this.
"""

import hashlib
import queue
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..analysis.concurrency import check_blocking
from ..ir import Pass, register_pass
from ..observability import metrics as _metrics

__all__ = ["EmbedPrefetchConfig", "HotRowCache", "HostEmbeddingPrefetcher",
           "active_config", "maybe_pipeline", "feed_names"]

# how long the coherence barrier waits for the previous steps' pushes
# before declaring the stream wedged (a dead pusher thread, a step that
# never ran its backward)
_BARRIER_TIMEOUT_S = 120.0


def feed_names(table_name):
    """The reserved feed-var names the rewrite pass and the pipeline
    agree on for one table (all is_data, never user-visible)."""
    return {
        "rows": "__embed_rows__%s" % table_name,
        "inv": "__embed_inv__%s" % table_name,
        "hit": "__embed_hit__%s" % table_name,
        "slot": "__embed_slot__%s" % table_name,
        "cache": "__embed_cache__%s" % table_name,
    }


class EmbedPrefetchConfig:
    """Resolved prefetch policy pinned on a program as `_embed_config`
    (the amp.AmpConfig decoration pattern). Presence of the decoration —
    set only by an active HostEmbeddingPrefetcher — is what arms the
    rewrite pass; a bare PTPU_EMBED_PREFETCH env without a pipeline never
    rewrites (the compiled step would expect feeds nobody stages)."""

    def __init__(self, tables, cache_rows=0, cache_admit=2):
        self.tables = tuple(sorted(tables))
        self.cache_rows = int(cache_rows)
        self.cache_admit = max(1, int(cache_admit))

    def cache_key(self):
        """Short stable digest for the compile-cache pipeline key."""
        h = hashlib.sha1()
        h.update(repr((self.tables, self.cache_rows,
                       self.cache_admit)).encode())
        return "%d:%d:%s" % (self.cache_rows, self.cache_admit,
                             h.hexdigest()[:8])


def active_config(program=None):
    """The prefetch config in effect for one compile, or None. Unlike
    AMP there is no env/BuildStrategy leg: only the pipeline decoration
    counts (see EmbedPrefetchConfig docstring)."""
    return getattr(program, "_embed_config", None) \
        if program is not None else None


def _inspect_program(program):
    """(lookup sites, push sites) for every host table in `program`.

    sites: {table_name: (ids var, n_lookup_ops)} — only tables with
    exactly ONE lookup whose Ids input is a data feed are prefetchable
    (one staged buffer per table per step).
    push_sites: {table_name: n_grad_ops} — how many sparse pushes one
    executed step emits per table; the coherence barrier's unit."""
    sites = {}
    push_sites = {}
    for blk in program.blocks:
        for op in blk.ops:
            if op.type in ("lookup_table_host", "lookup_table_prefetched"):
                tab = op.attrs["table_name"]
                ids_v, n = sites.get(tab, (None, 0))
                sites[tab] = (ids_v or op.inputs["Ids"][0], n + 1)
            if "__fwd_op__" in op.attrs:
                f = op.attrs["__fwd_op__"]
                while "__fwd_op__" in f.attrs:
                    f = f.attrs["__fwd_op__"]
                if f.type in ("lookup_table_host",
                              "lookup_table_prefetched"):
                    tab = f.attrs["table_name"]
                    push_sites[tab] = push_sites.get(tab, 0) + 1
    return sites, push_sites


def maybe_pipeline(program):
    """Build the prefetcher train_from_dataset attaches when
    PTPU_EMBED_PREFETCH=1 and `program` has prefetchable host-embedding
    lookups; None otherwise (the exact legacy path)."""
    from ..flags import env as _env

    if not _env("PTPU_EMBED_PREFETCH"):
        return None
    sites, _ = _inspect_program(program)
    eligible = [tab for tab, (ids_v, n) in sites.items()
                if n == 1 and getattr(ids_v, "is_data", False)]
    if not eligible:
        return None
    cfg = EmbedPrefetchConfig(
        eligible,
        cache_rows=_env("PTPU_EMBED_CACHE_ROWS"),
        cache_admit=_env("PTPU_EMBED_CACHE_ADMIT"))
    return HostEmbeddingPrefetcher(program, cfg)


# ---------------------------------------------------------------------------
# hot-row device cache
# ---------------------------------------------------------------------------


class HotRowCache:
    """Frequency-admitted, LRU-evicted device-resident row cache for one
    table: a `[cache_rows, dim]` jax array plus a host-side id→slot map.
    A row is admitted once `admit` distinct batches have touched it;
    pushes write through (the pipeline re-pulls dirtied cached rows and
    scatters the fresh values) so a cache hit is always bitwise the
    value `table.pull` would return. All mutation happens under the
    pipeline's finalize lock — this class is not itself thread-safe."""

    def __init__(self, table, rows, admit):
        import jax.numpy as jnp

        self.table = table
        self.rows = int(rows)
        self.admit = int(admit)
        self.arr = jnp.zeros((self.rows, table.dim), jnp.float32)
        self.slot_of = {}                # row id -> slot
        self._free = list(range(self.rows - 1, -1, -1))
        self._lru = OrderedDict()        # row id -> None, oldest first
        self._freq = {}                  # row id -> distinct-batch count

    def touch(self, row):
        """Mark a cached row used this step (LRU recency)."""
        self._lru.move_to_end(row)

    def note_use(self, row):
        """Count one distinct-batch touch toward admission; True once
        the row has earned a slot."""
        n = self._freq.get(row, 0) + 1
        self._freq[row] = n
        return n >= self.admit

    def _take_slot(self, protect=frozenset()):
        if self._free:
            return self._free.pop()
        for victim in self._lru:          # oldest first
            if victim in protect:
                continue
            del self._lru[victim]
            slot = self.slot_of.pop(victim)
            if _metrics.enabled():
                _metrics.counter("embed/cache_evictions").inc()
            return slot
        return None

    def admit_rows(self, rows_vals, protect=frozenset()):
        """Install [(row, value)] pairs, evicting LRU victims as needed.
        Rows in `protect` (this step's hits — their slots are already
        baked into the staged Slot feed) are never victims. Returns the
        number admitted."""
        updates = []
        for row, val in rows_vals:
            if row in self.slot_of:
                continue
            slot = self._take_slot(protect)
            if slot is None:
                break
            self.slot_of[row] = slot
            self._lru[row] = None
            updates.append((slot, val))
        if updates:
            self._scatter(updates)
        return len(updates)

    def refresh(self, rows, vals):
        """Write-through: overwrite already-cached rows with fresh table
        values (the push-dirty protocol)."""
        self._scatter([(self.slot_of[r], v) for r, v in zip(rows, vals)])

    def _scatter(self, slot_vals):
        import jax.numpy as jnp

        idx = np.array([s for s, _ in slot_vals], np.int32)
        vals = np.stack([v for _, v in slot_vals]).astype(np.float32)
        self.arr = self.arr.at[idx].set(jnp.asarray(vals))


# ---------------------------------------------------------------------------
# the prefetcher
# ---------------------------------------------------------------------------


class _TableState:
    """Per-table pipeline bookkeeping (see HostEmbeddingPrefetcher)."""

    __slots__ = ("table", "ids_name", "push_sites", "cache", "applied",
                 "dirty_log", "dirty_base", "cache_clean", "names")

    def __init__(self, table, ids_name, push_sites, cache):
        self.table = table
        self.ids_name = ids_name
        self.push_sites = push_sites
        self.cache = cache
        self.applied = 0          # optimizer applications observed
        self.dirty_log = []       # list of np row arrays, per application
        self.dirty_base = 0       # absolute index of dirty_log[0]
        self.cache_clean = 0      # abs dirty index the cache is synced to
        self.names = feed_names(table.name)

    def dirty_end(self):
        return self.dirty_base + len(self.dirty_log)

    def dirty_since(self, abs_idx):
        ents = self.dirty_log[max(0, abs_idx - self.dirty_base):]
        if not ents:
            return None
        return np.unique(np.concatenate(ents))

    def trim_dirty(self, keep_from):
        drop = min(max(0, keep_from - self.dirty_base),
                   len(self.dirty_log))
        if drop:
            del self.dirty_log[:drop]
            self.dirty_base += drop


class _TableTicket:
    __slots__ = ("ids", "u_rows", "inv", "buf", "pulled", "log_idx")

    def __init__(self, ids):
        self.ids = ids
        self.log_idx = None


class _Ticket:
    __slots__ = ("per_table", "done", "error")

    def __init__(self):
        self.per_table = {}
        self.done = threading.Event()
        self.error = None


class HostEmbeddingPrefetcher:
    """Stages each batch's embedding rows one step ahead of the device.

    Wiring (train_from_dataset):

        pipeline = maybe_pipeline(program)          # decorates program
        batches = pipeline.announce_iter(batches)   # taps the id stream
        for feed in prefetch_iter(batches, device_feeder):
            feed = pipeline.finalize_into(feed)     # merge staged arrays
            exe.run(program, feed=feed, ...)

    `announce_iter` sees batch t+1 while the device still owns batch t
    (the FeedPrefetcher lookahead pulls ahead of consumption), so the
    dedup + host gather run on this object's worker thread concurrently
    with the compiled step. `finalize_into` then settles coherence for
    the batch actually about to run: barrier on prior steps' pushes,
    re-pull rows dirtied since the gather, serve hot rows from the
    device cache, and hand the step its staged feeds."""

    def __init__(self, program, cfg):
        from .host_embedding import HostEmbeddingTable

        self.program = program
        self.cfg = cfg
        sites, push_sites = _inspect_program(program)
        self._tables = {}
        for tab in cfg.tables:
            ids_v, n = sites[tab]
            table = HostEmbeddingTable.get(tab)
            cache = (HotRowCache(table, cfg.cache_rows, cfg.cache_admit)
                     if cfg.cache_rows > 0 else None)
            self._tables[tab] = _TableState(
                table, ids_v.name, push_sites.get(tab, 0), cache)
        # finalize/observer rendezvous: applied-push counts, dirty logs
        # and caches all mutate under this condition's lock
        self._cv = threading.Condition()
        self._steps_finalized = 0
        self._pending = deque()
        self._observers = []
        for tab, ts in self._tables.items():
            fn = self._make_observer(ts)
            ts.table.add_push_observer(fn)
            self._observers.append((ts.table, fn))
        self._work = queue.Queue()
        self._closed = False
        self._worker = threading.Thread(
            target=self._worker_run, name="embed-prefetch", daemon=True)
        self._worker.start()
        # arm the rewrite pass: the decoration travels into the compile
        # clone (Program.clone) and flips the pipeline cache key
        program._embed_config = cfg

    # -- push observation -------------------------------------------------

    def _make_observer(self, ts):
        def on_push(rows_global, n_pushes):
            with self._cv:
                ts.applied += n_pushes
                ts.dirty_log.append(np.asarray(rows_global))
                self._cv.notify_all()
        return on_push

    # -- the announce leg (background gather) -----------------------------

    def announce(self, feed):
        """Snapshot batch ids and enqueue the background gather; returns
        the ticket finalize_into will settle (FIFO)."""
        ticket = _Ticket()
        for tab, ts in self._tables.items():
            if ts.ids_name not in feed:
                raise KeyError(
                    "embed prefetch: batch feed has no %r (the Ids input "
                    "of table %r); feeds present: %s"
                    % (ts.ids_name, tab, sorted(feed)))
            ids = np.asarray(feed[ts.ids_name]).copy()
            ticket.per_table[tab] = _TableTicket(ids)
        self._pending.append(ticket)
        self._work.put(ticket)
        return ticket

    def announce_iter(self, batches):
        """Tap a batch-feed stream: announce each batch as the H2D
        lookahead pulls it, pass the feed through unchanged."""
        for feed in batches:
            self.announce(feed)
            yield feed

    def _worker_run(self):
        while True:
            ticket = self._work.get()
            if ticket is None:
                return
            try:
                for tab, ts in self._tables.items():
                    self._gather_one(ts, ticket.per_table[tab])
            except BaseException as e:  # re-raised on the training thread
                ticket.error = e
            finally:
                ticket.done.set()

    def _gather_one(self, ts, tt):
        rows_glob = ts.table.global_rows(tt.ids)
        u_rows, inv = np.unique(rows_glob, return_inverse=True)
        with self._cv:
            # everything pushed from here on is "dirty": it may or may
            # not be visible to the pull below, so finalize re-pulls it
            tt.log_idx = ts.dirty_end()
            cached = (np.array([r in ts.cache.slot_of for r in u_rows],
                               bool)
                      if ts.cache is not None
                      else np.zeros(u_rows.size, bool))
        to_pull = u_rows[~cached]
        t0 = time.perf_counter()
        vals = (ts.table.pull(to_pull) if to_pull.size
                else np.zeros((0, ts.table.dim), np.float32))
        if _metrics.enabled():
            _metrics.histogram("embed/gather_ms").observe(
                (time.perf_counter() - t0) * 1e3)
        # pad the unique rows into a buffer of STATIC length n_flat_ids:
        # n_unique varies batch to batch and would retrace the jitted
        # step; the tail rows stay zero and are never indexed
        buf = np.zeros((rows_glob.size, ts.table.dim), np.float32)
        buf[np.flatnonzero(~cached)] = vals
        tt.u_rows, tt.inv = u_rows, inv.astype(np.int32)
        tt.buf, tt.pulled = buf, ~cached

    # -- the finalize leg (coherence + merge) -----------------------------

    def _wait_prior_pushes(self):
        """Barrier: every push the already-consumed steps owe must be
        APPLIED before this step's values are settled — the synchronous
        path's implicit ordering, restated as a count. Each executed
        step owes `push_sites` applications per table (the Communicator
        reports merged batches with their multiplicity)."""
        t = self._steps_finalized
        need = {tab: t * ts.push_sites for tab, ts in self._tables.items()
                if ts.push_sites}
        if not need:
            return
        check_blocking("cond.wait", "embed_pipeline.finalize")
        deadline = time.monotonic() + _BARRIER_TIMEOUT_S
        with self._cv:
            while any(self._tables[tab].applied < n
                      for tab, n in need.items()):
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=min(left, 1.0)):
                    if time.monotonic() >= deadline:
                        got = {tab: self._tables[tab].applied
                               for tab in need}
                        raise RuntimeError(
                            "embed prefetch coherence barrier timed out "
                            "after %.0fs: step %d needs applied pushes "
                            "%r but observed %r — is the Communicator "
                            "send thread alive?"
                            % (_BARRIER_TIMEOUT_S, t, need, got))

    def finalize_into(self, feed):
        """Settle the oldest announced batch and return `feed` merged
        with its staged embedding arrays (the feeds the rewritten step
        consumes). Must be called exactly once per announced batch, in
        order, immediately before the step runs."""
        if not self._pending:
            raise RuntimeError("finalize_into with no announced batch")
        ticket = self._pending.popleft()
        self._wait_prior_pushes()
        if not ticket.done.is_set():
            check_blocking("event.wait", "embed_pipeline.finalize")
            ticket.done.wait()
        if ticket.error is not None:
            raise RuntimeError("embed prefetch gather worker died") \
                from ticket.error
        merged = dict(feed)
        rec = _metrics.enabled()
        with self._cv:
            for tab, ts in self._tables.items():
                tt = ticket.per_table[tab]
                self._settle_table(ts, tt, merged, rec)
            for tab, ts in self._tables.items():
                # entries older than every outstanding gather's snapshot
                # can never be asked for again; a not-yet-processed
                # ticket will snapshot at >= the current end
                idxs = [t.per_table[tab].log_idx
                        if t.per_table[tab].log_idx is not None
                        else ts.dirty_end()
                        for t in self._pending]
                ts.trim_dirty(min(idxs) if idxs else ts.dirty_end())
            self._steps_finalized += 1
        return merged

    def _settle_table(self, ts, tt, merged, rec):
        u_rows, cache = tt.u_rows, ts.cache
        dirty = ts.dirty_since(tt.log_idx)
        dirty_set = set(dirty.tolist()) if dirty is not None else ()
        hit = None
        if cache is not None:
            # write-through refresh: cached rows dirtied by pushes take
            # their fresh table values BEFORE this step reads the cache
            # — pull(raw_ids) and the cached path agree. The window is
            # the cache's own watermark, NOT the gather snapshot: a late
            # gather may snapshot AFTER pushes the cache never saw.
            cache_dirty = ts.dirty_since(ts.cache_clean)
            if cache_dirty is not None:
                stale = [r for r in cache_dirty.tolist()
                         if r in cache.slot_of]
                if stale:
                    cache.refresh(stale, ts.table.pull(
                        np.asarray(stale, np.int64)))
            ts.cache_clean = ts.dirty_end()
            hit = np.array([r in cache.slot_of for r in u_rows], bool)
            for r in u_rows[hit].tolist():
                cache.touch(r)
        # staged-buffer fixup: a buffer row is served only when not a
        # cache hit; it must be re-pulled when the gather skipped it
        # (cached then, evicted since) or a push dirtied it after the
        # gather snapshot
        serve_buf = ~hit if hit is not None else np.ones(u_rows.size, bool)
        need = serve_buf & (~tt.pulled
                            | np.array([r in dirty_set
                                        for r in u_rows.tolist()], bool))
        n_fix = int(np.count_nonzero(need))
        if n_fix:
            tt.buf[np.flatnonzero(need)] = ts.table.pull(u_rows[need])
        if rec:
            n_hit = int(hit.sum()) if hit is not None else 0
            _metrics.counter("embed/cache_hits").inc(n_hit)
            # unique rows served straight from the background gather —
            # neither a cache hit nor an in-finalize repair
            _metrics.counter("embed/prefetch_hits").inc(
                int(u_rows.size) - n_hit - n_fix)
        if cache is not None:
            # frequency admission: rows touched by `admit` distinct
            # batches earn a slot, seeded with this step's fresh value
            admit = [(r, tt.buf[k])
                     for k, r in enumerate(u_rows.tolist())
                     if cache.note_use(r) and not (hit is not None
                                                   and hit[k])]
            if admit:
                # this step's hits keep their slots: the Slot feed below
                # bakes them in, so evicting one would point the step at
                # a reused slot holding some other row's value
                cache.admit_rows(admit, protect=set(
                    u_rows[hit].tolist()) if hit is not None else ())
        merged[ts.names["rows"]] = tt.buf
        merged[ts.names["inv"]] = tt.inv
        if cache is not None:
            # padded to the buffer's static n_flat length like the rows
            # themselves (the tail is never indexed by inv)
            n = tt.buf.shape[0]
            hit_f = np.zeros(n, np.int32)
            hit_f[:u_rows.size] = hit.astype(np.int32)
            slot_f = np.zeros(n, np.int32)
            slot_f[:u_rows.size] = [cache.slot_of.get(r, 0)
                                    for r in u_rows.tolist()]
            merged[ts.names["hit"]] = hit_f
            merged[ts.names["slot"]] = slot_f
            merged[ts.names["cache"]] = cache.arr

    # -- lifecycle --------------------------------------------------------

    def close(self):
        """Detach: stop the worker, unregister observers and remove the
        program decoration so later direct exe.run calls compile the
        legacy synchronous lookup again (the no-pipeline fallback)."""
        if self._closed:
            return
        self._closed = True
        self._work.put(None)
        self._worker.join(timeout=10)
        for table, fn in self._observers:
            table.remove_push_observer(fn)
        self._observers = []
        if getattr(self.program, "_embed_config", None) is self.cfg:
            del self.program._embed_config

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# the rewrite pass
# ---------------------------------------------------------------------------


@register_pass("embed_prefetch_rewrite")
class EmbedPrefetchRewritePass(Pass):
    """Rewire `lookup_table_host` ops to `lookup_table_prefetched` on the
    compile clone (the amp_rewrite in-place decoration pattern).
    Soundness:

      - fires only under an active pipeline decoration (`_embed_config`,
        set by HostEmbeddingPrefetcher) — a bare env flag never rewrites;
      - the staged vars are created `is_data` (fed every step by
        finalize_into), so the verifier's use-before-def anchor holds;
      - every grad op whose `__fwd_op__` is a rewritten lookup gains the
        new input slots: `_gather_grad_ins` iterates the GRAD op's own
        slots, so without them the generic vjp kernel would miss Rows/
        Inv at apply time. No `__grad_in_map__` entries are needed — the
        new slots are nondiff (zero/float0 cotangents, never named);
      - the backward push is the kernel's own io_callback, byte-
        identical to the legacy op's, so table updates are unchanged.
    """

    def apply(self, program, scope=None):
        cfg = active_config(program)
        if cfg is None:
            return program
        from .host_embedding import HostEmbeddingTable

        grad_ops = {}
        for blk in program.blocks:
            for op in blk.ops:
                fwd = op.attrs.get("__fwd_op__")
                if fwd is not None:
                    grad_ops.setdefault(id(fwd), []).append(op)
        block = program.global_block()
        for op in list(block.ops):
            if op.type != "lookup_table_host":
                continue
            tab = op.attrs["table_name"]
            if tab not in cfg.tables:
                continue
            dim = HostEmbeddingTable.get(tab).dim
            names = feed_names(tab)
            new_ins = {
                "Rows": block.create_var(
                    name=names["rows"], shape=[-1, dim], dtype="float32",
                    is_data=True, stop_gradient=True),
                "Inv": block.create_var(
                    name=names["inv"], shape=[-1], dtype="int32",
                    is_data=True, stop_gradient=True),
            }
            if cfg.cache_rows > 0:
                new_ins["Hit"] = block.create_var(
                    name=names["hit"], shape=[-1], dtype="int32",
                    is_data=True, stop_gradient=True)
                new_ins["Slot"] = block.create_var(
                    name=names["slot"], shape=[-1], dtype="int32",
                    is_data=True, stop_gradient=True)
                new_ins["Cache"] = block.create_var(
                    name=names["cache"], shape=[cfg.cache_rows, dim],
                    dtype="float32", is_data=True, stop_gradient=True)
            op.type = "lookup_table_prefetched"
            for slot, v in new_ins.items():
                op.inputs[slot] = [v]
            for gop in grad_ops.get(id(op), ()):
                for slot, v in new_ins.items():
                    gop.inputs[slot] = [v]
        return program
