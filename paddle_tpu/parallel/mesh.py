"""Device mesh management (the TPU-native replacement for
platform/nccl_helper.h NCCLContextMap — topology comes from the runtime,
no communicator init).
"""

import numpy as np

import jax
from jax.sharding import Mesh

_default_mesh = [None]


def get_mesh(axis_names=("dp",), shape=None, devices=None):
    """Build (and cache the default) Mesh. With shape=None all devices go on
    the first axis."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=axis_names)


def default_mesh():
    if _default_mesh[0] is None:
        _default_mesh[0] = get_mesh()
    return _default_mesh[0]


def set_default_mesh(mesh):
    _default_mesh[0] = mesh


def mesh_axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def current_abstract_mesh(fallback):
    """The mesh shardings must bind to INSIDE a (partial-)manual
    shard_map region: the context abstract mesh carries the Manual axis
    types — a concrete-mesh NamedSharding there poisons downstream avals
    with a mismatched all-Auto mesh. Outside any region, `fallback`."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:  # jax < 0.5 has no tracing-context abstract mesh
        return fallback
    cmesh = get()
    return fallback if cmesh is None or cmesh.empty else cmesh
