"""Deep Gradient Compression (parity: SURVEY §2.3 P9 —
details/sparse_all_reduce_op_handle.cc:43 `RunImplEncoded` top-k encode +
ncclAllGather :112-129; dgc_op.cc; optimizer.py:640 DGCMomentumOptimizer).

TPU-native: inside shard_map over the dp axis each rank keeps an error-
feedback residual (momentum correction), top-k selects the largest-magnitude
entries of (residual + grad), and only (values, indices) all_gather across
the ring — k/N of the allreduce bytes. The gathered sparse updates scatter-
add into a dense tensor on every rank, which stays bit-identical across
ranks (deterministic collective order parity: all_reduce_deps_pass).
"""

import functools

import jax
import jax.numpy as jnp


def topk_sparsify(x, k):
    """(values, indices) of the k largest-|x| entries of flat x; the dense
    complement (what stays in the residual)."""
    flat = x.reshape(-1)
    vals, idx = jax.lax.top_k(jnp.abs(flat), k)
    picked = flat[idx]
    dense_kept = jnp.zeros_like(flat).at[idx].set(picked)
    residual = flat - dense_kept
    return picked, idx, residual.reshape(x.shape)


def dgc_allreduce(grad, residual, axis_name, sparsity=0.99, momentum=0.9):
    """One DGC round for one gradient tensor inside shard_map.

    Returns (dense averaged sparse-allreduced grad, new residual).
    residual carries momentum-corrected unsent mass (dgc_op.cc encode)."""
    n = jax.lax.psum(1, axis_name)
    acc = residual * momentum + grad
    k = max(1, int(acc.size * (1.0 - sparsity)))
    vals, idx, new_residual = topk_sparsify(acc, k)

    all_vals = jax.lax.all_gather(vals, axis_name)   # [n, k]
    all_idx = jax.lax.all_gather(idx, axis_name)     # [n, k]
    dense = jnp.zeros((acc.size,), acc.dtype)
    dense = dense.at[all_idx.reshape(-1)].add(all_vals.reshape(-1))
    return (dense / n).reshape(grad.shape), new_residual


def make_dgc_step(mesh, loss_fn, lr=0.1, momentum=0.9, sparsity=0.99,
                  axis_name="dp"):
    """jitted (params, residuals, velocities, *batch-shards) ->
    (params, residuals, velocities, loss) — momentum SGD over DGC-compressed
    gradients (DGCMomentumOptimizer parity)."""
    from jax.sharding import PartitionSpec as P
    from ..core.jax_compat import shard_map

    def rank_step(params, residuals, velocities, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        loss = jax.lax.pmean(loss, axis_name)

        def upd(p, g, r, vel):
            g_avg, r_new = dgc_allreduce(g, r, axis_name, sparsity, momentum)
            vel_new = momentum * vel + g_avg
            return p - lr * vel_new, r_new, vel_new

        flat_p, tdef = jax.tree.flatten(params)
        out = [upd(p, g, r, v) for p, g, r, v in zip(
            flat_p, tdef.flatten_up_to(grads),
            tdef.flatten_up_to(residuals),
            tdef.flatten_up_to(velocities))]
        return (tdef.unflatten([o[0] for o in out]),
                tdef.unflatten([o[1] for o in out]),
                tdef.unflatten([o[2] for o in out]), loss)

    rep = P()
    data = P(axis_name)
    fn = shard_map(
        rank_step, mesh=mesh,
        in_specs=(rep, rep, rep, data, data),
        out_specs=(rep, rep, rep, rep),
        check_vma=False)
    return jax.jit(fn, donate_argnums=(0, 1, 2))
