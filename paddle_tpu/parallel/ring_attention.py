"""Ring attention — context/sequence parallelism for long sequences
(SURVEY §5.7: "the scale-sequence-length axis of the new framework is new
design work with no reference counterpart").

Each rank of the `axis` ring holds a sequence shard of Q, K, V
([B, H, T/n, D]). K/V blocks rotate around the ring with `ppermute` while
every rank accumulates its Q-shard's attention with the online-softmax
(flash) recurrence, so the full [T, T] score matrix never exists on any
chip and per-chip memory stays O(T/n). The rotation rides ICI neighbor
links; compute on block i overlaps the transfer of block i+1 (XLA schedules
the independent ppermute DMA concurrently with the matmuls).

Differentiable: the whole loop is a lax.scan of pure ops; reverse-mode
routes cotangents back through the reversed ring automatically.
"""

import functools

import jax
import jax.numpy as jnp

from ..core.jax_compat import axis_index as _axis_index

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, scale, causal):
    """Online-softmax partial update for one (Q-shard, KV-block) pair.
    q: [B, H, Tq, D], k/v: [B, H, Tk, D]. Returns (m, l, acc) deltas."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        qpos = q_off + jnp.arange(Tq)[:, None]
        kpos = k_off + jnp.arange(Tk)[None, :]
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    m_blk = s.max(axis=-1)                                   # [B,H,Tq]
    p = jnp.exp(s - m_blk[..., None])
    # fully-masked rows (possible on far ring ranks): zero, don't count
    p = jnp.where(s > _NEG_INF / 2, p, 0.0)
    l_blk = p.sum(axis=-1)
    acc_blk = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_blk, l_blk, acc_blk


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None):
    """Attention over a sequence sharded on mesh axis `axis_name`.

    Call inside shard_map; q, k, v: [B, H, T_local, D] per-rank shards of a
    length-(n*T_local) sequence laid out contiguously by rank order.
    """
    n = jax.lax.psum(1, axis_name)
    rank = _axis_index(axis_name)
    B, H, Tl, D = q.shape
    scale = sm_scale if sm_scale is not None else D ** -0.5
    q_off = rank * Tl

    # accumulators must be device-varying over the ring axis for the scan
    # carry to type-check under shard_map (vma tracking)
    zero_like_q = jnp.zeros_like(q[..., 0], jnp.float32)
    m0 = zero_like_q + _NEG_INF
    l0 = zero_like_q
    acc0 = jnp.zeros_like(q, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]  # rotate kv to next rank

    def step(carry, i):
        m, l, acc, kb, vb = carry
        # kv block currently held came from rank (rank - i) mod n
        k_off = ((rank - i) % n) * Tl
        m_blk, l_blk, acc_blk = _block_attn(q, kb, vb, q_off, k_off, scale,
                                            causal)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_blk - m_new)
        l = l * alpha + l_blk * beta
        acc = acc * alpha[..., None] + acc_blk * beta[..., None]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (m_new, l, acc, kb, vb), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=True,
                           sm_scale=None, partial_manual=False):
    """Convenience wrapper: shard_map ring_attention over `mesh` with the
    sequence dimension of [B, H, T, D] partitioned on `axis_name`.

    partial_manual=True makes only `axis_name` manual (other mesh axes
    stay GSPMD-auto) — the form the descriptor-path flash_attention op
    uses inside a jitted step whose dp/tp axes GSPMD manages."""
    from jax.sharding import PartitionSpec as P
    from ..core.jax_compat import shard_map

    spec = P(None, None, axis_name, None)
    kwargs = ({"axis_names": {axis_name}, "check_vma": False}
              if partial_manual else {})
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **kwargs)
    return fn(q, k, v)
