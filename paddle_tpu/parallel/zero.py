"""Sharded-optimizer data parallelism (parity: the reference's Reduce mode —
`ReduceSSAGraphBuilder` multi_devices_graph_pass.h:164 /
details/reduce_op_handle.cc, SURVEY §2.3 P2: "each param's grad reduced to
one owner device, updated there, then broadcast — ZeRO-1-like ancestor"),
grown into the full ZeRO ladder with comm/compute overlap (docs/ZERO.md;
Rajbhandari et al. SC 2020, Li et al. VLDB 2020).

Sharding levels (`zero_stage` / $PTPU_ZERO_STAGE):

  1  optimizer-state sharding (the historical default): each gradient is
     reduce-scattered along the dp axis, Adam's m/v live only as
     rank-local shards, and updated parameter slices all-gather back to
     the full (replicated) parameters — per-leaf collectives, or a few
     large flattened buckets with `bucket_mb` set (Megatron DDP parity,
     PR 5).
  2  + gradient sharding: bucketing is mandatory and each bucket's
     gradients exist only as dp-sharded bucket shards past the
     reduce-scatter boundary — the full-gradient buffer is a transient
     the backward segment frees, never part of step state. Update math
     is identical to the bucketed stage-1 path (fp32 legs are bitwise
     equal — tests/test_zero.py pins it).
  3  + parameter sharding: parameters are STORED dp-sharded (flat fp32
     bucket shards, 1/n of the model per device instead of a full
     replica), all-gathered per bucket at the start-of-step first use,
     and the update writes shards directly — the all-gather back that
     stages 1/2 pay never happens, and full-parameter HBM is freed
     between steps. `shard_params`/`gather_params` convert to/from the
     pytree form.

Comm/compute overlap (`overlap` / $PTPU_ZERO_OVERLAP, docs/ZERO.md):
buckets are planned in BACKWARD order (amp.plan_buckets order="backward":
segment 0 holds the leaves whose grads the backward pass produces first),
each bucket's parameters pass through a `custom_vjp` segment marker whose
backward rule is an `optimization_barrier` — splitting the backward into
per-bucket segments XLA cannot fuse across — and the per-bucket
`psum_scatter`s are chained with optimization_barrier ordering so
collective k is issued as soon as segment k's grads exist and XLA's
latency-hiding scheduler can run it concurrently with backward segment
k+1. Every marker/barrier is semantically identity: overlap on/off is
bitwise identical (pinned), only the schedule changes.

Host-offloaded optimizer state (`offload` / $PTPU_ZERO_OFFLOAD): m/v are
pinned in host RAM between steps (fp32 state larger than HBM stops being
a capacity wall). The step splits into a backward/scatter jit and an
update jit; while the backward executes, the PR-2 transfer machinery
(async_engine.HostStateStager riding the FeedPrefetcher worker) stages
m/v host->device, and the updated shards copy back out after the update
— the H2D leg overlaps backward, the D2H copy is the step's optimizer
sync point. Bytes both ways land in zero/offload_bytes.

The legacy surface is unchanged: defaults (stage 1, overlap/offload off)
run byte-for-byte the pre-overlap paths, so the existing ZeRO-1
trajectory is bitwise identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import flags as _flags
from ..core.jax_compat import shard_map
from ..observability import metrics as _metrics

__all__ = ["ShardedAdam", "ZeroLayoutError"]


class ZeroLayoutError(RuntimeError):
    """The optimizer's planned state layout and the configuration seen at
    make_step time disagree (init_state never called, or a knob changed
    after it ran) — re-plan with init_state instead of silently latching
    a stale layout."""


# the one boolean-spelling parser for PTPU_* switches now lives in the
# central flags registry; kept under the established local name
_env_flag = _flags.env_flag


def _env_stage():
    try:
        return _flags.env("PTPU_ZERO_STAGE")
    except ValueError as exc:
        raise ValueError("PTPU_ZERO_STAGE is not an integer: %s" % (exc,))


def _pad_leading(x, n):
    pad = (-x.shape[0]) % n
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    return x


# ---------------------------------------------------------------------------
# backward segment boundary
# ---------------------------------------------------------------------------
# Identity in the forward; the backward rule pins the segment's cotangents
# behind an optimization_barrier, so XLA cannot fuse gradient production
# across bucket boundaries — the "split the backward into per-bucket
# segments" half of the overlap contract (the issue-order chain in the
# step builders is the other half). The raw jax.lax primitive is safe
# here even on pre-0.5 jax (where it lacks an AD rule): the barrier in
# the bwd rule is traced, not differentiated — training steps are not
# themselves differentiated through.


@jax.custom_vjp
def _grad_segment(leaves):
    return leaves


def _grad_segment_fwd(leaves):
    return leaves, None


def _grad_segment_bwd(_, cotangents):
    with jax.named_scope("zero_backward_segment"):
        return (jax.lax.optimization_barrier(cotangents),)


_grad_segment.defvjp(_grad_segment_fwd, _grad_segment_bwd)


def _mark_segments(flat_p, layout):
    """flat_p with each bucket's leaves routed through its own
    _grad_segment boundary (values unchanged)."""
    marked = list(flat_p)
    for b in layout:
        outs = _grad_segment(tuple(flat_p[i] for i in b.indices))
        for i, o in zip(b.indices, outs):
            marked[i] = o
    return marked


def _segmented(loss_fn, layout):
    """loss_fn with every parameter leaf routed through its bucket's
    _grad_segment boundary INSIDE the differentiated function — the
    cotangents then cross the boundary's optimization_barrier on their
    way out, which is what splits the backward into per-bucket
    segments."""

    def marked_loss(params, *batch):
        flat, tdef = jax.tree.flatten(params)
        return loss_fn(tdef.unflatten(_mark_segments(flat, layout)),
                       *batch)

    return marked_loss


def _ordered(buf, token):
    """Order `buf`'s consumer (the bucket's collective) after `token`
    (the previous bucket's collective output): the issue chain that keeps
    collectives in backward-production order so each one can overlap the
    NEXT segment's compute instead of all bursting at the end."""
    buf, token = jax.lax.optimization_barrier((buf, token))
    return buf, token


class ShardedAdam:
    """Adam with dp-sharded state (the ZeRO ladder — module docstring /
    docs/ZERO.md).

    bucket_mb: flatten gradients into same-dtype buckets of this many
    MiB for the reduce-scatter (None = read $PTPU_AMP_BUCKET_MB; 0 or an
    unset environment = the legacy one-collective-per-leaf path).
    grad_dtype: dtype the gradients are cast to BEFORE the collective
    (e.g. jnp.bfloat16 under AMP — half the bytes on the wire); None
    keeps each gradient's own dtype.
    zero_stage: 1 (optimizer-state sharding, default), 2 (+ gradient
    sharding), 3 (+ parameter sharding). None reads $PTPU_ZERO_STAGE.
    overlap: issue per-bucket collectives in backward order under
    optimization_barrier segment boundaries (None reads
    $PTPU_ZERO_OVERLAP; bitwise identical to overlap=False).
    offload: keep m/v in host RAM between steps, staged through the
    async-engine transfer machinery (None reads $PTPU_ZERO_OFFLOAD).

    Stages 2/3, overlap and offload all require bucketing. init_state
    latches the planned layout; calling make_step with a configuration
    that no longer matches the plan raises ZeroLayoutError."""

    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, axis_name="dp", grad_dtype=None,
                 bucket_mb=None, zero_stage=None, overlap=None,
                 offload=None):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.axis = axis_name
        self.grad_dtype = grad_dtype
        self.bucket_mb = bucket_mb
        self.zero_stage = zero_stage
        self.overlap = overlap
        self.offload = offload
        self._plan = None    # resolved config latched by init_state
        self._layout = None  # bucket plan latched by init_state
        self._p_treedef = None   # ZeRO-3: params pytree structure
        self._p_template = None  # ZeRO-3: per-leaf ShapeDtypeStruct

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _bucket_bytes(self):
        from .. import amp

        if self.bucket_mb is not None:
            return amp.mb_to_bucket_bytes(self.bucket_mb)
        return amp.bucket_bytes_from_env(default_mb=None)

    def _resolve_config(self):
        """The effective (validated) configuration right now — ctor
        arguments win over the environment."""
        env_stage = _env_stage()
        stage = self.zero_stage if self.zero_stage is not None \
            else (env_stage if env_stage is not None else 1)
        if stage not in (1, 2, 3):
            raise ValueError("zero_stage must be 1, 2 or 3, got %r"
                             % (stage,))
        overlap = self.overlap if self.overlap is not None \
            else bool(_env_flag("PTPU_ZERO_OVERLAP"))
        offload = self.offload if self.offload is not None \
            else bool(_env_flag("PTPU_ZERO_OFFLOAD"))
        bb = self._bucket_bytes()
        needs = [k for k, on in (("zero_stage>=2", stage >= 2),
                                 ("overlap", overlap),
                                 ("offload", offload)) if on]
        if needs and not bb:
            raise ValueError(
                "%s requires gradient bucketing: set bucket_mb (or "
                "$PTPU_AMP_BUCKET_MB) to a positive MiB size"
                % " + ".join(needs))
        return {"bucket_bytes": bb, "stage": stage,
                "overlap": bool(overlap), "offload": bool(offload),
                "grad_dtype": str(self.grad_dtype)}

    def _check_plan(self, what):
        """make_step-time guard: the layout planned by init_state must
        match the configuration in force NOW (a changed bucket_mb /
        $PTPU_AMP_BUCKET_MB / stage / overlap / offload between the two
        calls would silently pair a stale state layout with a different
        step function)."""
        cfg = self._resolve_config()
        if self._plan is None:
            if cfg["bucket_bytes"] or cfg["stage"] >= 2 or cfg["offload"]:
                raise ZeroLayoutError(
                    "%s: call init_state(params, mesh) before make_step — "
                    "this configuration (%r) needs a planned state layout"
                    % (what, cfg))
            return cfg
        if cfg != self._plan:
            raise ZeroLayoutError(
                "%s: configuration changed after init_state (planned %r, "
                "now %r) — call init_state(params, mesh) again to re-plan "
                "the state layout" % (what, self._plan, cfg))
        return cfg

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------
    def init_state(self, params, mesh):
        """m/v pytrees sharded over dp: per-leaf leading-dim shards in
        the legacy path, flat per-BUCKET shards in bucketed mode (host
        numpy buffers under offload). The resolved configuration is
        LATCHED here — make_step verifies it still holds, so a knob
        changed in between raises instead of silently pairing a stale
        layout with a different step function."""
        cfg = self._resolve_config()
        self._plan = cfg
        n = mesh.shape[self.axis]
        if not cfg["bucket_bytes"]:
            self._layout = None

            def zeros_sharded(p):
                shape = ((p.shape[0] + (-p.shape[0]) % n),) + p.shape[1:]
                z = jnp.zeros(shape, jnp.float32)
                return jax.device_put(
                    z, jax.sharding.NamedSharding(mesh, P(self.axis)))

            return {"m": jax.tree.map(zeros_sharded, params),
                    "v": jax.tree.map(zeros_sharded, params),
                    "step": jnp.zeros((), jnp.int32)}

        from .. import amp

        flat, treedef = jax.tree.flatten(params)
        gdt = self.grad_dtype if self.grad_dtype is not None \
            else jnp.float32
        self._layout = amp.plan_buckets(
            flat, cfg["bucket_bytes"], pad_multiple=n, dtype=gdt,
            order="backward" if cfg["overlap"] else "forward")
        self._p_treedef = treedef
        self._p_template = [
            jax.ShapeDtypeStruct(
                np.shape(p), getattr(p, "dtype", None)
                or np.asarray(p).dtype)
            for p in flat]
        if cfg["offload"]:
            return {"m": [np.zeros((b.padded,), np.float32)
                          for b in self._layout],
                    "v": [np.zeros((b.padded,), np.float32)
                          for b in self._layout],
                    "step": np.zeros((), np.int32)}
        sh = NamedSharding(mesh, P(self.axis))

        def zeros_flat(b):
            return jax.device_put(jnp.zeros((b.padded,), jnp.float32), sh)

        return {"m": [zeros_flat(b) for b in self._layout],
                "v": [zeros_flat(b) for b in self._layout],
                "step": jnp.zeros((), jnp.int32)}

    # ------------------------------------------------------------------
    # ZeRO-3 parameter layout
    # ------------------------------------------------------------------
    def shard_params(self, params, mesh):
        """params pytree -> list of flat fp32 dp-sharded bucket buffers
        (the ZeRO-3 stored form: each device holds 1/n of the model).
        Requires init_state (the bucket layout doubles as the parameter
        layout so gradient shards and parameter shards stay aligned)."""
        from .. import amp

        if self._layout is None:
            raise ZeroLayoutError(
                "shard_params: call init_state(params, mesh) first — the "
                "parameter shards follow the planned bucket layout")
        flat, treedef = jax.tree.flatten(params)
        if treedef != self._p_treedef:
            raise ValueError("params structure does not match the tree "
                             "init_state planned for")
        sh = NamedSharding(mesh, P(self.axis))
        return [jax.device_put(
                    amp.flatten_bucket(b, flat, dtype=jnp.float32), sh)
                for b in self._layout]

    def gather_params(self, pshards):
        """The pytree form of ZeRO-3 sharded parameters (host-side
        assembly — jax reads the global view of each sharded buffer;
        leaves come back in their original dtypes)."""
        from .. import amp

        if self._layout is None or self._p_treedef is None:
            raise ZeroLayoutError("gather_params: no planned layout — "
                                  "call init_state first")
        if len(pshards) != len(self._layout):
            raise ZeroLayoutError(
                "gather_params: %d shard buffers for a %d-bucket layout "
                "(sharded under a different bucket plan?)"
                % (len(pshards), len(self._layout)))
        flat = [None] * self._p_treedef.num_leaves
        for b, buf in zip(self._layout, pshards):
            for i, seg in amp.unflatten_bucket(b, buf,
                                               self._p_template).items():
                flat[i] = seg
        return jax.tree.unflatten(self._p_treedef, flat)

    # ------------------------------------------------------------------
    # update math (shared by every path — the ladder changes data
    # movement, never the arithmetic)
    # ------------------------------------------------------------------
    def _local_update(self, g_shard, p_shard, m, v, t):
        m = self.b1 * m + (1 - self.b1) * g_shard
        v = self.b2 * v + (1 - self.b2) * jnp.square(g_shard)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        p_new = p_shard - self.lr * mhat / (jnp.sqrt(vhat) + self.eps)
        return p_new, m, v

    # ------------------------------------------------------------------
    def make_step(self, mesh, loss_fn):
        """jit-compiled (params, state, *batch) -> (params, state, loss)
        with grads reduce-scattered and updates computed on local shards.
        Under zero_stage=3 the params position holds the sharded form
        (`shard_params` output) and stays sharded. Under offload the
        callable is a host-side wrapper around a backward/scatter jit and
        an update jit (module docstring)."""
        cfg = self._check_plan("make_step")
        if cfg["overlap"]:
            # structural overlap receipt: with B buckets, the first B-1
            # collectives each have at least one backward segment still
            # outstanding to overlap with. Only overlap-enabled steps
            # write the gauge — it reads as "the headroom of the most
            # recent overlap-enabled step", and a later non-overlap
            # optimizer in the same process does not clobber it.
            nb = len(self._layout)
            _metrics.gauge("zero/overlap_ratio").set(
                (nb - 1) / nb if nb else 0.0)
        if cfg["offload"]:
            return self._make_step_offloaded(mesh, loss_fn, cfg)
        if cfg["stage"] == 3:
            return self._make_step_zero3(mesh, loss_fn, cfg)
        if cfg["bucket_bytes"]:
            return self._make_step_bucketed(mesh, loss_fn, cfg)
        return self._make_step_per_leaf(mesh, loss_fn)

    # -- stage 1, per-leaf collectives (the legacy default path) -------
    def _make_step_per_leaf(self, mesh, loss_fn):
        axis = self.axis
        n = mesh.shape[axis]

        def step(params, state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            t = state["step"] + 1

            def upd(p, g, m, v):
                # grad_dtype applies BEFORE the collective in this path
                # too (halved wire bytes); the fp32 cast moves to the
                # local shard, after the reduce-scatter
                gdt = self.grad_dtype if self.grad_dtype is not None \
                    else jnp.float32
                gp = _pad_leading(g.astype(gdt), n)
                pp = _pad_leading(p.astype(jnp.float32), n)

                def inner(gp, pp, m, v):
                    # mean-reduce + scatter the grad to its owner rank
                    gs = jax.lax.psum_scatter(
                        gp, axis, scatter_dimension=0, tiled=True) / n
                    p_new, m, v = self._local_update(
                        gs.astype(jnp.float32), pp, m, v,
                        t.astype(jnp.float32))
                    # broadcast updated slices back (BCastParamsToDevices
                    # parity, parallel_executor.cc:434)
                    p_full = jax.lax.all_gather(p_new, axis, axis=0,
                                                tiled=True)
                    return p_full, m, v

                spec_full = P()
                spec_shard = P(axis)
                p_full, m, v = shard_map(
                    inner, mesh=mesh,
                    in_specs=(spec_full, spec_shard, spec_shard, spec_shard),
                    out_specs=(spec_full, spec_shard, spec_shard),
                    check_vma=False)(gp, pp, m, v)
                return p_full[: p.shape[0]].astype(p.dtype), m, v

            flat_p, tdef = jax.tree.flatten(params)
            flat_g = tdef.flatten_up_to(grads)
            flat_m = tdef.flatten_up_to(state["m"])
            flat_v = tdef.flatten_up_to(state["v"])
            out = [upd(p, g, m, v)
                   for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
            new_p = tdef.unflatten([o[0] for o in out])
            new_state = {"m": tdef.unflatten([o[1] for o in out]),
                         "v": tdef.unflatten([o[2] for o in out]),
                         "step": t}
            return new_p, new_state, loss

        return jax.jit(step, donate_argnums=(0, 1))

    # -- shared bucket plumbing ----------------------------------------
    def _scatter_update(self, mesh, gbuf, pbuf, m, v, t, gather_back):
        """ONE large low-precision reduce-scatter for a bucket, the fp32
        update on the local shard, and (stages 1/2) the all-gather of the
        updated slices back to the full buffer."""
        axis = self.axis
        n = mesh.shape[axis]
        spec_full, spec_shard = P(), P(axis)

        def inner(gb, pb, m, v):
            gs = jax.lax.psum_scatter(
                gb, axis, scatter_dimension=0, tiled=True) / n
            p_new, m, v = self._local_update(
                gs.astype(jnp.float32), pb, m, v, t.astype(jnp.float32))
            if gather_back:
                p_new = jax.lax.all_gather(p_new, axis, axis=0, tiled=True)
            return p_new, m, v

        return shard_map(
            inner, mesh=mesh,
            in_specs=(spec_full, spec_shard, spec_shard, spec_shard),
            out_specs=(spec_full if gather_back else spec_shard,
                       spec_shard, spec_shard),
            check_vma=False)(gbuf, pbuf, m, v)

    # -- stages 1/2, bucketed collectives ------------------------------
    def _make_step_bucketed(self, mesh, loss_fn, cfg):
        """Same update math as per-leaf, but the reduce-scatter moves a
        few large flattened buckets (in grad_dtype) instead of one
        collective per leaf; overlap=True issues them in backward order
        behind segment boundaries. Stage 2 is this path with bucketing
        mandatory: gradients never exist as step state beyond their
        dp-sharded bucket shards."""
        from .. import amp

        layout = self._layout
        overlap = cfg["overlap"]

        fn = _segmented(loss_fn, layout) if overlap else loss_fn

        def step(params, state, *batch):
            flat_p, tdef = jax.tree.flatten(params)
            loss, grads = jax.value_and_grad(fn)(params, *batch)
            t = state["step"] + 1
            flat_g = tdef.flatten_up_to(grads)
            new_flat = list(flat_p)
            new_m, new_v = [], []
            token = loss
            for k, b in enumerate(layout):
                gbuf = amp.flatten_bucket(b, flat_g)
                if overlap:
                    gbuf, token = _ordered(gbuf, token)
                # params flatten in fp32 REGARDLESS of the collective
                # dtype — rounding the master copy through bf16 would
                # destroy the mixed-precision contract
                pbuf = amp.flatten_bucket(b, flat_p, dtype=jnp.float32)
                p_full, mb, vb = self._scatter_update(
                    mesh, gbuf, pbuf, state["m"][k], state["v"][k], t,
                    gather_back=True)
                if overlap:
                    token = mb
                for i, seg in amp.unflatten_bucket(b, p_full,
                                                   flat_p).items():
                    new_flat[i] = seg
                new_m.append(mb)
                new_v.append(vb)
            return (tdef.unflatten(new_flat),
                    {"m": new_m, "v": new_v, "step": t}, loss)

        return jax.jit(step, donate_argnums=(0, 1))

    # -- stage 3, parameter sharding -----------------------------------
    def _gathered_leaves(self, mesh, pshards):
        """Full-precision full-parameter leaves all-gathered per bucket
        from the sharded stored form — traced inside the step, so each
        bucket's gather is consumed exactly where its leaves are first
        used and XLA can overlap it with earlier compute."""
        from .. import amp

        if len(pshards) != len(self._layout):
            raise ZeroLayoutError(
                "%d parameter shard buffers for a %d-bucket layout — "
                "pass shard_params output from THIS optimizer's plan"
                % (len(pshards), len(self._layout)))
        axis = self.axis
        spec_shard = P(axis)

        def gather(buf):
            return shard_map(
                lambda s: jax.lax.all_gather(s, axis, axis=0, tiled=True),
                mesh=mesh, in_specs=(spec_shard,), out_specs=P(),
                check_vma=False)(buf)

        flat = [None] * self._p_treedef.num_leaves
        for b, buf in zip(self._layout, pshards):
            with jax.named_scope("zero3_param_gather"):
                full = gather(buf)
            for i, seg in amp.unflatten_bucket(b, full,
                                               self._p_template).items():
                flat[i] = seg
        return flat

    def _make_step_zero3(self, mesh, loss_fn, cfg):
        """(pshards, state, *batch) -> (pshards, state, loss): parameters
        live dp-sharded (shard_params), are gathered per bucket for the
        forward, and the update writes the fp32 shards in place — no
        gather-back, no replicated parameter storage."""
        from .. import amp

        layout = self._layout
        overlap = cfg["overlap"]
        tdef = self._p_treedef
        _metrics.gauge("zero/gather_bytes").set(sum(
            b.padded * 4 for b in layout))

        fn = _segmented(loss_fn, layout) if overlap else loss_fn

        def step(pshards, state, *batch):
            flat_full = self._gathered_leaves(mesh, pshards)
            params_in = jax.tree.unflatten(tdef, flat_full)
            loss, grads = jax.value_and_grad(fn)(params_in, *batch)
            t = state["step"] + 1
            flat_g = tdef.flatten_up_to(grads)
            new_shards, new_m, new_v = [], [], []
            token = loss
            for k, b in enumerate(layout):
                gbuf = amp.flatten_bucket(b, flat_g)
                if overlap:
                    gbuf, token = _ordered(gbuf, token)
                ps, mb, vb = self._scatter_update(
                    mesh, gbuf, pshards[k], state["m"][k], state["v"][k],
                    t, gather_back=False)
                if overlap:
                    token = mb
                new_shards.append(ps)
                new_m.append(mb)
                new_v.append(vb)
            return (new_shards,
                    {"m": new_m, "v": new_v, "step": t}, loss)

        return jax.jit(step, donate_argnums=(0, 1))

    # -- host-offloaded optimizer state --------------------------------
    def _make_step_offloaded(self, mesh, loss_fn, cfg):
        """Two-phase step with m/v living in host RAM between steps:

          phase 1 (backward jit): forward + segmented backward + the
                  per-bucket reduce-scatters -> dp-sharded grad shards.
                  Dispatched first; WHILE it executes, the HostStateStager
                  worker places m/v host->device with their shard
                  sharding.
          phase 2 (update jit): the same _local_update on (grad shard,
                  param fp32, m, v) per bucket; new m/v copy back to host
                  (the D2H sync), parameters return like the on-device
                  paths (full for stages 1/2, shards for stage 3).

        Splitting at the reduce-scatter boundary keeps the arithmetic
        identical to the fused step — offload on/off is bitwise equal on
        fp32 legs (pinned)."""
        from .. import amp
        from ..async_engine import HostStateStager

        layout = self._layout
        overlap = cfg["overlap"]
        stage3 = cfg["stage"] == 3
        tdef = self._p_treedef
        sh = NamedSharding(mesh, P(self.axis))
        # each returned step OWNS its stager (a re-made step must not
        # break callables handed out earlier); the worker thread is
        # daemonic and lazily started, and `step.close()` releases it
        # eagerly for callers that cycle many steps in one process
        stager = HostStateStager(place_fn=lambda v: jax.device_put(v, sh))
        if stage3:
            _metrics.gauge("zero/gather_bytes").set(sum(
                b.padded * 4 for b in layout))

        fn = _segmented(loss_fn, layout) if overlap else loss_fn

        def backward(pstate, *batch):
            if stage3:
                flat_full = self._gathered_leaves(mesh, pstate)
            else:
                flat_full, _ = jax.tree.flatten(pstate)
            params_in = jax.tree.unflatten(tdef, flat_full)
            loss, grads = jax.value_and_grad(fn)(params_in, *batch)
            flat_g = tdef.flatten_up_to(grads)
            axis, n = self.axis, mesh.shape[self.axis]

            def scatter(gb):
                return shard_map(
                    lambda g: jax.lax.psum_scatter(
                        g, axis, scatter_dimension=0, tiled=True) / n,
                    mesh=mesh, in_specs=(P(),), out_specs=P(axis),
                    check_vma=False)(gb)

            gshards = []
            token = loss
            for b in layout:
                gbuf = amp.flatten_bucket(b, flat_g)
                if overlap:
                    gbuf, token = _ordered(gbuf, token)
                gs = scatter(gbuf)
                if overlap:
                    token = gs
                gshards.append(gs)
            return loss, gshards

        def update(pstate, gshards, ms, vs, step_count):
            t = step_count + 1
            spec_shard = P(self.axis)
            flat_p = None if stage3 else jax.tree.flatten(pstate)[0]
            new_p, new_m, new_v = [], [], []
            for k, b in enumerate(layout):
                pbuf = pstate[k] if stage3 else amp.flatten_bucket(
                    b, flat_p, dtype=jnp.float32)

                def inner(gs, pb, m, v):
                    p_new, m, v = self._local_update(
                        gs.astype(jnp.float32), pb, m, v,
                        t.astype(jnp.float32))
                    if not stage3:
                        p_new = jax.lax.all_gather(p_new, self.axis,
                                                   axis=0, tiled=True)
                    return p_new, m, v

                pn, mb, vb = shard_map(
                    inner, mesh=mesh,
                    in_specs=(spec_shard, spec_shard, spec_shard,
                              spec_shard),
                    out_specs=(spec_shard if stage3 else P(),
                               spec_shard, spec_shard),
                    check_vma=False)(gshards[k], pbuf, ms[k], vs[k])
                new_p.append(pn)
                new_m.append(mb)
                new_v.append(vb)
            if stage3:
                out_p = new_p
            else:
                flat_new = list(flat_p)
                for b, full in zip(layout, new_p):
                    for i, seg in amp.unflatten_bucket(b, full,
                                                       flat_p).items():
                        flat_new[i] = seg
                out_p = jax.tree.unflatten(tdef, flat_new)
            return out_p, new_m, new_v, t

        backward_jit = jax.jit(backward)
        update_jit = jax.jit(update, donate_argnums=(0, 1, 2, 3))

        def step(pstate, state, *batch):
            # H2D of m/v overlaps the backward's async execution. A
            # failing backward (trace error, transient XLA fault the
            # PR-4 trainer retries) must not wedge the stager: abort
            # drops the staged batch so the retry starts clean.
            stager.stage_in_begin(list(state["m"]) + list(state["v"]))
            try:
                loss, gshards = backward_jit(pstate, *batch)
                staged = stager.stage_in_end()
            except BaseException:
                stager.abort()
                raise
            ms, vs = staged[:len(layout)], staged[len(layout):]
            new_p, new_m, new_v, t = update_jit(
                pstate, gshards, ms, vs, jnp.asarray(state["step"]))
            host_m = stager.stage_out(new_m)
            host_v = stager.stage_out(new_v)
            return new_p, {"m": host_m, "v": host_v,
                           "step": np.asarray(t)}, loss

        step.close = stager.close
        return step
