"""Sharded-optimizer data parallelism (parity: the reference's Reduce mode —
`ReduceSSAGraphBuilder` multi_devices_graph_pass.h:164 /
details/reduce_op_handle.cc, SURVEY §2.3 P2: "each param's grad reduced to
one owner device, updated there, then broadcast — ZeRO-1-like ancestor").

TPU-native: inside shard_map over the dp axis each gradient leaf is
reduce-scattered along its leading dimension, the optimizer update runs on
the rank-local 1/n slice of (param, m, v), and updated slices all-gather
back — optimizer state is born sharded, never materialized whole, exactly
the memory the pserver param-blocking bought the reference.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from ..core.jax_compat import shard_map


def _pad_leading(x, n):
    pad = (-x.shape[0]) % n
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    return x


class ShardedAdam:
    """Adam with dp-sharded moments (ZeRO-1 / Reduce-mode parity)."""

    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, axis_name="dp"):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.axis = axis_name

    def init_state(self, params, mesh):
        """m/v pytrees sharded over dp on the leading dim (padded)."""
        n = mesh.shape[self.axis]

        def zeros_sharded(p):
            shape = ((p.shape[0] + (-p.shape[0]) % n),) + p.shape[1:]
            z = jnp.zeros(shape, jnp.float32)
            return jax.device_put(
                z, jax.sharding.NamedSharding(mesh, P(self.axis)))

        return {"m": jax.tree.map(zeros_sharded, params),
                "v": jax.tree.map(zeros_sharded, params),
                "step": jnp.zeros((), jnp.int32)}

    def make_step(self, mesh, loss_fn):
        """jit-compiled (params, state, *batch) -> (params, state, loss)
        with grads reduce-scattered and updates computed on local shards."""
        axis = self.axis
        n = mesh.shape[axis]

        def local_update(g_shard, p_shard, m, v, t):
            m = self.b1 * m + (1 - self.b1) * g_shard
            v = self.b2 * v + (1 - self.b2) * jnp.square(g_shard)
            mhat = m / (1 - self.b1 ** t)
            vhat = v / (1 - self.b2 ** t)
            p_new = p_shard - self.lr * mhat / (jnp.sqrt(vhat) + self.eps)
            return p_new, m, v

        def step(params, state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            t = state["step"] + 1

            def upd(p, g, m, v):
                gp = _pad_leading(g.astype(jnp.float32), n)
                pp = _pad_leading(p.astype(jnp.float32), n)

                def inner(gp, pp, m, v):
                    # mean-reduce + scatter the grad to its owner rank
                    gs = jax.lax.psum_scatter(
                        gp, axis, scatter_dimension=0, tiled=True) / n
                    p_new, m, v = local_update(gs, pp, m, v,
                                               t.astype(jnp.float32))
                    # broadcast updated slices back (BCastParamsToDevices
                    # parity, parallel_executor.cc:434)
                    p_full = jax.lax.all_gather(p_new, axis, axis=0,
                                                tiled=True)
                    return p_full, m, v

                spec_full = P()
                spec_shard = P(axis)
                p_full, m, v = shard_map(
                    inner, mesh=mesh,
                    in_specs=(spec_full, spec_shard, spec_shard, spec_shard),
                    out_specs=(spec_full, spec_shard, spec_shard),
                    check_vma=False)(gp, pp, m, v)
                return p_full[: p.shape[0]].astype(p.dtype), m, v

            flat_p, tdef = jax.tree.flatten(params)
            flat_g = tdef.flatten_up_to(grads)
            flat_m = tdef.flatten_up_to(state["m"])
            flat_v = tdef.flatten_up_to(state["v"])
            out = [upd(p, g, m, v)
                   for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
            new_p = tdef.unflatten([o[0] for o in out])
            new_state = {"m": tdef.unflatten([o[1] for o in out]),
                         "v": tdef.unflatten([o[2] for o in out]),
                         "step": t}
            return new_p, new_state, loss

        return jax.jit(step, donate_argnums=(0, 1))
